"""Dynamic micro-batcher: coalesce concurrent requests into bucket-shaped
device batches.

The core serving problem with a jitted XLA model is that every novel batch
shape is a fresh multi-second compile, while real traffic arrives one
request at a time.  The batcher sits between the HTTP threads and the
engine thread and turns arrival-order requests into batches that are

* **coalesced**: up to ``max_batch`` requests, or whatever arrived within
  ``deadline_ms`` of the first dequeued request — whichever happens first;
* **bounded**: a queue deeper than ``max_queue`` load-sheds new submits
  with :class:`QueueFull` (HTTP 429 + Retry-After) instead of growing an
  unbounded backlog whose tail can never meet its deadline;
* **deadline-aware**: requests that exceeded their per-request timeout
  while queued are failed (HTTP 504) at dequeue time, never shipped to the
  device.

Bucket padding itself lives in the engine (`serving/engine.py`); the
batcher only promises ``1 <= len(batch) <= max_batch``.
"""

from __future__ import annotations

import itertools
import logging
import queue
import random
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..cache import SingleFlight
from .resilience import jittered_retry_after

_logger = logging.getLogger(__name__)

__all__ = ["Request", "QueueFull", "DeadlineExceeded", "MicroBatcher",
           "pick_bucket"]


class QueueFull(Exception):
    """Raised by submit() when the queue is at max depth (load shedding)."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"queue full (depth {depth})")
        self.depth = depth
        self.retry_after_s = retry_after_s


class DeadlineExceeded(Exception):
    """The request spent longer than its deadline waiting for the device."""


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest pre-compiled bucket that fits ``n`` rows."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket "
                     f"{max(buckets)}")


class Request:
    """One scoring request: a preprocessed uint8 canvas plus a one-shot
    completion slot the HTTP thread blocks on.

    A stripped-down future (stdlib ``concurrent.futures.Future`` drags in
    condition-variable state we don't need): exactly one producer — the
    engine — resolves it exactly once.
    """

    _ids = itertools.count()
    # one lock for all requests: claim() is a few-ns critical section and
    # a per-instance lock would cost an allocation per HTTP request
    _claim_guard = threading.Lock()

    __slots__ = ("id", "array", "model_id", "enqueue_t", "deadline_t",
                 "timings", "on_resolve", "from_cache", "_event",
                 "_result", "_error", "_claimed")

    def __init__(self, array: Any, timeout_s: Optional[float] = None,
                 model_id: str = "default"):
        self.id = next(self._ids)
        self.array = array
        #: True when the verdict cache resolved this request (exact/near
        #: probe or coalesced rider) — callers that keep their own books
        #: (the streaming dispatcher) split cache_hit from scored on it
        self.from_cache = False
        self.model_id = model_id    # engine model-table key (per-model
        # books + compiled-program routing; "default" = primary model)
        self.enqueue_t = time.monotonic()
        self.deadline_t = (self.enqueue_t + timeout_s
                           if timeout_s and timeout_s > 0 else None)
        self.timings: dict = {}
        #: resolution fan-out hook (verdict-cache coalescing): fires once,
        #: AFTER the waiter is released, on every resolution path — score,
        #: failure, queue deadline, close() drain, watchdog recovery —
        #: because they all funnel through set_result/set_exception
        self.on_resolve: Optional[Any] = None
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._claimed = False

    def claim(self) -> bool:
        """One-shot resolution ticket: True for exactly one caller, ever.

        The request-books ledger (accepted == scored + shed + deadline +
        failed) needs every request counted EXACTLY once even when the
        engine worker and the watchdog race to resolve it — whoever wins
        the claim does both the counting and the set_result/exception."""
        with self._claim_guard:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline_t is not None and \
            (time.monotonic() if now is None else now) > self.deadline_t

    def _fire_on_resolve(self) -> None:
        cb, self.on_resolve = self.on_resolve, None
        if cb is not None:
            try:
                cb(self)
            except Exception:                       # noqa: BLE001
                # the engine worker must never die to a cache hiccup
                _logger.exception("on_resolve callback failed "
                                  "(request %d)", self.id)

    def set_result(self, value: Any) -> None:
        self._result = value
        self._event.set()
        self._fire_on_resolve()

    def set_exception(self, err: BaseException) -> None:
        self._error = err
        self._event.set()
        self._fire_on_resolve()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until resolved; raises the producer's exception, or
        :class:`DeadlineExceeded` if ``timeout`` elapses first."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded(f"request {self.id}: no result within "
                                   f"{timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """Bounded request queue with deadline-or-full coalescing.

    ``submit()`` is called from many HTTP threads; ``next_batch()`` from
    the single engine thread.  ``queue.Queue`` provides the blocking
    semantics; depth accounting is explicit so load-shedding reads a
    consistent value.
    """

    def __init__(self, max_batch: int = 64, deadline_ms: float = 5.0,
                 max_queue: int = 128, metrics: Optional[Any] = None,
                 retry_jitter_s: float = 2.0, cache: Optional[Any] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.deadline_s = float(deadline_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self.retry_jitter_s = float(retry_jitter_s)
        #: label unrouted submits carry in the per-model books; the
        #: engine overwrites it with its primary model id at start()
        self.default_model_id = "default"
        #: verdict cache (cache/store.py VerdictCache) — None disables the
        #: dedup tier entirely; submits without a content_key bypass it
        self.cache = cache
        #: ``model_id -> fingerprint`` resolver, set by ``engine.start()``
        #: (the cache key must carry the weight identity; until an engine
        #: attaches, there is no identity and the cache stays cold)
        self.fingerprint_of: Optional[Any] = None
        self._flight = SingleFlight()
        self._retry_rng = random.Random(0x5EED)
        self._q: "queue.Queue[Request]" = queue.Queue()
        self._depth = 0
        self._depth_lock = threading.Lock()
        self._closed = threading.Event()

    # ------------------------------------------------------------------
    @property
    def depth(self) -> int:
        return self._depth

    def _track_depth(self, delta: int) -> int:
        with self._depth_lock:
            self._depth += delta
            d = self._depth
        if self.metrics is not None:
            self.metrics.queue_depth = d
        return d

    # ------------------------------------------------------------------
    def submit(self, array: Any,
               timeout_s: Optional[float] = None,
               model_id: Optional[str] = None,
               content_key: Optional[Tuple[str, Any]] = None) -> Request:
        """Enqueue one preprocessed request; raises :class:`QueueFull` past
        ``max_queue`` depth.  ``model_id`` routes it to one entry of the
        engine's model table (None = the primary model).

        ``content_key`` is the dedup identity ``(content_hash, phash)``
        (phash None unless near-dup is enabled).  With a cache attached
        and a weight fingerprint available, a hit resolves the request
        right here — it never enters a bucket, and by the same token
        never sheds; a miss elects a single-flight leader so N concurrent
        copies of one clip dispatch ONE inference."""
        if self._closed.is_set():
            raise RuntimeError("batcher is closed")
        model_id = model_id or self.default_model_id
        if self.metrics is not None:
            # the books ledger: every submit attempt is accepted, then
            # resolves exactly once as cache_hit/scored/shed/deadline/
            # failed (the model= labeled books mirror each increment)
            self.metrics.accepted_total.inc()
            self.metrics.count_model("accepted", model_id)
        fp = None
        if self.cache is not None and content_key is not None \
                and self.fingerprint_of is not None:
            try:
                fp = self.fingerprint_of(model_id)
            except Exception:                       # noqa: BLE001
                fp = None       # no weight identity -> no safe cache key
        if fp is None:
            req = Request(array, timeout_s, model_id=model_id)
            self._enqueue(req)
            return req
        hit = self._probe(array, timeout_s, model_id, content_key, fp)
        if hit is not None:
            return hit
        chash, phash = content_key
        key = (chash, model_id, fp)
        req = Request(array, timeout_s, model_id=model_id)
        if not self._flight.lead_or_follow(key, req):
            # follower: never enqueued, never shed — the leader's fan-out
            # resolves and books it (cache_hit on success, the mirrored
            # deadline/failed term otherwise)
            return req
        req.on_resolve = self._make_resolver(key, chash, phash, model_id,
                                             fp)
        try:
            self._enqueue(req)
        except QueueFull as qf:
            # the leader shed before entering the queue: every follower
            # that attached in the window sheds with it (each carries an
            # accepted count that must resolve)
            for f in self._flight.pop(key):
                if f.claim():
                    if self.metrics is not None:
                        self.metrics.shed_total.inc()
                        self.metrics.count_model("shed", f.model_id)
                    f.set_exception(QueueFull(qf.depth, qf.retry_after_s))
            raise
        return req

    def _enqueue(self, req: Request) -> None:
        """Depth-checked queue insert (the pre-cache submit() body)."""
        with self._depth_lock:
            if self._depth >= self.max_queue:
                depth = self._depth
                full = True
            else:
                self._depth += 1
                depth = self._depth
                full = False
        if self.metrics is not None:
            self.metrics.queue_depth = depth
        if full:
            if self.metrics is not None:
                self.metrics.shed_total.inc()
                self.metrics.count_model("shed", req.model_id)
            # Retry-After estimate: drain time of the current backlog at
            # one deadline-window per max_batch, floored at 1s (the
            # HTTP-date alternative needs no clock sync this way), plus a
            # bounded uniform jitter — a constant here synchronizes every
            # shed client into one resend wave that sheds again
            retry = jittered_retry_after(
                max(1.0, depth / self.max_batch * self.deadline_s),
                self.retry_jitter_s, self._retry_rng)
            raise QueueFull(depth, retry)
        self._q.put(req)
        if self._closed.is_set():
            # close() raced us: its drain may have run before our put
            # landed, which would strand an accepted-counted request and
            # break the books identity — whoever wins the claim resolves
            # it (the drain, or us, exactly once)
            if req.claim():
                if self.metrics is not None:
                    self.metrics.failed_total.inc()
                    self.metrics.count_model("failed", req.model_id)
                req.set_exception(RuntimeError("batcher is closed"))

    # ----------------------------------------------------- verdict cache
    def _probe(self, array: Any, timeout_s: Optional[float],
               model_id: str, content_key: Tuple[str, Any],
               fp: str) -> Optional[Request]:
        """Exact-then-near cache probe; a hit returns a request resolved
        on the spot (claimed + booked as cache_hit, per model)."""
        chash, phash = content_key
        value = self.cache.get(chash, model_id, fp)
        near = False
        if value is None and phash is not None:
            got = self.cache.get_near(phash, model_id, fp)
            if got is not None:
                value, _dist = got
                near = True
        if value is None:
            if self.metrics is not None:
                self.metrics.cache_miss_total.inc()
            return None
        req = Request(array, timeout_s, model_id=model_id)
        req.claim()
        if self.metrics is not None:
            self.metrics.cache_hit_total.inc()
            self.metrics.count_model("cache_hit", model_id)
            if near:
                # separate counter by decree: a near hit is a different
                # clip's verdict and must never pass as an exact hit
                self.metrics.cache_near_hit_total.inc()
        req.timings["queue"] = 0.0
        req.timings["device"] = 0.0
        req.from_cache = True
        req.set_result(np.array(value, copy=True))
        return req

    def _make_resolver(self, key: Any, chash: str, phash: Any,
                       model_id: str, fp: str) -> Any:
        def _resolved(leader: Request) -> None:
            # runs on whatever thread resolved the leader (engine worker,
            # queue-deadline drop, close() drain) — pop first so late
            # arrivals elect a fresh leader instead of attaching to a
            # resolved one
            followers = self._flight.pop(key)
            err = leader._error
            row = None
            if err is None:
                # copy out of the batch array: the stored verdict must
                # outlive (and never alias) the engine's scratch
                row = np.array(leader._result, copy=True)
                self.cache.put(chash, model_id, fp, row, phash=phash)
                if self.metrics is not None:
                    self.metrics.cache_insert_total.inc()
                    self.metrics.cache_entries = self.cache.size()
            now = time.monotonic()
            for f in followers:
                if not f.claim():
                    continue
                f.timings["queue"] = now - f.enqueue_t
                if err is None:
                    if self.metrics is not None:
                        self.metrics.cache_hit_total.inc()
                        self.metrics.count_model("cache_hit", f.model_id)
                        self.metrics.cache_coalesced_total.inc()
                    f.timings["device"] = 0.0
                    f.from_cache = True
                    f.set_result(np.array(row, copy=True))
                else:
                    # mirror the leader's outcome so the books identity
                    # holds for every coalesced rider
                    if self.metrics is not None:
                        if isinstance(err, DeadlineExceeded):
                            self.metrics.deadline_total.inc()
                            self.metrics.count_model("deadline",
                                                     f.model_id)
                        else:
                            self.metrics.failed_total.inc()
                            self.metrics.count_model("failed", f.model_id)
                    f.set_exception(err)
        return _resolved

    # ------------------------------------------------------------------
    def take(self, timeout: Optional[float]) -> Optional[Request]:
        """One queue pop; drops (fails) requests that expired while queued
        and keeps popping within the same grant.  The engine uses this
        directly to gather the next batch while the device is busy."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            try:
                req = self._q.get(block=remaining is None or remaining > 0,
                                  timeout=remaining)
            except queue.Empty:
                return None
            self._track_depth(-1)
            if req.expired():
                req.timings["queue"] = time.monotonic() - req.enqueue_t
                if req.claim():
                    if self.metrics is not None:
                        self.metrics.deadline_total.inc()
                        self.metrics.count_model("deadline", req.model_id)
                    req.set_exception(DeadlineExceeded(
                        f"request {req.id} expired after "
                        f"{req.timings['queue'] * 1000:.0f} ms in queue"))
                continue
            return req

    def next_batch(self, timeout: Optional[float] = 0.1) -> List[Request]:
        """Dequeue the next batch.

        Blocks up to ``timeout`` for the FIRST request (empty list on
        timeout), then coalesces followers for up to ``deadline_ms`` —
        measured from that first dequeue — returning early once
        ``max_batch`` is reached.  (While a previous batch is still
        executing, the engine instead gathers via :meth:`take` directly,
        paced by the device rather than the clock — engine.py.)
        """
        first = self.take(timeout)
        if first is None:
            return []
        batch = [first]
        flush_at = time.monotonic() + self.deadline_s
        while len(batch) < self.max_batch:
            wait = flush_at - time.monotonic()
            nxt = self.take(max(0.0, wait))
            if nxt is None:       # flush window elapsed / queue drained
                break
            batch.append(nxt)
        now = time.monotonic()
        for r in batch:
            r.timings["queue"] = now - r.enqueue_t
        return batch

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Fail everything still queued (server shutdown)."""
        self._closed.set()
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            self._track_depth(-1)
            if req.claim():
                if self.metrics is not None:
                    self.metrics.failed_total.inc()
                    self.metrics.count_model("failed", req.model_id)
                req.set_exception(RuntimeError("server shutting down"))
