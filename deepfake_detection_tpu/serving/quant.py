"""Post-training quantization for the inference path (ISSUE 14).

Weight-only PTQ applied at engine warmup from an f32 checkpoint — the
checkpoint on disk, the reload watcher's input, and the canary's shape
gate all stay f32; only the *device-resident serving copy* is compressed:

* ``bf16`` — every float32 leaf under the ``params`` collection is cast
  to bfloat16 (half the HBM per weight).  The compiled program upcasts
  via jax's normal type promotion at each use, so compute stays float32
  against bf16-rounded weights: classic weight-only bf16.
* ``int8`` — conv/dense kernels (the ``params``-collection ``kernel``
  leaves with ndim >= 2) are quantized to int8 with **per-output-channel
  symmetric scales** (scale over all axes but the last, the flax HWIO /
  (I, O) output axis).  Each quantized leaf is replaced in-tree by a
  two-leaf container ``{__q8__, __q8_scale__}``; :func:`realize_tree`
  dequantizes it *inside* the jitted call (``q.astype(f32) * scale``) so
  the dequant fuses into the program right next to the uint8-wire
  normalize epilogue — the weights cross host->device and live in HBM as
  int8, and XLA materializes f32 tiles on the fly.  Everything that is
  not a kernel (biases, BN scale/bias, batch_stats) stays f32: those
  leaves are tiny and the BN statistics are numerically load-bearing.

The quantized tree is an ordinary pytree (nested dicts + arrays), so the
engine's whole params-as-arguments machinery — ``jax.device_put``, AOT
``lower().compile()`` avals, the hot-reload A/B swap — works unchanged;
``quantize_tree`` is deterministic, so a reloaded f32 checkpoint
re-quantizes to aval-identical arguments for the existing executables.

``realize_tree`` on a plain (un-quantized) tree returns it untouched —
zero inserted ops — which is what keeps the f32 path bit-identical to
the pre-quant programs (the CLI-parity contract of tests/test_serving).

Accuracy is *measured*, never assumed: ``tools/quant_parity.py`` scores
a seeded eval list under f32/bf16/int8 and hard-fails past the
pre-registered score-drift/AUC bounds recorded in SERVE_BENCH.md.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QUANT_MODES", "canonical_mode", "quantize_tree",
           "realize_tree", "is_quantized_leaf", "quant_summary",
           "quantize_leaf"]

#: canonical serving dtypes (aliases accepted by :func:`canonical_mode`)
QUANT_MODES = ("f32", "bf16", "int8")

_ALIASES = {"f32": "f32", "float32": "f32",
            "bf16": "bf16", "bfloat16": "bf16",
            "int8": "int8"}

#: container keys of one quantized leaf — dunder-prefixed so no flax
#: module name can collide with them
_QKEY = "__q8__"
_SKEY = "__q8_scale__"


def canonical_mode(mode: str) -> str:
    """``float32``/``bfloat16`` aliases → the canonical short names."""
    try:
        return _ALIASES[str(mode).lower()]
    except KeyError:
        raise ValueError(
            f"unknown quantization dtype {mode!r}; pick one of "
            f"{QUANT_MODES} (aliases: float32, bfloat16)") from None


def quantize_leaf(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """One kernel → (int8 values, per-output-channel f32 scales).

    Symmetric: ``scale = amax(|w|) / 127`` over every axis but the last,
    ``q = round(w / scale)`` clipped to [-127, 127].  An all-zero output
    channel gets scale 1.0 (its rows quantize to exact zeros either
    way), so dequant never divides by zero; a NON-FINITE channel gets
    scale NaN so the poison survives dequant for the canary to see."""
    w = np.asarray(w, np.float32)
    axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=axes)
    scale = np.where(amax > 0, amax / 127.0, 1.0)
    # a non-finite channel gets a NaN scale so dequant REPRODUCES the
    # poison: int8 must fail the canary's finite-scores gate exactly
    # like the f32/bf16 paths do — casting NaN through int8 would
    # launder it into finite garbage the canary cannot see
    scale = np.where(np.isfinite(amax), scale, np.nan).astype(np.float32)
    with np.errstate(invalid="ignore"):
        q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def is_quantized_leaf(x: Any) -> bool:
    """True for the two-leaf int8 container ``realize_tree`` dequantizes."""
    return isinstance(x, dict) and _QKEY in x and _SKEY in x


def _path_keys(path) -> list:
    return [getattr(p, "key", getattr(p, "idx", None)) for p in path]


def _int8_eligible(path, leaf) -> bool:
    keys = _path_keys(path)
    return ("params" in keys and keys[-1] == "kernel"
            and np.ndim(leaf) >= 2
            and np.asarray(leaf).dtype == np.float32)


def _bf16_eligible(path, leaf) -> bool:
    return ("params" in _path_keys(path)
            and np.asarray(leaf).dtype == np.float32)


def quantize_tree(variables: Any, mode: str) -> Any:
    """Host-side PTQ transform of an f32 variables tree.

    ``f32`` returns the tree untouched (same object — the identity
    contract the bit-parity tests pin).  ``bf16``/``int8`` return a new
    tree as described in the module docstring; feed it to
    :func:`realize_tree` inside the compiled call."""
    mode = canonical_mode(mode)
    if mode == "f32":
        return variables
    if mode == "bf16":
        def cast(path, leaf):
            if _bf16_eligible(path, leaf):
                return np.asarray(jnp.asarray(leaf).astype(jnp.bfloat16))
            return leaf
        return jax.tree_util.tree_map_with_path(cast, variables)

    def q(path, leaf):
        if _int8_eligible(path, leaf):
            q8, scale = quantize_leaf(np.asarray(leaf))
            return {_QKEY: q8, _SKEY: scale}
        return leaf
    return jax.tree_util.tree_map_with_path(
        q, variables, is_leaf=is_quantized_leaf)


def realize_tree(variables: Any) -> Any:
    """Trace-compatible dequantization: int8 containers become
    ``q.astype(f32) * scale`` (the per-output-channel broadcast over the
    last axis); every other leaf — incl. bf16 casts, which jax's type
    promotion upcasts at the op that consumes them — passes through.

    A tree with no quantized leaves is returned *as-is* (not rebuilt),
    so un-quantized programs trace identically to pre-quant ones."""
    leaves = jax.tree.leaves(variables, is_leaf=is_quantized_leaf)
    if not any(is_quantized_leaf(l) for l in leaves):
        return variables

    def deq(x):
        if is_quantized_leaf(x):
            return x[_QKEY].astype(jnp.float32) * x[_SKEY]
        return x
    return jax.tree.map(deq, variables, is_leaf=is_quantized_leaf)


def quant_summary(variables: Any) -> Dict[str, int]:
    """{quantized_leaves, quantized_bytes, bf16_leaves, total_leaves} —
    what the engine logs at warmup so an operator can see the transform
    actually happened."""
    n_q = n_bf16 = n_total = q_bytes = 0
    # attribute reads only (dtype/size exist on numpy AND jax arrays):
    # np.asarray on a device-resident leaf would download the weights
    # just for a log line
    for leaf in jax.tree.leaves(variables, is_leaf=is_quantized_leaf):
        n_total += 1
        if is_quantized_leaf(leaf):
            n_q += 1
            q_bytes += int(leaf[_QKEY].size)
        elif getattr(leaf, "dtype", None) == jnp.bfloat16:
            n_bf16 += 1
    return {"quantized_leaves": n_q, "quantized_bytes": q_bytes,
            "bf16_leaves": n_bf16, "total_leaves": n_total}
