"""Persistent AOT compiled-executable store (the warm-start tier).

Wraps jax's ``serialize_executable`` pair behind a content-addressed
on-disk store so a replica spawn pays XLA compilation **once per
(checkpoint geometry, runtime)** instead of once per process.  Layout
under ``root/``::

    <key>.exe    pickle((payload_bytes, in_tree, out_tree))
    <key>.json   manifest: key fields echoed + golden scores + params
                 fingerprint at serialize time (see serving.warmkey)

Both are written write→fsync→atomic-rename, so a crashed writer leaves
either a complete entry or none.  Loading is paranoid by construction:

* key-field echo mismatch (foreign/corrupt manifest) → ``WarmstartMiss``
* unpickle / ``deserialize_and_load`` failure → ``WarmstartMiss``
* every deserialized executable is then gated by the engine's
  golden-batch canary before it serves (bit-exact against the manifest
  scores when the params fingerprint matches)

A miss is *never* an error — callers count it and fall back to a fresh
``lower().compile()``, then ``save`` re-serializes so the next spawn
hits.  The store itself keeps no metrics; serving and backfill each
count hits/misses/fallbacks in their own registries.
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Any, Dict, Tuple

from . import warmkey

log = logging.getLogger(__name__)


class WarmstartMiss(Exception):
    """Entry absent/foreign/undeserializable — count it, compile fresh."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


class ExecutableStore:
    """Content-addressed store of serialized XLA executables."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def exe_path(self, key: str) -> str:
        return os.path.join(self.root, key + ".exe")

    def manifest_path(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def __contains__(self, key: str) -> bool:
        return (os.path.exists(self.exe_path(key))
                and os.path.exists(self.manifest_path(key)))

    # -- load ----------------------------------------------------------
    def load(self, fields: Dict[str, Any]) -> Tuple[Any, Dict[str, Any]]:
        """Deserialize the executable for ``fields``.

        Returns ``(compiled, manifest)`` or raises :class:`WarmstartMiss`
        with a loud reason.  The caller MUST still run the golden-batch
        canary before letting the executable serve.
        """
        key = warmkey.store_key(fields)
        mpath, epath = self.manifest_path(key), self.exe_path(key)
        if not (os.path.exists(mpath) and os.path.exists(epath)):
            raise WarmstartMiss("absent", key[:12])
        try:
            manifest = warmkey.read_manifest(mpath)
        except (OSError, ValueError) as e:
            raise WarmstartMiss("manifest-unreadable", f"{key[:12]}: {e}")
        # Defense in depth against foreign files parked under our name:
        # the manifest must echo the exact key fields we derived the hash
        # from, else the blob was serialized for a different program.
        if manifest.get("fields") != fields:
            raise WarmstartMiss("key-mismatch", key[:12])
        try:
            with open(epath, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            from jax.experimental import serialize_executable
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:  # corrupt pickle, version skew, XLA reject
            raise WarmstartMiss("deserialize-failed", f"{key[:12]}: {e}")
        return compiled, manifest

    # -- save ----------------------------------------------------------
    def save(self, fields: Dict[str, Any], compiled: Any, *,
             golden_scores: Any, params_fingerprint: str) -> bool:
        """Serialize ``compiled`` under its content key.

        Best-effort: serialization failures (unsupported backend, full
        disk) are logged and swallowed — the executable still serves
        from memory, the next spawn just recompiles.
        """
        key = warmkey.store_key(fields)
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            # Round-trip proof BEFORE anything hits disk: an executable
            # that was itself loaded from XLA's persistent compilation
            # cache (the --compile-cache-dir fallback tier) serializes
            # to a payload its own deserializer rejects ("Symbols not
            # found") — parking it would turn every future spawn into a
            # loud fallback, so refuse it here and let that spawn ride
            # the compile-cache tier instead.
            serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
            blob = pickle.dumps((payload, in_tree, out_tree))
            warmkey.write_atomic(self.exe_path(key), blob)
            manifest = {
                "schema": warmkey.WARMSTART_SCHEMA,
                "fields": fields,
                "key": key,
                "params_fingerprint": str(params_fingerprint),
                "golden_scores": warmkey.encode_array(golden_scores),
                "payload_bytes": len(blob),
            }
            warmkey.write_manifest(self.manifest_path(key), manifest)
            return True
        except Exception as e:  # never let persistence break serving
            log.warning("warmstart: serialize of %s failed: %s", key[:12], e)
            return False

    def refresh_manifest(self, fields: Dict[str, Any], *, golden_scores: Any,
                         params_fingerprint: str) -> None:
        """Re-stamp an existing entry's manifest for the current checkpoint
        (after a fingerprint-skew load passed the canary) so the *next*
        same-checkpoint spawn gets the bit-exact gate back."""
        key = warmkey.store_key(fields)
        try:
            manifest = warmkey.read_manifest(self.manifest_path(key))
            manifest["params_fingerprint"] = str(params_fingerprint)
            manifest["golden_scores"] = warmkey.encode_array(golden_scores)
            warmkey.write_manifest(self.manifest_path(key), manifest)
        except (OSError, ValueError) as e:  # pragma: no cover - best effort
            log.warning("warmstart: manifest refresh of %s failed: %s",
                        key[:12], e)
