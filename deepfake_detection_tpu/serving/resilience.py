"""Serving resilience primitives: typed failures, circuit breaker, stuck-
batch watchdog, retry jitter.

PR 3 made *training* provably fault-tolerant (injection points, loud
accounting, recovery contracts, e2e chaos tests); this module brings the
same discipline to the request path.  The pieces are deliberately small,
jax-free state machines so the fast tier can unit-test every transition
with an injected clock, while ``serving/engine.py`` wires them to the
real device loop and ``tools/chaos_serve.py`` proves them end-to-end
against a live server under injected faults.

Failure taxonomy (what an HTTP client sees):

* :class:`NonFiniteScores` — the device batch executed but produced
  NaN/Inf rows.  Mapped to **503** (+ Retry-After): the *request* was
  fine, the *serving set* is suspect — a silent NaN score would poison
  every downstream verdict, so it is never returned.
* :class:`EngineStalled` — the stuck-batch watchdog abandoned a device
  batch that never completed.  Mapped to **503**; readiness drops until
  the engine worker is restarted and every AOT bucket is re-warmed.
* :class:`BreakerOpen` — the circuit breaker is rejecting before the
  queue: **503** + jittered Retry-After without touching the batcher.

The breaker follows the classic three-state contract (all state visible
in ``/metrics``):

* **closed** — normal serving; ``failure_threshold`` *consecutive* batch
  failures open it (successes reset the streak — sporadic poison
  requests must not trip it).
* **open** — every ``allow()`` is rejected for ``open_s`` seconds with a
  Retry-After derived from the remaining cooldown plus a bounded jitter
  (the bare remainder would point every shed client at the same
  half-open instant).
* **half-open** — after the cooldown exactly ONE probe is admitted; its
  batch outcome closes the breaker (success) or re-opens it (failure).
  Other arrivals keep shedding while the probe is in flight.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Optional

__all__ = ["NonFiniteScores", "EngineStalled", "BreakerOpen",
           "CircuitBreaker", "ServeWatchdog", "jittered_retry_after",
           "torn_copy", "BREAKER_CLOSED", "BREAKER_OPEN",
           "BREAKER_HALF_OPEN"]


class NonFiniteScores(RuntimeError):
    """The device batch returned NaN/Inf scores (never served silently)."""


class EngineStalled(RuntimeError):
    """A device batch exceeded the stuck-batch watchdog timeout."""


class BreakerOpen(RuntimeError):
    """The circuit breaker is open; retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"circuit breaker open; retry in "
                         f"{retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


def jittered_retry_after(base_s: float, spread_s: float,
                         rng: Optional[random.Random] = None) -> float:
    """``base_s`` plus a bounded uniform spread.

    A constant Retry-After synchronizes every shed client into one
    thundering-herd resend wave exactly ``base_s`` later; the uniform
    ``[0, spread_s)`` jitter de-correlates them while keeping the bound
    explicit (the advertised worst case is ``base_s + spread_s``)."""
    r = rng if rng is not None else random
    return float(base_s) + r.uniform(0.0, max(0.0, float(spread_s)))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

#: stable numeric encoding for the /metrics gauge
BREAKER_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1,
                      BREAKER_HALF_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker over *batch* outcomes.

    ``allow()`` gates admission (HTTP thread), ``record_success`` /
    ``record_failure`` report batch outcomes (engine thread).  A
    ``failure_threshold`` of 0 disables the breaker entirely (``allow``
    always True, outcomes ignored) so the knob can be turned off without
    a second code path at the call sites.

    ``clock`` is injectable for deterministic state-machine tests.
    """

    def __init__(self, failure_threshold: int = 5, open_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, retry_jitter_s: float = 2.0):
        self.failure_threshold = int(failure_threshold)
        self.open_s = float(open_s)
        self.retry_jitter_s = float(retry_jitter_s)
        self._retry_rng = random.Random(0xB12EA4)
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0

    # ------------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> None:
        self._state = state
        if self._metrics is not None:
            self._metrics.breaker_state = BREAKER_STATE_CODE[state]

    # ------------------------------------------------------------------
    def allow(self) -> None:
        """Admission check; raises :class:`BreakerOpen` when shedding.

        The OPEN → HALF_OPEN transition happens lazily here (no timer
        thread): the first arrival after the cooldown becomes the probe.
        """
        if not self.enabled:
            return
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return
            now = self._clock()
            if self._state == BREAKER_OPEN:
                remaining = self._opened_at + self.open_s - now
                if remaining > 0:
                    if self._metrics is not None:
                        self._metrics.breaker_rejected_total.inc()
                    # jittered: the remaining cooldown alone would point
                    # every shed client at the same half-open instant —
                    # one resend wave, one probe, everyone else shed again
                    raise BreakerOpen(jittered_retry_after(
                        max(0.1, remaining), self.retry_jitter_s,
                        self._retry_rng))
                self._set_state(BREAKER_HALF_OPEN)
                self._probe_inflight = False
            # HALF_OPEN: exactly one probe rides through.  A probe whose
            # outcome never reports (e.g. its request deadlined out of
            # the queue) must not wedge the breaker shut — after a full
            # cooldown's worth of silence the next arrival re-probes.
            if self._probe_inflight and \
                    now - self._probe_started <= self.open_s:
                if self._metrics is not None:
                    self._metrics.breaker_rejected_total.inc()
                raise BreakerOpen(jittered_retry_after(
                    max(0.1, self.open_s / 2.0), self.retry_jitter_s,
                    self._retry_rng))
            self._probe_inflight = True
            self._probe_started = now
            if self._metrics is not None:
                self._metrics.breaker_probes_total.inc()

    # ------------------------------------------------------------------
    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._consecutive_failures = 0
            if self._state != BREAKER_CLOSED:
                self._set_state(BREAKER_CLOSED)
                self._probe_inflight = False

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self._state == BREAKER_HALF_OPEN:
                # the probe failed: back to a full cooldown
                self._opened_at = self._clock()
                self._set_state(BREAKER_OPEN)
                self._probe_inflight = False
                self._consecutive_failures = self.failure_threshold
                if self._metrics is not None:
                    self._metrics.breaker_opens_total.inc()
                return
            self._consecutive_failures += 1
            if self._state == BREAKER_CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._set_state(BREAKER_OPEN)
                if self._metrics is not None:
                    self._metrics.breaker_opens_total.inc()


# ---------------------------------------------------------------------------
# stuck-batch watchdog
# ---------------------------------------------------------------------------

class ServeWatchdog:
    """Monitor thread for the engine's two wedge modes: a device batch
    that never completes (hang) and a worker thread that died outright
    (an injected kill, an un-catchable error).

    Deliberately knows nothing about jax: it reads two callables —
    ``oldest_dispatch()`` (monotonic dispatch time of the oldest
    in-flight batch, or None) and ``worker_alive()`` — and calls
    ``recover(reason)`` on the watchdog thread when either trips.
    ``recover`` runs synchronously, so a recovery that re-warms every
    bucket cannot be re-triggered mid-flight.
    """

    def __init__(self, timeout_s: float,
                 oldest_dispatch: Callable[[], Optional[float]],
                 worker_alive: Callable[[], bool],
                 recover: Callable[[str], None],
                 poll_s: float = 0.05):
        self.timeout_s = float(timeout_s)
        self._oldest_dispatch = oldest_dispatch
        self._worker_alive = worker_alive
        self._recover = recover
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            oldest = self._oldest_dispatch()
            if oldest is not None and \
                    time.monotonic() - oldest > self.timeout_s:
                self._recover("stalled")
                continue
            if not self._worker_alive():
                self._recover("worker_died")


# ---------------------------------------------------------------------------
# chaos support
# ---------------------------------------------------------------------------

def torn_copy(path: str, tmp_dir: Optional[str] = None) -> str:
    """Write a half-truncated copy of ``path`` next to it (or in
    ``tmp_dir``) and return the copy's path.

    The ``torn_reload`` chaos point routes the reload watcher through
    this so the REAL torn-msgpack rejection path (``CheckpointCorrupt``
    naming the file) is exercised, not a synthetic stand-in."""
    with open(path, "rb") as f:
        data = f.read()
    dst = os.path.join(tmp_dir or os.path.dirname(path),
                       ".chaos-torn-" + os.path.basename(path))
    with open(dst, "wb") as f:
        f.write(data[:max(1, len(data) // 2)])
    return dst
