"""Serving observability: per-stage latency histograms, counters, rolling
throughput, and a Prometheus text-format renderer.

Built on :class:`deepfake_detection_tpu.utils.metrics.LatencyHistogram` —
the host-side sibling of the train loop's ``AverageMeter``.  Everything is
stdlib: no prometheus_client dependency; the text exposition format lives
in the shared :mod:`deepfake_detection_tpu.utils.prometheus` renderer
(also used by the trainer's ``--metrics-port`` endpoint, obs/telemetry.py),
which is what ``GET /metrics`` serves — output is byte-identical to the
pre-refactor inline renderer (locked by tests/test_obs.py).

Stages mirror a request's life: ``queue`` (submit → batch dispatch),
``preprocess`` (decode+resize on the HTTP thread), ``device`` (padded
bucket executes), ``total`` (socket in → response out).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Tuple

from ..utils.metrics import LatencyHistogram
from ..utils.prometheus import Counter as _Counter
from ..utils.prometheus import PromText

__all__ = ["ServingMetrics"]

_PREFIX = "dfd_serving"

# ---------------------------------------------------------------------------
# Process-wide backend-compile observer.  The engine's own compiles_total
# counts its AOT bucket builds, but only a signal from INSIDE jax can
# catch a silent recompile some other code path triggers — this listener
# increments on every real backend compile in the process, and the bench's
# zero-recompile probe asserts the DELTA across the load phase is zero.
# ---------------------------------------------------------------------------

_backend_compiles = 0
_backend_lock = threading.Lock()
_listener_installed = False


def _on_event_duration(name: str, *_args, **_kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        global _backend_compiles
        with _backend_lock:
            _backend_compiles += 1


def install_backend_compile_listener() -> bool:
    """Idempotent; returns True if the jax monitoring hook is available."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax._src import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:                              # noqa: BLE001 — optional
        return False
    _listener_installed = True
    return True


def backend_compile_count() -> int:
    """Backend compiles observed process-wide since the listener went in
    (0 until then)."""
    with _backend_lock:
        return _backend_compiles

#: serving latencies cluster well under the train-loop default bounds —
#: extend down to 100 µs so queue-wait under light load still resolves
_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
           0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

STAGES = ("queue", "preprocess", "device", "total")

#: per-model request-book resolutions (the ``model=`` labeled mirror of
#: the global books: per model, accepted == cache_hit + scored + shed +
#: deadline + failed holds exactly, plus reloads for A/B observability)
MODEL_BOOK_KINDS = ("accepted", "scored", "failed", "shed", "deadline",
                    "cache_hit", "reloads")

#: cascade tiers (serving/cascade.py latency histograms)
CASCADE_TIERS = ("student", "flagship")

#: cold-start stages in pipeline order (spawn → serving): the runner
#: stamps spawn/import/params_load/ready, the engine stamps compile
#: (deserialize-or-compile) and warm — SERVE_BENCH §Cold start reads
#: the breakdown off one /metrics scrape
WARMUP_STAGES = ("spawn", "import", "params_load", "compile", "warm",
                 "ready")


class ServingMetrics:
    """One registry per server process."""

    def __init__(self, throughput_window_s: float = 30.0):
        self.latency: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram(_BOUNDS) for s in STAGES}
        self.requests_total: Dict[str, _Counter] = {}   # keyed by status
        self._requests_lock = threading.Lock()
        # request-books ledger: every submit attempt lands in accepted,
        # and every accepted request resolves EXACTLY once as cache_hit,
        # scored, shed, deadline or failed — tools/chaos_serve.py asserts
        # the identity accepted == cache_hit + scored + shed + deadline +
        # failed from a /metrics scrape after every fault scenario
        self.accepted_total = _Counter()
        self.scored_total = _Counter()
        self.failed_total = _Counter()
        self.shed_total = _Counter()
        self.deadline_total = _Counter()
        self.batches_total = _Counter()
        self.batch_rows_total = _Counter()
        self.padded_rows_total = _Counter()
        self.compiles_total = _Counter()
        self.reloads_total = _Counter()
        self.reload_errors_total = _Counter()
        self.reload_canary_failures_total = _Counter()
        self.worker_restarts_total = _Counter()
        self.watchdog_recoveries_total = _Counter()
        self.nonfinite_batches_total = _Counter()
        self.rewarms_total = _Counter()
        self.breaker_opens_total = _Counter()
        self.breaker_probes_total = _Counter()
        self.breaker_rejected_total = _Counter()
        # verdict-cache books (ISSUE 17 dedup tier): cache_hit is the new
        # resolution term (exact + near + coalesced); near/coalesced are
        # sub-counters, the rest is store lifecycle (never silent)
        self.cache_hit_total = _Counter()
        self.cache_near_hit_total = _Counter()
        self.cache_coalesced_total = _Counter()
        self.cache_miss_total = _Counter()
        self.cache_insert_total = _Counter()
        self.cache_expired_total = _Counter()
        self.cache_evicted_total = _Counter()
        self.cache_invalidated_total = _Counter()
        # warm-start executable store books (ISSUE 19): every store
        # interaction at warmup lands in exactly one of hit (entry
        # deserialized), miss (absent — fresh compile), fallback
        # (present but corrupt/foreign/version-skewed — fresh compile,
        # loudly); canary_rejects count deserialized executables the
        # golden-batch gate refused to let serve (also recompiled);
        # serialized counts entries (re)written to the store
        self.warmstart_hits_total = _Counter()
        self.warmstart_misses_total = _Counter()
        self.warmstart_fallbacks_total = _Counter()
        self.warmstart_canary_rejects_total = _Counter()
        self.warmstart_serialized_total = _Counter()
        # per-stage cold-start walls (gauges, seconds): stamped once on
        # the way up, so one scrape yields the whole breakdown
        self.warmup_seconds: Dict[str, float] = {
            s: 0.0 for s in WARMUP_STAGES}
        self.chaos_injections_total: Dict[str, _Counter] = {}
        self._chaos_lock = threading.Lock()
        # per-model request books (ISSUE 14 multi-model engine): the
        # same resolution ledger as the global books, keyed by model id
        # — (kind, model) -> Counter, kinds from MODEL_BOOK_KINDS
        self.model_books: Dict[Tuple[str, str], _Counter] = {}
        self._model_lock = threading.Lock()
        # per-(model, bucket) row accounting: (model, bucket, kind) ->
        # Counter with kind in {"real", "pad"} — bench_serve's per-bucket
        # padding-fraction report reads these
        self.bucket_rows: Dict[Tuple[str, int, str], _Counter] = {}
        self._bucket_lock = threading.Lock()
        # cascade books (serving/cascade.py): triaged == cleared +
        # escalated; escalated == flagship_scored + escalation_failed —
        # both identities hold EXACTLY through every fault
        self.cascade_triaged_total = _Counter()
        self.cascade_cleared_total = _Counter()
        self.cascade_escalated_total = _Counter()
        self.cascade_flagship_scored_total = _Counter()
        self.cascade_escalation_failed_total = _Counter()
        self.cascade_latency: Dict[str, LatencyHistogram] = {
            t: LatencyHistogram(_BOUNDS) for t in CASCADE_TIERS}
        self.queue_depth = 0            # gauge, written by the batcher
        self.cache_entries = 0          # gauge, written on cache inserts
        self.inflight = 0               # gauge, written by the engine
        self.ready = False              # gauge, flipped after warmup and
        # DROPPED during watchdog recovery / bucket re-warm / reload canary
        self.breaker_state = 0          # gauge (0 closed, 1 open, 2 half)
        self._window_s = float(throughput_window_s)
        self._completions: Deque[Tuple[float, int]] = collections.deque()
        self._completions_lock = threading.Lock()

    # ------------------------------------------------------------------
    def count_request(self, status: int) -> None:
        key = str(int(status))
        with self._requests_lock:
            c = self.requests_total.get(key)
            if c is None:
                c = self.requests_total[key] = _Counter()
        c.inc()

    def count_chaos(self, point: str) -> None:
        """One injected fault fired (keyed by injection-point name) —
        chaos runs must be as loudly accounted as the faults they mimic."""
        with self._chaos_lock:
            c = self.chaos_injections_total.get(point)
            if c is None:
                c = self.chaos_injections_total[point] = _Counter()
        c.inc()

    def count_model(self, kind: str, model: str, n: int = 1) -> None:
        """One per-model book resolution (``kind`` from
        MODEL_BOOK_KINDS); rides next to every global-book increment so
        the labeled ledger balances exactly like the global one."""
        key = (kind, model or "default")
        with self._model_lock:
            c = self.model_books.get(key)
            if c is None:
                c = self.model_books[key] = _Counter()
        c.inc(n)

    def model_book(self, kind: str, model: str) -> int:
        """Current value of one per-model book counter (0 if untouched)."""
        with self._model_lock:
            c = self.model_books.get((kind, model or "default"))
        return c.value if c is not None else 0

    def count_bucket_rows(self, model: str, bucket: int, real: int,
                          pad: int) -> None:
        """Real/pad row counts of one executed (model, bucket) batch."""
        model = model or "default"
        for kind, n in (("real", real), ("pad", pad)):
            if n <= 0:
                continue
            key = (model, int(bucket), kind)
            with self._bucket_lock:
                c = self.bucket_rows.get(key)
                if c is None:
                    c = self.bucket_rows[key] = _Counter()
            c.inc(n)

    def count_completion(self, n: int, now: float | None = None) -> None:
        """Record ``n`` scored requests for the rolling-throughput gauge."""
        now = time.monotonic() if now is None else now
        with self._completions_lock:
            self._completions.append((now, n))
            self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self._window_s
        while self._completions and self._completions[0][0] < cutoff:
            self._completions.popleft()

    def throughput(self, now: float | None = None) -> float:
        """Scored requests/sec over the trailing window."""
        now = time.monotonic() if now is None else now
        with self._completions_lock:
            self._trim(now)
            if not self._completions:
                return 0.0
            total = sum(n for _, n in self._completions)
            span = max(now - self._completions[0][0], 1e-9)
            # a single just-landed batch would divide by ~0; floor the span
            # at 1s so the gauge ramps instead of spiking
            return total / max(span, 1.0)

    # ------------------------------------------------------------------
    def render_prometheus(self) -> str:
        doc = PromText(_PREFIX)
        counter, gauge = doc.counter, doc.gauge

        doc.header("requests_total", "Requests by HTTP status", "counter")
        with self._requests_lock:
            items = sorted((k, c.value) for k, c in
                           self.requests_total.items())
        for status, value in items:
            doc.sample("requests_total", f'{{status="{status}"}}', value)
        counter("accepted_total", "Requests offered to the micro-batcher "
                "(books: accepted == cache_hit + scored + shed + deadline "
                "+ failed)", self.accepted_total.value)
        counter("scored_total", "Requests resolved with a score",
                self.scored_total.value)
        counter("failed_total", "Requests resolved with an error (engine "
                "fault, non-finite batch, stall, shutdown)",
                self.failed_total.value)
        counter("shed_total", "Requests rejected 429 (queue full)",
                self.shed_total.value)
        counter("deadline_total", "Requests failed 504 (deadline exceeded)",
                self.deadline_total.value)
        counter("batches_total", "Device batches executed",
                self.batches_total.value)
        counter("batch_rows_total", "Real rows across executed batches",
                self.batch_rows_total.value)
        counter("padded_rows_total", "Padding rows across executed batches",
                self.padded_rows_total.value)
        counter("compiles_total", "Bucket executables built by the engine "
                "(startup warmup only)", self.compiles_total.value)
        counter("backend_compiles_total", "Real XLA backend compiles "
                "observed process-wide (jax monitoring hook; growth after "
                "ready=1 means something recompiled)",
                backend_compile_count())
        counter("reloads_total", "Successful hot weight reloads",
                self.reloads_total.value)
        counter("reload_errors_total", "Rejected/failed hot reloads",
                self.reload_errors_total.value)
        counter("reload_canary_failures_total", "Hot reloads rejected by "
                "the golden-batch canary (non-finite / drifted scores)",
                self.reload_canary_failures_total.value)
        counter("worker_restarts_total", "Engine worker crash recoveries",
                self.worker_restarts_total.value)
        counter("watchdog_recoveries_total", "Watchdog-driven engine "
                "restarts (stuck batch or dead worker)",
                self.watchdog_recoveries_total.value)
        counter("nonfinite_batches_total", "Device batches discarded for "
                "NaN/Inf scores (every row failed 503, never served)",
                self.nonfinite_batches_total.value)
        counter("rewarms_total", "Full AOT bucket re-warm passes after a "
                "recovery (executes existing executables; no recompiles)",
                self.rewarms_total.value)
        counter("breaker_opens_total", "Circuit-breaker closed/half-open "
                "-> open transitions", self.breaker_opens_total.value)
        counter("breaker_probes_total", "Half-open probe requests admitted",
                self.breaker_probes_total.value)
        counter("breaker_rejected_total", "Requests shed 503 by the open "
                "breaker", self.breaker_rejected_total.value)
        counter("cache_hit_total", "Requests resolved by the verdict "
                "cache — exact + near-dup + coalesced (books: accepted "
                "== cache_hit + scored + shed + deadline + failed)",
                self.cache_hit_total.value)
        counter("cache_near_hit_total", "Verdict-cache hits via the "
                "near-dup perceptual index (subset of cache_hit_total; "
                "never conflated with exact hits)",
                self.cache_near_hit_total.value)
        counter("cache_coalesced_total", "Requests that rode an "
                "in-flight twin's single dispatch (subset of "
                "cache_hit_total)", self.cache_coalesced_total.value)
        counter("cache_miss_total", "Keyed submits that found no cached "
                "verdict and dispatched", self.cache_miss_total.value)
        counter("cache_insert_total", "Verdicts stored after a scored "
                "miss", self.cache_insert_total.value)
        counter("cache_expired_total", "Verdict-cache entries dropped at "
                "TTL expiry", self.cache_expired_total.value)
        counter("cache_evicted_total", "Verdict-cache entries evicted by "
                "LRU capacity", self.cache_evicted_total.value)
        counter("cache_invalidated_total", "Verdict-cache entries purged "
                "by a reload's fingerprint bump (stale hits are "
                "impossible by construction; this reclaims the memory)",
                self.cache_invalidated_total.value)
        counter("warmstart_hits_total", "Warm-start store entries "
                "deserialized at warmup (each still gated by the "
                "golden-batch canary before serving)",
                self.warmstart_hits_total.value)
        counter("warmstart_misses_total", "Warm-start store lookups "
                "that found no entry (fresh compile + serialize)",
                self.warmstart_misses_total.value)
        counter("warmstart_fallbacks_total", "Warm-start entries "
                "present but unusable (corrupt/foreign/version-skew) — "
                "counted fallback to fresh compile, never a crash",
                self.warmstart_fallbacks_total.value)
        counter("warmstart_canary_rejects_total", "Deserialized "
                "executables rejected by the golden-batch canary "
                "(non-finite/shape/bit-drift) and recompiled fresh",
                self.warmstart_canary_rejects_total.value)
        counter("warmstart_serialized_total", "Executables serialized "
                "into the warm-start store this process",
                self.warmstart_serialized_total.value)
        # per-model request books (multi-model engine): one labeled
        # family per resolution kind, mirroring the global ledger
        with self._model_lock:
            model_items = sorted(
                ((kind, model), c.value)
                for (kind, model), c in self.model_books.items())
        for kind in MODEL_BOOK_KINDS:
            doc.header(f"model_{kind}_total",
                       f"Per-model request books: {kind}", "counter")
            for (k, model), value in model_items:
                if k == kind:
                    doc.sample(f"model_{kind}_total",
                               f'{{model="{model}"}}', value)
        doc.header("bucket_rows_total", "Rows per executed (model, "
                   "bucket) batch, split real|pad (bench_serve's "
                   "per-bucket padding report)", "counter")
        with self._bucket_lock:
            bucket_items = sorted((k, c.value)
                                  for k, c in self.bucket_rows.items())
        for (model, bucket, kind), value in bucket_items:
            doc.sample("bucket_rows_total",
                       f'{{model="{model}",bucket="{bucket}",'
                       f'kind="{kind}"}}', value)
        counter("cascade_triaged_total", "Clips scored by the cascade "
                "student (books: triaged == cleared + escalated)",
                self.cascade_triaged_total.value)
        counter("cascade_cleared_total", "Cascade clips resolved by the "
                "student verdict (score outside the suspect band)",
                self.cascade_cleared_total.value)
        counter("cascade_escalated_total", "Cascade clips escalated to "
                "the flagship (books: escalated == flagship_scored + "
                "escalation_failed)", self.cascade_escalated_total.value)
        counter("cascade_flagship_scored_total", "Escalated clips "
                "resolved by a flagship score",
                self.cascade_flagship_scored_total.value)
        counter("cascade_escalation_failed_total", "Escalations that "
                "failed (shed/deadline/engine fault): the student "
                "verdict is served instead — never a silent drop",
                self.cascade_escalation_failed_total.value)
        doc.header("chaos_injections_total",
                   "Injected faults fired (DFD_CHAOS), by point", "counter")
        with self._chaos_lock:
            chaos_items = sorted((k, c.value) for k, c in
                                 self.chaos_injections_total.items())
        for point, value in chaos_items:
            doc.sample("chaos_injections_total", f'{{point="{point}"}}',
                       value)
        gauge("queue_depth", "Requests waiting in the micro-batch queue",
              self.queue_depth)
        gauge("cache_entries", "Verdicts currently stored in the cache",
              self.cache_entries)
        gauge("inflight", "Requests staged on device", self.inflight)
        gauge("ready", "1 once all buckets are warmed (drops during "
              "recovery re-warm and the reload canary)", int(self.ready))
        gauge("breaker_state", "Circuit breaker state (0 closed, 1 open, "
              "2 half-open)", self.breaker_state)
        gauge("throughput_rps",
              f"Scored requests/sec, trailing {self._window_s:.0f}s window",
              round(self.throughput(), 3))
        doc.header("warmup_seconds", "Cold-start stage walls "
                   "(spawn -> serving), seconds", "gauge")
        for stage in WARMUP_STAGES:
            doc.sample("warmup_seconds", f'{{stage="{stage}"}}',
                       round(self.warmup_seconds[stage], 6))

        for stage in STAGES:
            # one-snapshot consistency per stage lives in PromText.histogram
            doc.histogram("latency_seconds", "Per-stage request latency",
                          self.latency[stage], labels=f'stage="{stage}"')
        for tier in CASCADE_TIERS:
            doc.histogram("cascade_latency_seconds",
                          "Per-tier cascade latency (submit -> verdict)",
                          self.cascade_latency[tier],
                          labels=f'tier="{tier}"')
        return doc.render()
