"""Content-addressed keys + manifests for the warm-start executable store.

jax-free on purpose (enforced by dfdlint's purity rule): the key of a
compiled executable must be computable — and auditable — without paying
the jax import, so the router/autoscaler side and offline tooling can
reason about store contents.  The jax-touching serialize/deserialize
half lives in ``serving.warmstart``.

A store key is the sha256 of the canonical-JSON rendering of a *loud,
complete* fingerprint of everything compilation is a pure function of:

===================  =====================================================
field                meaning
===================  =====================================================
``schema``           ``dfd.serving.warmstart.v1`` — bump to orphan a store
``jax`` / ``jaxlib`` installed dists (XLA ships pinned inside jaxlib)
``backend``          ``jax.default_backend()`` at compile time
``device_kind``      ``devices()[0].device_kind`` (cpu / TPU v4 / …)
``program``          sha256 of the program identity: model repr + the
                     (path, shape, dtype) signature of the params tree +
                     normalization constants — weights are *arguments*,
                     so checkpoints of one architecture share executables
``geometry``         image_size / img_num / num_classes-bearing dict
``bucket``/``chans`` the padded batch bucket and input channel width
``wire``             wire dtype (``uint8`` / ``float32``)
``quant``            params quantization mode (``f32``/``bf16``/``int8``)
``sharding``         donation + in/out sharding signature ("" when unsharded)
===================  =====================================================

Any field drift → different key → clean miss; a *foreign* file under the
right name is still rejected by the manifest echo-check and then by the
golden-batch canary (see ``warmstart.ExecutableStore``).  Manifests ride
next to the payload as JSON and additionally record the golden-batch
scores + params fingerprint at serialize time so a same-checkpoint load
can demand bit-exactness.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import tempfile
from typing import Any, Dict

import numpy as np

WARMSTART_SCHEMA = "dfd.serving.warmstart.v1"

#: key fields that must be present before hashing — a partial key is a bug,
#: not a cache miss, so ``store_key`` refuses to hash one.
KEY_FIELDS = (
    "schema", "jax", "jaxlib", "backend", "device_kind",
    "program", "geometry", "bucket", "chans", "wire", "quant", "sharding",
)


def runtime_versions() -> Dict[str, str]:
    """Installed jax/jaxlib dist versions without importing jax."""
    from importlib import metadata
    out = {}
    for dist in ("jax", "jaxlib"):
        try:
            out[dist] = metadata.version(dist)
        except metadata.PackageNotFoundError:  # pragma: no cover - dev tree
            out[dist] = "unknown"
    return out


def key_fields(*, backend: str, device_kind: str, program: str,
               geometry: Dict[str, Any], bucket: int, chans: int,
               wire: str, quant: str, sharding: str = "") -> Dict[str, Any]:
    """Assemble the complete key-field dict (versions filled in here)."""
    vers = runtime_versions()
    return {
        "schema": WARMSTART_SCHEMA,
        "jax": vers["jax"],
        "jaxlib": vers["jaxlib"],
        "backend": str(backend),
        "device_kind": str(device_kind),
        "program": str(program),
        "geometry": dict(geometry),
        "bucket": int(bucket),
        "chans": int(chans),
        "wire": str(wire),
        "quant": str(quant),
        "sharding": str(sharding),
    }


def store_key(fields: Dict[str, Any]) -> str:
    """sha256 over the canonical JSON of a *complete* field dict."""
    missing = [f for f in KEY_FIELDS if f not in fields]
    if missing:
        raise ValueError(f"incomplete warmstart key, missing {missing}")
    blob = json.dumps(fields, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    """Bit-exact JSON-able encoding of an ndarray (golden scores)."""
    a = np.ascontiguousarray(arr)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def decode_array(enc: Dict[str, Any]) -> np.ndarray:
    buf = base64.b64decode(enc["data"])
    return np.frombuffer(buf, dtype=np.dtype(enc["dtype"])).reshape(enc["shape"])


def write_atomic(path: str, blob: bytes) -> None:
    """write → fsync → atomic rename, same idiom as data/packed.py."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".warm-", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_manifest(path: str, manifest: Dict[str, Any]) -> None:
    blob = (json.dumps(manifest, sort_keys=True) + "\n").encode()
    write_atomic(path, blob)


def read_manifest(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return json.loads(f.read().decode())
