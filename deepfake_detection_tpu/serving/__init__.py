"""Dynamic-batching inference serving subsystem.

The path from a trained checkpoint to a long-lived, concurrent, observable
service: ``batcher`` coalesces arrival-order requests into pre-compiled
batch buckets, ``engine`` owns the params on device (bucketed AOT compile
cache, double-buffered staging, hot weight reload), ``http`` is the
stdlib-only front end, ``metrics`` the Prometheus-text observability.
Entry point: ``python -m deepfake_detection_tpu.runners.serve``.

Lazy exports (same idiom as ``data/__init__``): importing the package
itself stays cheap — submodules (and their jax import) load on first
attribute access.
"""

from __future__ import annotations

_LAZY = {
    "MicroBatcher": "batcher", "Request": "batcher", "QueueFull": "batcher",
    "DeadlineExceeded": "batcher", "pick_bucket": "batcher",
    "InferenceEngine": "engine", "DEFAULT_BUCKETS": "engine",
    "ServingMetrics": "metrics",
    "ServingServer": "http", "make_server": "http",
    "serve_forever_in_thread": "http",
    "quantize_tree": "quant", "realize_tree": "quant",
    "canonical_mode": "quant", "QUANT_MODES": "quant",
    "CascadeRouter": "cascade", "CascadeResult": "cascade",
    "ExecutableStore": "warmstart", "WarmstartMiss": "warmstart",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
