"""Stdlib HTTP front end: ``POST /score``, health/readiness, Prometheus
metrics.

``http.server.ThreadingHTTPServer`` — one thread per connection, HTTP/1.1
keep-alive — is deliberately boring: request decode + preprocess are
GIL-releasing (PIL), the real concurrency is the micro-batcher, and no new
dependency enters the image.  The handler threads do the per-request CPU
work (JPEG decode, resize to canvas) so it overlaps the engine thread's
device calls.

Endpoints:

* ``POST /score`` — body is raw image bytes (``Content-Type: image/*``
  or ``application/octet-stream``), JSON ``{"image_b64": "..."}``, or a
  MULTI-FRAME clip: JSON ``{"frames_b64": [f1, ..., f_img_num]}`` or a
  ``multipart/*`` body with one image per part.  A single frame is
  replicated ×``img_num`` (the reference CLI's semantics); ``img_num``
  distinct frames are channel-concatenated into one temporal clip — and
  a clip of identical frames scores bit-identically to the replicate
  path (tests/test_serving.py).  On a multi-model engine a ``model``
  JSON field or ``?model=`` query param routes to one entry of the model
  table (unknown id = 400 listing the table); no ``model`` defaults to
  the flagship — or, when a cascade is configured, to student-first
  triage (suspects escalate to the flagship, the response then carries a
  ``cascade`` object with tier/student_score).  Responds
  ``{"fake_score": p, "scores": [...], "frames": n, "model": id,
  "timings_ms": {...}}``; 400 undecodable or a frame count other than
  1/``img_num``, 429 + jittered ``Retry-After`` when load-shedding, 503
  before warmup / while the circuit breaker is open / when the batch
  produced non-finite scores or was abandoned by the watchdog, 504 past
  the request deadline.
* ``GET /healthz`` — process liveness (200 while the process serves,
  INCLUDING during recovery re-warms — only readiness drops).
* ``GET /readyz`` — 200 only while every bucket is compiled+warmed AND
  no recovery re-warm or reload canary is in flight.  The body is the
  per-model readiness JSON (``engine.readiness_detail()``): a 503 with
  a parseable body tells a fleet router "cold model warming", no
  response at all means "engine down".
* ``GET /metrics`` — Prometheus text format (serving/metrics.py).
"""

from __future__ import annotations

import base64
import io
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs

import numpy as np
from PIL import Image

from ..cache import clip_phash, content_hash
from ..params import normalize_concat, normalize_replicate, prepare_canvas
from .batcher import DeadlineExceeded, MicroBatcher, QueueFull
from .engine import InferenceEngine
from .metrics import ServingMetrics
from .resilience import BreakerOpen, EngineStalled, NonFiniteScores

_logger = logging.getLogger(__name__)

__all__ = ["ServingServer", "make_server", "serve_forever_in_thread",
           "multipart_boundary", "split_multipart"]

_MAX_BODY = 32 * 1024 * 1024            # 32 MiB: generous for one image


def multipart_boundary(ctype_full: str) -> Optional[str]:
    """Boundary token from a full Content-Type header value, or None.
    The one parser both ``POST /score`` and the stream ingest use."""
    import re
    m = re.search(r'boundary="?([^";]+)"?', ctype_full)
    return m.group(1) if m else None


def split_multipart(body: bytes, boundary: str) -> list:
    """MJPEG/multipart chunk → list of part payloads.

    Handles both ``multipart/x-mixed-replace`` (MJPEG-over-HTTP's
    framing) and ``multipart/form-data`` bodies: parts are delimited by
    ``--<boundary>``, each part's payload starts after its blank line.
    Lives here (not streaming/) because streaming is built ON TOP of
    serving — the dependency only points one way.
    """
    delim = b"--" + boundary.encode()
    parts = []
    for raw in body.split(delim)[1:]:      # [0] is the preamble
        if raw.startswith(b"--"):          # closing terminator
            break
        # one CRLF (or bare LF) follows the boundary line ...
        if raw.startswith(b"\r\n"):
            raw = raw[2:]
        elif raw.startswith(b"\n"):
            raw = raw[1:]
        # ... then an (optionally EMPTY) header block ends at the first
        # blank line.  Locate it before touching any payload bytes — a
        # JPEG legally contains 0d0a0d0a, so trimming first (the old
        # strip()) could eat the real delimiter and truncate the frame.
        if raw.startswith(b"\r\n"):
            payload = raw[2:]
        elif raw.startswith(b"\n"):
            payload = raw[1:]
        else:
            head_end = raw.find(b"\r\n\r\n")
            if head_end >= 0:
                payload = raw[head_end + 4:]
            else:
                head_end = raw.find(b"\n\n")
                payload = raw[head_end + 2:] if head_end >= 0 else raw
        if payload.endswith(b"\r\n"):
            payload = payload[:-2]
        elif payload.endswith(b"\n"):
            payload = payload[:-1]
        if payload:
            parts.append(payload)
    return parts


class ServingServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the serving wiring."""

    daemon_threads = True
    # keep-alive matters: the load generator and any sane client reuse
    # connections, and accept() is the single-threaded part of this server
    protocol_version = "HTTP/1.1"
    # a router tier (or a bench loadgen) opens its whole connection pool
    # in one burst; the stdlib backlog of 5 would drop SYNs into 1s
    # retransmit stalls
    request_queue_size = 256

    def __init__(self, addr: Tuple[str, int], engine: InferenceEngine,
                 batcher: MicroBatcher, metrics: ServingMetrics,
                 request_timeout_s: float = 2.0, cascade=None):
        super().__init__(addr, _Handler)
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.request_timeout_s = float(request_timeout_s)
        #: optional serving/cascade.py CascadeRouter: when set, requests
        #: with no explicit ``model`` run student-first triage
        self.cascade = cascade


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # response headers + body are two writes; Nagle would hold the body
    # for the client's delayed ACK (~40 ms) on every small response
    disable_nagle_algorithm = True
    server: ServingServer   # typing aid

    # ------------------------------------------------------------------
    def log_message(self, fmt, *args):            # BaseHTTP logs to stderr
        _logger.debug("%s " + fmt, self.address_string(), *args)

    def _respond(self, status: int, body: bytes,
                 content_type: str = "application/json",
                 extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)
        self.server.metrics.count_request(status)

    def _respond_json(self, status: int, obj: dict,
                      extra_headers: Optional[dict] = None) -> None:
        self._respond(status, json.dumps(obj).encode(),
                      extra_headers=extra_headers)

    # ------------------------------------------------------------------
    def do_GET(self) -> None:                     # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._respond(200, b"ok\n", "text/plain")
        elif path == "/readyz":
            # JSON per-model readiness detail (ISSUE 15): the fleet
            # router's health scraper distinguishes "cold model warming"
            # (503 + parseable body, some model warmed=false) from
            # "engine down" (no response) without parsing metrics text
            detail = self.server.engine.readiness_detail()
            body = (json.dumps(detail, sort_keys=True) + "\n").encode()
            self._respond(200 if detail["ready"] else 503, body)
        elif path == "/metrics":
            text = self.server.metrics.render_prometheus()
            self._respond(200, text.encode(),
                          "text/plain; version=0.0.4; charset=utf-8")
        else:
            self._respond_json(404, {"error": f"no route {path!r}"})

    # ------------------------------------------------------------------
    def _read_body(self) -> Optional[bytes]:
        """Drain the request body (None = unreadable/oversize, connection
        will be closed).

        MUST run before any response on a POST: the connections are
        HTTP/1.1 keep-alive, so an unread body would be parsed as the
        next request line by the same socket's next round trip."""
        if self.headers.get("Transfer-Encoding"):
            # chunked bodies are unsupported and of unknown length —
            # poison the connection instead of the stream
            self.close_connection = True
            return None
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 <= length <= _MAX_BODY:
            # can't safely drain (unknown/huge length): poison the
            # connection instead of the stream
            self.close_connection = True
            return None
        return self.rfile.read(length)

    @staticmethod
    def _decode_frames(body: bytes, ctype_full: str
                       ) -> Tuple[Optional[list], Optional[str]]:
        """Body bytes → (list of uint8 RGB frame arrays, JSON ``model``
        routing field); (None, _) if any frame is undecodable."""
        ctype = ctype_full.split(";")[0].strip()
        model = None
        if ctype == "application/json":
            try:
                payload = json.loads(body)
                if not isinstance(payload, dict):
                    return None, None
                m = payload.get("model")
                model = m if isinstance(m, str) and m else None
                if "frames_b64" in payload:
                    blobs = [base64.b64decode(b, validate=True)
                             for b in payload["frames_b64"]]
                else:
                    b64 = payload.get("image_b64") or payload.get("image")
                    blobs = [base64.b64decode(b64, validate=True)]
            except (ValueError, TypeError, KeyError):
                return None, model
        elif ctype.startswith("multipart/"):
            boundary = multipart_boundary(ctype_full)
            if not boundary:
                return None, None
            blobs = split_multipart(body, boundary)
        else:
            blobs = [body]
        frames = []
        for blob in blobs:
            try:
                img = Image.open(io.BytesIO(blob))
                frames.append(np.asarray(img.convert("RGB"), np.uint8))
            except Exception:                      # noqa: BLE001 — 400 path
                return None, model
        return frames or None, model

    @staticmethod
    def _payload_for(srv, entry, frames: list):
        """Frames → one wire payload for ``entry`` (its canvas size, its
        img_num): the float32 wire runs the full CLI preprocess on the
        handler thread, the uint8 wire ships the canvas and defers the
        photometrics to the device prologue.  One frame replicates
        ×img_num (reference CLI semantics), img_num distinct frames
        concatenate into one temporal clip.  Raises ValueError for a
        clip this entry can't take (the 400 path)."""
        canvases = [prepare_canvas(f, entry.image_size) for f in frames]
        return _Handler._payload_from(srv, entry, canvases)

    @staticmethod
    def _payload_from(srv, entry, canvases: list):
        if srv.engine.wire == "float32":
            if len(canvases) == 1:
                return normalize_replicate(canvases[0], entry.img_num)
            return normalize_concat(canvases)
        if len(canvases) == 1:
            return canvases[0]
        if not entry.multi_frame:
            raise ValueError(f"multi-frame clips are disabled for model "
                             f"{entry.model_id!r} on this uint8-wire "
                             f"engine")
        return np.concatenate(canvases, axis=-1)

    def do_POST(self) -> None:                    # noqa: N802 (stdlib API)
        t0 = time.monotonic()
        body = self._read_body()        # always drain before responding
        t_body = time.monotonic()       # preprocess stage must not bill a
        path, _, query = self.path.partition("?")   # slow client's socket
        if path != "/score":
            self._respond_json(404, {"error": f"no route {path!r}"})
            return
        srv = self.server
        if not srv.engine.ready:
            # warming up (any model of the table still cold), or the
            # watchdog is re-warming buckets after a recovery, or a
            # reload canary is in flight — /healthz stays 200 throughout,
            # only readiness drops
            self._respond_json(503, {"error": "model warming up"},
                               extra_headers={"Retry-After": 1})
            return
        try:
            # breaker shedding happens BEFORE body decode costs anything
            # beyond the mandatory keep-alive drain
            srv.engine.breaker.allow()
        except BreakerOpen as e:
            self._respond_json(
                503, {"error": "circuit breaker open, retry later"},
                extra_headers={"Retry-After":
                               max(1, int(round(e.retry_after_s)))})
            return
        ctype_full = self.headers.get("Content-Type") or ""
        frames, json_model = (self._decode_frames(body, ctype_full)
                              if body else (None, None))
        if frames is None:
            self._respond_json(400, {"error": "undecodable image payload"})
            return
        # model routing: explicit ?model= / JSON field beats the default
        # (flagship, or student-first cascade when one is configured)
        requested = parse_qs(query).get("model", [None])[0] or json_model
        if requested is not None and not srv.engine.has_model(requested):
            self._respond_json(
                400, {"error": f"unknown model {requested!r}",
                      "models": list(srv.engine.model_ids())})
            return
        cascade = srv.cascade if (srv.cascade is not None
                                  and requested is None) else None
        entry = srv.engine.entry(
            cascade.student_id if cascade else requested)
        if len(frames) not in (1, entry.img_num):
            self._respond_json(
                400, {"error": f"need 1 or img_num={entry.img_num} "
                               f"frames, got {len(frames)}"})
            return
        try:
            canvases = [prepare_canvas(f, entry.image_size)
                        for f in frames]
            payload = self._payload_from(srv, entry, canvases)
        except ValueError as e:
            self._respond_json(400, {"error": str(e)})
            return
        # verdict-cache identity: hash the CANONICAL canvases (not the
        # wire bytes), so byte-identical re-uploads at any container or
        # encoding collide once decode+resize has normalized them; billed
        # to the preprocess stage like the canvas work it extends
        content_key = None
        if srv.batcher.cache is not None:
            content_key = (content_hash(canvases),
                           clip_phash(canvases)
                           if srv.batcher.cache.near_dup else None)
        t_pre = time.monotonic() - t_body     # decode+canvas only
        srv.metrics.latency["preprocess"].observe(t_pre)
        cas_result = None
        req = None
        try:
            if cascade is not None:
                flagship_entry = srv.engine.entry(cascade.flagship_id)
                # the flagship canvas is only prepared for the escalated
                # fraction (the thunk runs on this handler thread)
                cas_result = cascade.score(
                    payload,
                    lambda: self._payload_for(srv, flagship_entry,
                                              frames),
                    content_key=content_key)
                scores = cas_result.scores
            else:
                req = srv.batcher.submit(payload,
                                         timeout_s=srv.request_timeout_s,
                                         model_id=entry.model_id,
                                         content_key=content_key)
                # the batcher/engine enforce the queue-side deadline; the
                # extra 5s here only catches a wedged engine so the HTTP
                # thread can never hang forever
                scores = req.result(timeout=srv.request_timeout_s + 5.0)
        except QueueFull as e:
            self._respond_json(
                429, {"error": "overloaded, retry later",
                      "queue_depth": e.depth},
                extra_headers={"Retry-After":
                               max(1, int(round(e.retry_after_s)))})
            return
        except DeadlineExceeded:
            self._respond_json(504, {"error": "deadline exceeded"})
            return
        except (NonFiniteScores, EngineStalled) as e:
            # the request was fine, the serving set / engine was not:
            # 503 + Retry-After, never a silent NaN score or a 500 that
            # blames the client
            self._respond_json(503, {"error": f"scoring unavailable: {e}"},
                               extra_headers={"Retry-After": 1})
            return
        except Exception as e:                     # noqa: BLE001
            self._respond_json(500, {"error": f"scoring failed: {e!r}"})
            return
        total = time.monotonic() - t0
        srv.metrics.latency["total"].observe(total)
        served_model = entry.model_id if cas_result is None else (
            cascade.flagship_id if cas_result.tier == "flagship"
            else cascade.student_id)
        out = {
            "fake_score": float(scores[0]),
            "scores": [float(s) for s in scores],
            "frames": len(frames),
            "model": served_model,
            "timings_ms": {
                "preprocess": round(t_pre * 1000, 3),
                # cascade traffic reports the served tier's request
                # timings (CascadeResult.timings), not zeros
                "queue": round((req.timings if req is not None
                                else cas_result.timings
                                ).get("queue", 0.0) * 1000, 3),
                "device": round((req.timings if req is not None
                                 else cas_result.timings
                                 ).get("device", 0.0) * 1000, 3),
                "total": round(total * 1000, 3),
            },
        }
        if cas_result is not None:
            out["cascade"] = {
                "tier": cas_result.tier,
                "student_score": cas_result.student_score,
                "escalated": cas_result.escalated,
            }
            if cas_result.escalation_error:
                out["cascade"]["escalation_error"] = \
                    cas_result.escalation_error
        self._respond_json(200, out)


def make_server(host: str, port: int, engine: InferenceEngine,
                batcher: MicroBatcher, metrics: ServingMetrics,
                request_timeout_s: float = 2.0,
                cascade=None) -> ServingServer:
    return ServingServer((host, port), engine, batcher, metrics,
                         request_timeout_s, cascade=cascade)


def serve_forever_in_thread(server: ServingServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1},
                         name="serving-http", daemon=True)
    t.start()
    return t
