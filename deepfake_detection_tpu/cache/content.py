"""Content addressing: exact and perceptual hashes over canonical
canvases, plus the params-tree fingerprint that keys weight identity.

Everything here is numpy + hashlib — no jax, no I/O.  The exact hash is
taken AFTER canonicalization (``params.prepare_canvas``'s uint8 HWC
canvas) so the same clip re-encoded at a different quality/container
still collides once decode+resize has normalized it; two uploads that
decode to different pixels are different content by definition and only
the (opt-in) perceptual index may identify them.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Sequence, Tuple

import numpy as np

__all__ = [
    "content_hash",
    "dhash64",
    "ahash64",
    "clip_phash",
    "hamming64",
    "tree_fingerprint",
]


def content_hash(canvases: Sequence[np.ndarray]) -> str:
    """Exact content address: sha256 over dtype/shape/bytes of each
    canonical canvas, in frame order.

    Frame order is part of the identity (a reversed clip is different
    content), as are dtype and shape (a 380px canvas of the same clip is
    a different key — it feeds a different model entry anyway).
    """
    h = hashlib.sha256()
    for c in canvases:
        a = np.ascontiguousarray(c)
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _gray_grid(canvas: np.ndarray, gh: int, gw: int) -> np.ndarray:
    """Block-mean downsample to a ``gh x gw`` grayscale grid.

    Pure-numpy: channel mean, crop to block multiples, reshape-mean.
    Small inputs are edge-padded up to the grid size first.
    """
    a = np.asarray(canvas, dtype=np.float64)
    if a.ndim == 3:
        a = a.mean(axis=2)
    if a.ndim != 2:
        raise ValueError(f"canvas must be HW or HWC, got shape {a.shape}")
    h, w = a.shape
    if h < gh or w < gw:
        a = np.pad(a, ((0, max(0, gh - h)), (0, max(0, gw - w))),
                   mode="edge")
        h, w = a.shape
    h2, w2 = (h // gh) * gh, (w // gw) * gw
    a = a[:h2, :w2]
    return a.reshape(gh, h2 // gh, gw, w2 // gw).mean(axis=(1, 3))


def _pack_bits(bits: np.ndarray) -> int:
    v = 0
    for b in bits.reshape(-1):
        v = (v << 1) | int(b)
    return v


def dhash64(canvas: np.ndarray) -> int:
    """64-bit difference hash: 8x9 block-mean grid, bit = right > left.

    Gradient-based, so robust to global brightness/contrast shifts —
    the classic near-dup workhorse.
    """
    g = _gray_grid(canvas, 8, 9)
    return _pack_bits(g[:, 1:] > g[:, :-1])


def ahash64(canvas: np.ndarray) -> int:
    """64-bit average hash: 8x8 block-mean grid, bit = cell > mean."""
    g = _gray_grid(canvas, 8, 8)
    return _pack_bits(g > g.mean())


def clip_phash(canvases: Sequence[np.ndarray]) -> Tuple[int, int]:
    """Perceptual identity of a multi-frame clip: ``(dhash, ahash)``
    over the per-frame grids averaged across frames.

    Averaging grids (not hashing frame 0) keeps the identity stable
    under small temporal offsets while staying deterministic.
    """
    if not canvases:
        raise ValueError("clip_phash needs at least one canvas")
    d = np.mean([_gray_grid(c, 8, 9) for c in canvases], axis=0)
    a = np.mean([_gray_grid(c, 8, 8) for c in canvases], axis=0)
    return _pack_bits(d[:, 1:] > d[:, :-1]), _pack_bits(a > a.mean())


def hamming64(a: int, b: int) -> int:
    """Hamming distance between two 64-bit hashes."""
    return bin((a ^ b) & 0xFFFFFFFFFFFFFFFF).count("1")


def tree_fingerprint(leaves: Iterable[Tuple[str, np.ndarray]],
                     extra: Sequence[str] = ()) -> str:
    """Stable hex digest of a flattened params tree.

    ``leaves`` is ``(path, host_array)`` pairs in a deterministic order
    (the engine flattens with jax's key-path traversal and hands plain
    numpy here, keeping this module jax-free).  ``extra`` folds in
    out-of-tree identity such as the serving dtype — an f32→bf16 swap of
    the same weights scores differently and must not share verdicts.
    """
    h = hashlib.sha256()
    for tag in extra:
        h.update(str(tag).encode())
        h.update(b"\x00")
    for path, arr in leaves:
        a = np.ascontiguousarray(arr)
        h.update(str(path).encode())
        h.update(b"\x1f")
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()
