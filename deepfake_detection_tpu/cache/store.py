"""The bounded verdict store (LRU + TTL + near-dup band index) and the
in-flight coalescing table.

``VerdictCache`` is value-agnostic: the serving batcher stores score
rows, the fleet router stores whole HTTP response bodies under a
synthetic "edge" model whose fingerprint is the fleet weights-epoch.
One lock guards everything — probes are a dict hit plus an OrderedDict
move, far below the ~0.6 ms/clip device floor they replace.

The near-dup index is multi-index Hamming: each 64-bit dHash splits
into four 16-bit bands; by pigeonhole, any candidate within Hamming
radius ≤ 3 of the probe matches it exactly in at least one band, so a
probe is 4 bucket lookups + a handful of popcounts, never a scan.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import (Any, Callable, Dict, Hashable, List, Optional, Set,
                    Tuple)

from .content import hamming64

__all__ = ["VerdictCache", "SingleFlight"]

_BANDS = 4
_BAND_BITS = 16
_BAND_MASK = (1 << _BAND_BITS) - 1

Key = Tuple[str, str, str]          # (content_hash, model_id, fingerprint)
PHash = Tuple[int, int]             # (dhash64, ahash64)


class _Entry:
    __slots__ = ("value", "phash", "deadline")

    def __init__(self, value: Any, phash: Optional[PHash],
                 deadline: float) -> None:
        self.value = value
        self.phash = phash
        self.deadline = deadline


class VerdictCache:
    """Bounded LRU+TTL store keyed ``(content_hash, model_id,
    fingerprint)``.

    * ``capacity`` bounds entries; inserting past it evicts LRU (counted
      via ``on_evicted``, never silent).
    * ``ttl_s`` bounds staleness; an expired entry found by a probe is
      removed and counted via ``on_expired`` — expiry is lazy, there is
      no sweeper thread.
    * ``near_dup`` enables the dHash band index; near probes only ever
      run after an exact miss and hits are counted separately by the
      caller (never conflated with exact hits).
    * ``clock`` is injected for tests (monotonic seconds).

    Invalidation-on-reload needs no sweep either: a reload bumps the
    fingerprint, so old entries simply can never be addressed again —
    ``purge_model`` exists to reclaim their memory eagerly and count
    them.
    """

    def __init__(self, capacity: int, ttl_s: float, *,
                 near_dup: bool = False, near_radius: int = 3,
                 clock: Callable[[], float] = time.monotonic,
                 on_expired: Optional[Callable[[int], None]] = None,
                 on_evicted: Optional[Callable[[int], None]] = None) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be positive, got {ttl_s}")
        if not 0 <= near_radius <= 8:
            raise ValueError(
                f"near_radius must be in [0, 8], got {near_radius}")
        self.capacity = int(capacity)
        self.ttl_s = float(ttl_s)
        self.near_dup = bool(near_dup)
        self.near_radius = int(near_radius)
        self._clock = clock
        self._on_expired = on_expired
        self._on_evicted = on_evicted
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Key, _Entry]" = OrderedDict()
        # (model_id, fingerprint, band_index, band_value) -> keys
        self._bands: Dict[Tuple[str, str, int, int], Set[Key]] = {}

    # ------------------------------------------------------------- internals

    def _band_keys(self, key: Key, dhash: int):
        model_id, fp = key[1], key[2]
        for i in range(_BANDS):
            yield (model_id, fp, i, (dhash >> (_BAND_BITS * i)) & _BAND_MASK)

    def _index_add(self, key: Key, phash: Optional[PHash]) -> None:
        if phash is None:
            return
        for bk in self._band_keys(key, phash[0]):
            self._bands.setdefault(bk, set()).add(key)

    def _index_remove(self, key: Key, phash: Optional[PHash]) -> None:
        if phash is None:
            return
        for bk in self._band_keys(key, phash[0]):
            bucket = self._bands.get(bk)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._bands[bk]

    def _remove(self, key: Key) -> None:
        e = self._entries.pop(key)
        self._index_remove(key, e.phash)

    def _expire(self, keys: List[Key]) -> None:
        for key in keys:
            self._remove(key)
        if keys and self._on_expired is not None:
            self._on_expired(len(keys))

    # --------------------------------------------------------------- probes

    def get(self, content_hash: str, model_id: str,
            fingerprint: str) -> Optional[Any]:
        """Exact probe; None on miss.  Hits refresh LRU recency."""
        key = (content_hash, model_id, fingerprint)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            if e.deadline <= self._clock():
                self._expire([key])
                return None
            self._entries.move_to_end(key)
            return e.value

    def get_near(self, phash: PHash, model_id: str,
                 fingerprint: str) -> Optional[Tuple[Any, int]]:
        """Near-dup probe: best in-radius candidate as ``(value, dist)``.

        Both the dHash and aHash distances must sit within the radius —
        the aHash check cuts false positives the gradient hash alone
        lets through (its caveats are documented in the README: a
        near-hit is a *different* clip's verdict by construction).
        """
        if not self.near_dup:
            return None
        dhash, ahash = phash
        with self._lock:
            now = self._clock()
            candidates: Set[Key] = set()
            for i in range(_BANDS):
                bk = (model_id, fingerprint, i,
                      (dhash >> (_BAND_BITS * i)) & _BAND_MASK)
                candidates |= self._bands.get(bk, set())
            best_key, best_dist = None, None
            dead: List[Key] = []
            for key in candidates:
                e = self._entries.get(key)
                if e is None or e.phash is None:
                    continue
                if e.deadline <= now:
                    dead.append(key)
                    continue
                d = hamming64(dhash, e.phash[0])
                if d > self.near_radius:
                    continue
                if hamming64(ahash, e.phash[1]) > self.near_radius:
                    continue
                if best_dist is None or d < best_dist:
                    best_key, best_dist = key, d
            self._expire(dead)
            if best_key is None:
                return None
            self._entries.move_to_end(best_key)
            return self._entries[best_key].value, int(best_dist)

    # ------------------------------------------------------------ mutations

    def put(self, content_hash: str, model_id: str, fingerprint: str,
            value: Any, *, phash: Optional[PHash] = None) -> None:
        key = (content_hash, model_id, fingerprint)
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._index_remove(key, old.phash)
                del self._entries[key]
            self._entries[key] = _Entry(
                value, phash if self.near_dup else None,
                self._clock() + self.ttl_s)
            if self.near_dup:
                self._index_add(key, phash)
            evicted = 0
            while len(self._entries) > self.capacity:
                victim, _ = next(iter(self._entries.items()))
                self._remove(victim)
                evicted += 1
            if evicted and self._on_evicted is not None:
                self._on_evicted(evicted)

    def purge_model(self, model_id: str, *,
                    keep_fingerprint: Optional[str] = None) -> int:
        """Drop every entry for ``model_id`` whose fingerprint differs
        from ``keep_fingerprint``; returns how many were dropped.

        Called after a reload commit: the bumped fingerprint already
        orphans old entries addressably, this reclaims their memory and
        lets the caller book them as invalidated.
        """
        with self._lock:
            doomed = [k for k in self._entries
                      if k[1] == model_id and k[2] != keep_fingerprint]
            for key in doomed:
                self._remove(key)
            return len(doomed)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bands.clear()
            return n

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def __len__(self) -> int:
        return self.size()


class SingleFlight:
    """In-flight coalescing: N concurrent requests for one key dispatch
    ONE inference and all N ride the result.

    The first caller for a key becomes the *leader* (``lead_or_follow``
    returns True) and must eventually ``pop`` the key — on resolution or
    on failing to enqueue — handing back every follower that attached in
    the meantime.  Followers attached after the pop simply elect a new
    leader; there is no window where a follower can be stranded, because
    attach and pop serialize on one lock.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._waiting: Dict[Hashable, List[Any]] = {}

    def lead_or_follow(self, key: Hashable, follower: Any) -> bool:
        """True → caller is the leader (``follower`` is NOT registered);
        False → ``follower`` was attached to the existing leader."""
        with self._lock:
            if key in self._waiting:
                self._waiting[key].append(follower)
                return False
            self._waiting[key] = []
            return True

    def pop(self, key: Hashable) -> List[Any]:
        """Detach and return all followers for ``key`` (leader's duty,
        exactly once per lead)."""
        with self._lock:
            return self._waiting.pop(key, [])

    def depth(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._waiting.values())
