"""Content-addressed verdict cache (ISSUE 17): the dedup tier.

At production scale the request distribution is Zipfian — the same
re-shared clip arrives over and over, and without this tier every copy
pays a full forward pass.  This package is the jax-free core shared by
all three consumers:

* the serving batcher's pre-dispatch probe (hit resolves a request
  without it ever entering a bucket; miss populates on score),
* the fleet router's optional edge probe (both data planes), and
* the backfill dedup pass over pack shards.

Keying is ``(content_hash, model_id, checkpoint_fingerprint)`` — the
content hash is taken over the *canonical uint8 canvas* (after
``params.prepare_canvas``) so byte-identical re-uploads at any
container/encoding collide, and the fingerprint is the engine's weight
identity so a hot reload or quantized swap can never serve a stale
verdict: the reload commit bumps the fingerprint atomically and old
entries are orphaned by construction.

jax-free by decree (``lint/manifest.py:JAX_FREE_MODULES``): the router
process and backfill book audits import this with no accelerator stack.
"""

from .content import (ahash64, clip_phash, content_hash, dhash64,
                      hamming64, tree_fingerprint)
from .store import SingleFlight, VerdictCache

__all__ = [
    "VerdictCache",
    "SingleFlight",
    "content_hash",
    "clip_phash",
    "dhash64",
    "ahash64",
    "hamming64",
    "tree_fingerprint",
]
