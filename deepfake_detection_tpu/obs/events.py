"""Structured JSONL event/metrics log for a training run.

One file per run directory (``telemetry.jsonl``), append-only, one JSON
object per line.  Two record types share a schema version:

* ``{"v": 1, "t": <unix s>, "type": "metrics", ...}`` — one per trainer
  drain cadence (the step-time breakdown, throughput, MFU, loss window;
  obs/telemetry.py emits these), and
* ``{"v": 1, "t": <unix s>, "type": "event", "event": <name>, ...}`` —
  one per lifecycle transition (run_start, resume, rewind, preempted,
  epoch_end, eval, profile_capture, run_end).

Resume coherence: a SIGTERM can land mid-``write`` and leave a torn last
line; reopening for append first truncates the file back to its last
complete record (``\\n``-terminated), so a killed + ``--auto-resume``d run
produces ONE parseable stream — no torn and no duplicate records (the
torn record, if any, described a drain window the resumed run re-reports).
``summary.csv`` keeps coexisting: the CSV stays the per-epoch artifact
plotting tools already read; the JSONL is the in-run, per-drain record.

jax-free on purpose (the module is imported by tools/obs_report.py, which
must stay as light as the other jax-free tools).
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

_logger = logging.getLogger(__name__)

__all__ = ["SCHEMA_VERSION", "EventLog", "read_records",
           "repair_torn_tail"]

#: bump when a record's field meaning changes; readers must check it
SCHEMA_VERSION = 1


def _repair_torn_tail(path: str) -> int:
    """Truncate a trailing partial line; returns bytes dropped (0 if clean).

    A record writer killed mid-``os.write`` leaves bytes with no final
    newline.  Scanning back to the last ``\\n`` (not json-validating every
    line) is enough: records are written atomically-per-line below, so the
    only corruption a kill can produce is exactly one torn tail.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb+") as f:
        f.seek(-1, io.SEEK_END)
        if f.read(1) == b"\n":
            return 0
        # walk back in chunks to the last newline
        pos = size
        chunk = 4096
        keep = 0
        while pos > 0:
            step = min(chunk, pos)
            f.seek(pos - step)
            buf = f.read(step)
            nl = buf.rfind(b"\n")
            if nl >= 0:
                keep = pos - step + nl + 1
                break
            pos -= step
        f.truncate(keep)
        dropped = size - keep
    _logger.warning("telemetry log %s had a torn tail (%d bytes dropped); "
                    "truncated to the last complete record", path, dropped)
    return dropped


#: public name for the torn-tail repair (the streaming session-durability
#: layer reopens per-stream verdict JSONL files with the same discipline)
repair_torn_tail = _repair_torn_tail


class EventLog:
    """Append-only JSONL writer with torn-tail repair on open.

    Thread-safe (the metrics HTTP thread and the train loop may both
    record); each record is serialized to one line and written with a
    single ``write`` + ``flush`` so a kill can tear at most the final
    line — which the next open repairs.
    """

    def __init__(self, path: str):
        self.path = path
        self.torn_bytes_dropped = _repair_torn_tail(path)
        self._lock = threading.Lock()
        self._f: Optional[Any] = open(path, "a", encoding="utf-8")
        self.records_written = 0

    # ------------------------------------------------------------------
    def write(self, record: Dict[str, Any]) -> None:
        rec = {"v": SCHEMA_VERSION, "t": round(time.time(), 3)}
        rec.update(record)
        line = json.dumps(_sanitize(rec), separators=(",", ":"),
                          allow_nan=False, default=_json_default) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._f.flush()
            self.records_written += 1

    def event(self, name: str, **fields: Any) -> None:
        self.write({"type": "event", "event": name, **fields})

    def metrics(self, **fields: Any) -> None:
        self.write({"type": "metrics", **fields})

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_default(o):
    """numpy scalars and other float-likes appear in metric dicts; a
    telemetry record must never crash the train loop over serialization."""
    try:
        f = float(o)
    except (TypeError, ValueError):
        return repr(o)
    return None if f != f or f in (float("inf"), float("-inf")) else f


def _sanitize(o):
    """Non-finite floats → null: the stream must stay STRICT JSON (jq,
    non-Python consumers) even when an eval loss goes NaN."""
    if isinstance(o, dict):
        return {k: _sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_sanitize(v) for v in o]
    if isinstance(o, float) and (o != o or o in (float("inf"),
                                                 float("-inf"))):
        return None
    return o


def iter_records(path: str, strict_version: bool = False
                 ) -> Iterator[Dict[str, Any]]:
    """Yield parsed records, skipping (with a warning) torn/corrupt lines."""
    with open(path, encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                _logger.warning("%s:%d unparseable record skipped", path, ln)
                continue
            if strict_version and rec.get("v") != SCHEMA_VERSION:
                _logger.warning("%s:%d schema v%r != %d skipped",
                                path, ln, rec.get("v"), SCHEMA_VERSION)
                continue
            yield rec


def read_records(path: str) -> List[Dict[str, Any]]:
    return list(iter_records(path))
