"""Trainer metrics endpoint: stdlib HTTP serving Prometheus text + health.

The training sibling of serving/http.py's ``GET /metrics``: a
``ThreadingHTTPServer`` on ``--metrics-port`` rendering the
:class:`~deepfake_detection_tpu.obs.telemetry.TrainTelemetry` registry
through the shared :mod:`..utils.prometheus` renderer.  A scrape costs a
registry snapshot on the HTTP thread — the train loop is never blocked
(registry mutations take the same short lock, microseconds).

Endpoints:

* ``GET /metrics`` — Prometheus text format (the full train catalog).
* ``GET /healthz`` — 200 while the process serves; body carries the
  current loop position gauge so ``curl`` alone answers "is it moving".
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Tuple

_logger = logging.getLogger(__name__)

__all__ = ["MetricsServer", "start_metrics_server"]


class MetricsServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr: Tuple[str, int], telemetry):
        super().__init__(addr, _Handler)
        self.telemetry = telemetry

    @property
    def port(self) -> int:
        return self.server_address[1]


class _Handler(BaseHTTPRequestHandler):
    server: MetricsServer               # typing aid

    def log_message(self, fmt, *args):  # BaseHTTP logs to stderr by default
        _logger.debug("%s " + fmt, self.address_string(), *args)

    def _respond(self, status: int, body: bytes,
                 content_type: str = "text/plain") -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:           # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            text = self.server.telemetry.render_prometheus()
            self._respond(200, text.encode(),
                          "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            snap = self.server.telemetry.snapshot()
            g = snap["gauges"]
            body = (f"ok epoch={g.get('epoch', 0):.0f} "
                    f"update={g.get('update', 0):.0f}\n")
            self._respond(200, body.encode())
        else:
            self._respond(404, f"no route {path!r}\n".encode())


def start_metrics_server(telemetry, host: str = "0.0.0.0",
                         port: int = 0) -> MetricsServer:
    """Bind, start serving on a daemon thread, return the server (its
    ``.port`` is the bound port — pass 0 for an ephemeral one in tests).
    Stop with ``server.shutdown(); server.server_close()``."""
    server = MetricsServer((host, port), telemetry)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.2},
                         name="dfd-train-metrics", daemon=True)
    t.start()
    _logger.info("trainer metrics endpoint on %s:%d (/metrics, /healthz)",
                 host, server.port)
    return server
