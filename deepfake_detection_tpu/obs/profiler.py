"""On-demand profiler capture for a running training job.

The ``--profile N`` flag traces the first steps of epoch 0 and is gone —
but "where did this step's milliseconds go" questions arrive mid-run, at
step 300k, on a job nobody wants to restart.  Two triggers start a
bounded ``jax.profiler.trace`` window on a LIVE run:

* ``SIGUSR2`` — single-host ergonomics: ``kill -USR2 <pid>``.
* ``touch <output_dir>/PROFILE`` — multi-host ergonomics: the file is
  visible to every rank on a shared filesystem, checked at the trainer's
  drain cadence (one ``stat`` per drain, nothing per step).

Both are **rank-0-gated**: on a shared filesystem, N ranks writing one
trace directory race each other (exactly the hazard the ``--profile``
window's gate documents) — rank 0 traces, the others note the request
and drop it.  Rank 0 also consumes (deletes) the trigger file so one
touch yields one capture, and each capture lands in its own
``profile/ondemand-<update>`` directory so successive captures never
overwrite.

The steady-state cost when idle is two attribute checks per step and one
``stat`` per drain; starting/stopping a window adds the same
``block_until_ready`` + ``stop_trace`` pair the ``--profile`` flag pays.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Optional

_logger = logging.getLogger(__name__)

__all__ = ["ProfilerCapture", "TRIGGER_FILENAME"]

TRIGGER_FILENAME = "PROFILE"


class ProfilerCapture:
    """Bounded on-demand trace windows over a running train loop.

    The trainer calls :meth:`poll` at its drain cadence (file trigger
    check) and :meth:`on_step` once per step (window start/stop
    management).  ``telemetry`` (optional TrainTelemetry) gets a
    ``profile_capture`` event per completed window.
    """

    def __init__(self, output_dir: str, num_steps: int = 20,
                 telemetry=None, signum: int = signal.SIGUSR2):
        self.output_dir = output_dir
        self.num_steps = max(1, int(num_steps))
        self.telemetry = telemetry
        self._signum = signum
        self._prev_handler = None
        self._installed = False
        # _want is written by the signal handler (main thread) and poll();
        # read per step.  bool writes are atomic under the GIL.
        self._want = False
        self.active = False
        self._stop_after = -1
        self._trace_dir = ""
        self.captures_total = 0
        self._lock = threading.Lock()

    # -- triggers ------------------------------------------------------
    def install(self) -> bool:
        """Install the SIGUSR2 handler; False outside the main thread
        (the file trigger still works)."""
        try:
            self._prev_handler = signal.signal(self._signum, self._handle)
        except ValueError:
            return False
        self._installed = True
        return True

    def uninstall(self) -> None:
        if self._installed:
            try:
                signal.signal(self._signum, self._prev_handler
                              or signal.SIG_DFL)
            except (ValueError, TypeError):
                pass
            self._installed = False

    def _handle(self, signum, frame) -> None:
        _logger.warning("signal %d: profiler capture requested "
                        "(next %d steps)", signum, self.num_steps)
        self._want = True

    @property
    def _trigger_path(self) -> str:
        return os.path.join(self.output_dir, TRIGGER_FILENAME)

    def poll(self) -> None:
        """Drain-cadence check of the file trigger (one stat)."""
        if self._want or self.active or not self.output_dir:
            return
        if os.path.exists(self._trigger_path):
            self._want = True
            _logger.warning("%s trigger found: profiler capture requested "
                            "(next %d steps)", self._trigger_path,
                            self.num_steps)

    # -- window management --------------------------------------------
    def on_step(self, step_index: int, sync_ref=None) -> None:
        """Once per train step, after the step dispatch.

        Starts a pending window (the trace then covers the NEXT
        ``num_steps`` dispatches); stops an active one once they have all
        been dispatched (``sync_ref`` — the latest step's loss array — is
        block_until_ready'd first so the trace covers real device
        execution, the --profile window's idiom).
        """
        if self.active and step_index >= self._stop_after:
            self.stop(sync_ref)
        if not self._want or self.active:
            return
        self._want = False
        import jax
        if jax.process_index() != 0:
            # rank-0 gate: trace side effects must not race on a shared
            # filesystem; non-zero ranks drop the request (the trigger
            # file is consumed by rank 0 below)
            return
        self._consume_trigger()
        self._trace_dir = os.path.join(self.output_dir, "profile",
                                       f"ondemand-{step_index}")
        try:
            jax.profiler.start_trace(self._trace_dir)
        except Exception as e:          # noqa: BLE001 — never kill the run
            _logger.warning("profiler capture failed to start: %r", e)
            return
        self.active = True
        self._stop_after = step_index + self.num_steps
        _logger.warning("profiler capture started at update %d -> %s "
                        "(%d steps)", step_index, self._trace_dir,
                        self.num_steps)

    def stop(self, sync_ref=None) -> None:
        if not self.active:
            return
        import jax
        try:
            if sync_ref is not None:
                jax.block_until_ready(sync_ref)
            jax.profiler.stop_trace()
        except Exception as e:          # noqa: BLE001
            _logger.warning("profiler capture failed to stop cleanly: %r", e)
        self.active = False
        with self._lock:
            self.captures_total += 1
        _logger.warning("profiler capture written to %s", self._trace_dir)
        if self.telemetry is not None:
            self.telemetry.event("profile_capture", trace_dir=self._trace_dir,
                                 num_steps=self.num_steps)

    def _consume_trigger(self) -> None:
        try:
            os.unlink(self._trigger_path)
        except OSError:
            pass

    def close(self) -> None:
        self.stop()
        self.uninstall()
