"""Per-step training telemetry: time breakdown, throughput, MFU, catalog.

The tracker rides the trainer's existing metric-drain cadence and adds
**zero device syncs**: every input it receives is a host float the trainer
already materialized (the buffered ``float(m["loss"])`` reads at drain),
or a ``time.monotonic`` delta around work the loop already does.  The
breakdown attributes a drain window's wall time to three places:

* **data wait** — the loop blocked on ``next(loader)`` (host pipeline
  starving the chip); the per-step ``data_time`` the trainer logs.
* **device wait** — the loop blocked materializing the buffered metric
  scalars at the drain boundary (the device still executing its step
  backlog).  Because metric reads are the ONLY host syncs in the loop,
  this is the async-dispatch measurement of "the chip is the bottleneck".
* **host time** — the remainder: dispatch, collate hand-off, Python.

The :class:`~deepfake_detection_tpu.data.loader.DeviceLoader` double-buffer
boundaries add two more counters (``input_*``): time blocked in
``next()`` on the host loader and time blocked in the slab-recycle
``block_until_ready`` (prologue/staging backpressure) — both are waits the
loader already performed; the tracker only timestamps them.

Throughput (img/s over the drain window) times the per-sample forward
FLOP count from ``tools/flops_breakdown.py`` (× 3 for fwd+bwd, the
standard training approximation) against the device's peak rate to give a
**live MFU gauge** — the in-run counterpart of bench.py's offline MFU row
and of the PERF.md §6 accept/revert criterion.

Rendering goes through the shared :mod:`..utils.prometheus` text renderer
(the serving subsystem's ``GET /metrics`` sibling); obs/server.py exposes
it on ``--metrics-port``.  Each drain also appends one ``metrics`` record
to the run's JSONL event log (obs/events.py).
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..utils.metrics import LatencyHistogram
from ..utils.prometheus import PromText

_logger = logging.getLogger(__name__)

__all__ = ["TrainTelemetry", "forward_flops_per_sample", "peak_flops",
           "loader_collector", "native_warp_collector",
           "resilience_collector"]

_PREFIX = "dfd_train"

#: step/data-wait histogram bounds: 1 ms .. 60 s (first-step compile tails
#: land in the top buckets; steady-state steps resolve at ms granularity)
_STEP_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# bf16 peak per chip by device_kind (bench.py's table; the MFU gauge and
# the offline bench rows must agree on the denominator)
_PEAK_FLOPS = {
    "TPU v2": 22.5e12, "TPU v3": 61.5e12 / 2, "TPU v4": 137.5e12 * 2,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 229.5e12 * 2,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
    "TPU v7": 2307e12,
}

_COUNTER_CATALOG = (
    ("steps_total", "Train steps dispatched"),
    ("samples_total", "Training samples consumed"),
    ("drains_total", "Metric drain boundaries (telemetry records)"),
    ("step_seconds_total", "Wall seconds spent in the train loop"),
    ("data_wait_seconds_total", "Seconds the loop blocked on next(loader)"),
    ("device_wait_seconds_total", "Seconds the drain blocked materializing "
     "buffered device scalars (device-bound time)"),
    ("nonfinite_steps_total", "Steps whose loss/grad-norm was non-finite"),
    ("guard_spike_steps_total", "Steps the anomaly guard flagged as loss "
     "spikes"),
    ("rewinds_total", "Guard rewinds to a recovery snapshot"),
    ("recovery_snapshots_total", "In-epoch recovery snapshots written"),
    ("preemptions_total", "Preemption stops honored at a step boundary"),
    ("profile_captures_total", "On-demand profiler trace windows captured"),
    ("watchdog_beats_total", "Stall-watchdog heartbeats received"),
    ("watchdog_near_misses_total", "Heartbeats older than 0.5x the "
     "watchdog timeout when they landed"),
    ("events_total", "Lifecycle events recorded to the JSONL log"),
)

_GAUGE_CATALOG = (
    ("up", "1 while the trainer's telemetry is live"),
    ("epoch", "Current epoch"),
    ("update", "Global update counter at the last drain"),
    ("loss", "Train loss, epoch-running average at the last drain (the "
     "trainer log line's avg — spikes show in nonfinite/spike counters)"),
    ("prec1", "Train top-1 precision, epoch-running average at the last "
     "drain"),
    ("learning_rate", "Current learning rate"),
    ("throughput_imgs_per_s", "Images/sec over the last drain window"),
    ("step_time_ms", "Mean step wall time over the last drain window"),
    ("data_wait_frac", "Fraction of the last window blocked on input"),
    ("device_wait_frac", "Fraction of the last window blocked on the "
     "device backlog"),
    ("host_frac", "Fraction of the last window in host-side dispatch"),
    ("mfu", "Live model FLOPs utilization (0 when peak rate unknown, "
     "e.g. CPU)"),
    ("model_fwd_gflops_per_sample", "Per-sample forward GFLOPs feeding "
     "the MFU gauge (tools/flops_breakdown.py)"),
    ("restart_count", "Restart-wrapper relaunches of this run "
     "(DFD_RESTART_COUNT)"),
    ("watchdog_beat_age_s", "Seconds since the last watchdog heartbeat"),
)


class TrainTelemetry:
    """One registry per training process.

    Hot-path contract: :meth:`on_step` and :meth:`on_drain` take host
    floats only and never touch a ``jax.Array`` — the overhead-guard test
    asserts a telemetry-on run performs exactly the device syncs a
    telemetry-off run does.
    """

    def __init__(self, event_log: Optional[Any] = None,
                 flops_per_sample: float = 0.0,
                 peak_flops: float = 0.0,
                 meta: Optional[Dict[str, Any]] = None):
        self.event_log = event_log
        self.flops_per_sample = float(flops_per_sample)
        self.peak = float(peak_flops)
        self.meta = dict(meta or {})
        self.profiler = None          # optional obs.profiler.ProfilerCapture
        self._lock = threading.RLock()
        self._c: "OrderedDict[str, float]" = OrderedDict()
        self._g: "OrderedDict[str, float]" = OrderedDict()
        self._help: Dict[str, str] = {}
        for name, help_ in _COUNTER_CATALOG:
            self._c[name] = 0.0
            self._help[name] = help_
        for name, help_ in _GAUGE_CATALOG:
            self._g[name] = 0.0
            self._help[name] = help_
        self._g["up"] = 1.0
        self._g["model_fwd_gflops_per_sample"] = round(
            self.flops_per_sample / 1e9, 3)
        self._g["restart_count"] = float(
            os.environ.get("DFD_RESTART_COUNT", 0) or 0)
        self.h_step = LatencyHistogram(_STEP_BOUNDS)
        self.h_data_wait = LatencyHistogram(_STEP_BOUNDS)
        self._collectors: List[Callable[[], Dict[str, Dict[str, float]]]] = []
        # drain-window accumulators (single-writer: the train loop).  The
        # window length is the SUM of per-step wall times, not a monotonic
        # anchor: per-step wall (trainer batch_time) already covers the
        # loop end-to-end including data wait and the drain block, so the
        # breakdown fractions are consistent by construction and the
        # tracker is a pure function of its inputs (testable without
        # sleeping).
        self._win_steps = 0
        self._win_samples = 0
        self._win_wall = 0.0
        self._win_data_wait = 0.0

    # -- registry ------------------------------------------------------
    def inc(self, name: str, n: float = 1.0) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0.0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._g[name] = value

    def register_collector(
            self, fn: Callable[[], Dict[str, Dict[str, float]]]) -> None:
        """``fn`` returns ``{"counters": {...}, "gauges": {...}}`` of
        already-monotonic totals / current values; called at every drain
        and render so names appear in the catalog from registration on."""
        self._collectors.append(fn)
        self._run_collectors()

    def _run_collectors(self) -> None:
        for fn in self._collectors:
            try:
                out = fn()
            except Exception as e:          # noqa: BLE001 — never kill a run
                _logger.warning("telemetry collector failed: %r", e)
                continue
            with self._lock:
                for k, v in out.get("counters", {}).items():
                    self._c[k] = float(v)
                for k, v in out.get("gauges", {}).items():
                    self._g[k] = float(v)

    # -- hot-loop hooks ------------------------------------------------
    def on_step(self, n_samples: int, data_wait_s: float,
                step_wall_s: float) -> None:
        """Once per loop iteration; host floats only."""
        self._win_steps += 1
        self._win_wall += step_wall_s
        self._win_samples += int(n_samples)
        self._win_data_wait += data_wait_s
        self.h_step.observe(step_wall_s)
        self.h_data_wait.observe(data_wait_s)
        with self._lock:
            self._c["steps_total"] += 1
            self._c["samples_total"] += n_samples
            self._c["step_seconds_total"] += step_wall_s
            self._c["data_wait_seconds_total"] += data_wait_s

    def on_drain(self, *, epoch: int, batch_idx: int, num_updates: int,
                 loss: float, prec1: float, lr: float,
                 drain_wait_s: float, nonfinite_steps: int = 0) -> None:
        """Once per drain boundary, AFTER the trainer materialized the
        buffered scalars (``drain_wait_s`` is how long that block took;
        ``nonfinite_steps`` is this window's bad-step count)."""
        wall = max(self._win_wall, 1e-9)
        steps, samples = self._win_steps, self._win_samples
        if steps == 0:
            return
        data_wait = self._win_data_wait
        imgs_per_s = samples / wall
        mfu = 0.0
        if self.peak > 0 and self.flops_per_sample > 0:
            mfu = imgs_per_s * self.flops_per_sample * 3.0 / self.peak
        with self._lock:
            self._c["drains_total"] += 1
            self._c["device_wait_seconds_total"] += drain_wait_s
            self._c["nonfinite_steps_total"] += max(int(nonfinite_steps), 0)
            g = self._g
            g["epoch"] = float(epoch)
            g["update"] = float(num_updates)
            g["loss"] = float(loss)
            g["prec1"] = float(prec1)
            g["learning_rate"] = float(lr)
            g["throughput_imgs_per_s"] = round(imgs_per_s, 3)
            g["step_time_ms"] = round(wall / steps * 1e3, 3)
            g["data_wait_frac"] = round(min(data_wait / wall, 1.0), 4)
            g["device_wait_frac"] = round(min(drain_wait_s / wall, 1.0), 4)
            g["host_frac"] = round(
                max(1.0 - (data_wait + drain_wait_s) / wall, 0.0), 4)
            g["mfu"] = round(mfu, 4)
        self._run_collectors()
        if self.event_log is not None:
            with self._lock:
                counters = dict(self._c)
                gauges = {k: v for k, v in self._g.items()
                          if k not in ("up",)}
            self.event_log.metrics(
                epoch=epoch, batch=batch_idx, update=num_updates,
                imgs_per_s=round(imgs_per_s, 3),
                step_ms=gauges["step_time_ms"],
                data_wait_frac=gauges["data_wait_frac"],
                device_wait_frac=gauges["device_wait_frac"],
                host_frac=gauges["host_frac"],
                loss=float(loss), prec1=float(prec1), lr=float(lr),
                mfu=gauges["mfu"], counters=counters)
        # reset the window
        self._win_steps = 0
        self._win_samples = 0
        self._win_wall = 0.0
        self._win_data_wait = 0.0

    # -- lifecycle -----------------------------------------------------
    def event(self, name: str, **fields: Any) -> None:
        self.inc("events_total")
        if name == "rewind":
            self.inc("rewinds_total")
        elif name == "preempted":
            self.inc("preemptions_total")
        elif name == "profile_capture":
            self.inc("profile_captures_total")
        if self.event_log is not None:
            self.event_log.event(name, **fields)

    def close(self) -> None:
        self.set_gauge("up", 0.0)
        if self.event_log is not None:
            self.event_log.close()

    # -- exposition ----------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """One consistent view of the whole registry."""
        self._run_collectors()
        with self._lock:
            return {"counters": dict(self._c), "gauges": dict(self._g)}

    def render_prometheus(self) -> str:
        snap = self.snapshot()
        doc = PromText(_PREFIX)
        for name, value in snap["counters"].items():
            doc.counter(name, self._help.get(name, name), _num(value))
        for name, value in snap["gauges"].items():
            doc.gauge(name, self._help.get(name, name), _num(value))
        doc.histogram("step_seconds", "Per-step wall time", self.h_step)
        doc.histogram("data_wait_seconds",
                      "Per-step input wait", self.h_data_wait)
        return doc.render()


def _num(v: float):
    """Integral values render without a trailing .0 (counter idiom)."""
    return int(v) if float(v).is_integer() else v


# ---------------------------------------------------------------------------
# MFU inputs
# ---------------------------------------------------------------------------

def peak_flops(device=None) -> float:
    """Per-chip bf16 peak for the MFU denominator; 0.0 when unknown (CPU —
    the gauge then reads 0 rather than a meaningless ratio)."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:               # noqa: BLE001 — backend-less callers
            return 0.0
    kind = getattr(device, "device_kind", "cpu")
    for k, v in _PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    for k, v in _PEAK_FLOPS.items():
        if k.lower() in kind.lower():
            return v
    return 0.0


def forward_flops_per_sample(model, variables, input_shape) -> float:
    """Per-sample forward FLOPs via tools/flops_breakdown.py's jaxpr walk.

    ``input_shape`` is the (1, H, W, C) shape the LOADER feeds the model
    (already pixel-shuffled under ``--stem-s2d``).  Returns 0.0 when the
    tools/ directory is not present (installed-package layout) or the walk
    fails — the MFU gauge then stays 0 instead of lying.
    """
    import importlib.util
    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tools")
    path = os.path.join(tools_dir, "flops_breakdown.py")
    if not os.path.isfile(path):
        return 0.0
    try:
        import jax.numpy as jnp
        spec = importlib.util.spec_from_file_location(
            "_dfd_flops_breakdown", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        x = jnp.zeros(tuple(input_shape), jnp.float32)
        buckets, _, _ = mod.analyze(model, variables, x,
                                    in_chans=int(input_shape[-1]))
        return float(sum(buckets.values()))
    except Exception as e:              # noqa: BLE001 — telemetry is optional
        _logger.warning("forward-FLOPs analysis failed (%r); "
                        "MFU gauge disabled", e)
        return 0.0


# ---------------------------------------------------------------------------
# Collectors: input pipeline, native warp, resilience
# ---------------------------------------------------------------------------

def loader_collector(device_loader, name: str = "train"):
    """Input-pipeline counters/gauges off a DeviceLoader and its host
    loader (thread or shm backend) — attribute reads only, no locking
    against the producer (floats are single-writer, torn reads impossible
    under the GIL)."""

    def collect() -> Dict[str, Dict[str, float]]:
        st = device_loader.stats
        c = {
            f"input_{name}_batches_total": st.batches,
            f"input_{name}_host_wait_seconds_total": st.host_wait_s,
            # with --augment-device on this block is ALSO where the
            # prologue's augment compute surfaces to the host (the only
            # wait on the prologue output): the per-drain breakdown's
            # attribution of "where the augment milliseconds live"
            f"input_{name}_stage_block_seconds_total": st.stage_block_s,
            # samples x host-chain stages (warp/blur/mixup-blend) elided
            # by device-side augmentation
            f"input_{name}_host_augment_stages_elided_total":
                getattr(st, "augment_elided", 0),
        }
        g: Dict[str, float] = {
            # 1 = the train augment renders on device (--augment-device
            # on), 0 = host chain — the /metrics-scraper pivot; the JSONL
            # log carries counters only, so tools/obs_report.py keys the
            # same fact off the elided-stages counter above
            f"input_{name}_augment_path_device":
                1.0 if getattr(device_loader, "augment_device", False)
                else 0.0,
        }
        host = device_loader.loader
        hstats = getattr(host, "stats", None)
        if hstats is not None:           # thread backend producer stats
            c[f"input_{name}_fetch_seconds_total"] = hstats.fetch_s
            c[f"input_{name}_backpressure_seconds_total"] = hstats.put_wait_s
        if hasattr(host, "ring_depth"):  # shm backend
            c[f"input_{name}_worker_respawns_total"] = host.respawn_count
            c[f"input_{name}_ring_stall_sweeps_total"] = getattr(
                host, "stall_sweeps", 0)
            c[f"input_{name}_ring_collect_wait_seconds_total"] = getattr(
                host, "collect_wait_s", 0.0)
            workers = [p for p in getattr(host, "_workers", [])
                       if p is not None]
            g[f"input_{name}_workers_alive"] = float(
                sum(1 for p in workers if p.is_alive())) if workers else 0.0
            depth = float(host.ring_depth)
            g[f"input_{name}_ring_occupancy"] = round(
                min(getattr(host, "inflight_batches", 0) / depth, 1.0), 4)
        return {"counters": c, "gauges": g}

    return collect


def native_warp_collector():
    """Fused-warp source-copy counters (data/native.py): elided = packed
    mmap views handed to the strided kernel with no ``ascontiguousarray``
    copy; copied = frames that still needed the contiguous staging copy."""

    def collect() -> Dict[str, Dict[str, float]]:
        from ..data import native
        stats = native.warp_copy_stats()
        return {"counters": {
            "input_warp_src_copies_elided_total": stats["elided"],
            "input_warp_src_copies_total": stats["copied"],
        }, "gauges": {}}

    return collect


def resilience_collector(resilience):
    """Fault-layer counters off a train.resilience.Resilience handle."""

    def collect() -> Dict[str, Dict[str, float]]:
        c: Dict[str, float] = {}
        g: Dict[str, float] = {}
        guard = resilience.guard
        if guard is not None:
            c["guard_spike_steps_total"] = guard.spike_total
        wd = resilience.watchdog
        if wd is not None:
            c["watchdog_beats_total"] = wd.beats_total
            c["watchdog_near_misses_total"] = wd.near_miss_total
            g["watchdog_beat_age_s"] = round(wd.beat_age(), 3)
        return {"counters": c, "gauges": g}

    return collect
