"""Training observability subsystem.

The cross-cutting layer every scaling PR reports through: a per-step
time-breakdown tracker riding the trainer's drain cadence with zero extra
device syncs (telemetry.py), a schema-versioned JSONL event/metrics log in
the run dir (events.py), an optional stdlib ``--metrics-port`` Prometheus
endpoint sharing the serving renderer (server.py + utils/prometheus.py),
and on-demand bounded profiler capture on a live job via SIGUSR2 or a
``PROFILE`` trigger file (profiler.py).

The jax-touching modules (telemetry pulls utils.metrics → jnp; profiler
traces) are imported LAZILY (PEP 562, the data/ package idiom):
tools/obs_report.py reads telemetry logs through ``events`` without
dragging jax into a reporting subprocess.
"""

from .events import SCHEMA_VERSION, EventLog, iter_records, read_records

# lazily-resolved (jax-importing) attributes: name -> submodule
_LAZY = {
    "TrainTelemetry": "telemetry", "forward_flops_per_sample": "telemetry",
    "loader_collector": "telemetry", "native_warp_collector": "telemetry",
    "peak_flops": "telemetry", "resilience_collector": "telemetry",
    "MetricsServer": "server", "start_metrics_server": "server",
    "ProfilerCapture": "profiler", "TRIGGER_FILENAME": "profiler",
}

__all__ = ["SCHEMA_VERSION", "EventLog", "iter_records", "read_records",
           *sorted(_LAZY)]


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        value = getattr(mod, name)
        globals()[name] = value        # cache: __getattr__ runs once per name
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
