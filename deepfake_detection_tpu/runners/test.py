"""Single-image inference runner.

Parity with ``/root/reference/dfd/runners/test.py``: load the flagship
checkpoint, preprocess each image (aspect-preserving resize + center pad to
600×600, normalize, replicate ×4 → 12 channels, :49-58), print the softmax
fake score (``scores[:, 0]``, :58-60).

Usage::

    python -m deepfake_detection_tpu.runners.test img1.png img2.jpg \
        [--model-path PATH] [--image-size 600]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from ..models import create_deepfake_model_v4, init_model
from ..models.helpers import load_checkpoint
from ..params import (image_max_height, img_num, make_score_fn,
                      normalize_concat, normalize_replicate, prepare_canvas)

__all__ = ["test_img", "preprocess", "preprocess_clip"]


def preprocess(img_file, size: int = image_max_height,
               num: int = img_num) -> np.ndarray:
    """file (path or file-like) → (1, H, W, 3*num) normalized float32
    (reference test.py:49-56).  The two halves live in ``params.py`` so the
    serving subsystem (serving/engine.py) reuses them verbatim: geometric
    canvas on host, photometrics replicated on device."""
    img = np.asarray(Image.open(img_file).convert("RGB"), np.uint8)
    return normalize_replicate(prepare_canvas(img, size), num)[None]


def preprocess_clip(img_files, size: int = image_max_height,
                    num: int = img_num) -> np.ndarray:
    """``num`` frame files → ONE (1, H, W, 3*num) temporal clip: each frame
    gets the geometric canvas, then the frames channel-concatenate
    (``params.normalize_concat``) instead of replicating one frame — the
    multi-frame wire the streaming windower and ``--clip`` mode score.
    ``num`` identical files reproduce :func:`preprocess` bit-for-bit."""
    canvases = [prepare_canvas(
        np.asarray(Image.open(f).convert("RGB"), np.uint8), size)
        for f in img_files]
    return normalize_concat(canvases, num)[None]


def test_img(model_path: Optional[str], img_files: Sequence[str],
             size: int = image_max_height, clip: bool = False,
             dtype: str = "f32") -> List[float]:
    """Score images one at a time (replicate ×img_num, reference parity),
    or — with ``clip=True`` — in groups of ``img_num`` distinct frames
    channel-concatenated into temporal clips (the streaming windower's
    layout; scores are bit-identical to the serving float32 wire).

    ``dtype`` applies the serving PTQ transform (``serving/quant.py``)
    to the loaded f32 weights before scoring — the same quantized tree
    and the same variables-as-argument program the engine serves, so
    this CLI is the parity harness's non-server oracle: bit-identical
    to the engine's float32 wire at f32, and within the measured
    SERVE_BENCH.md tolerance under bf16/int8."""
    assert all(os.path.isfile(f) for f in img_files), "file not exist!"
    if clip and len(img_files) % img_num:
        raise ValueError(f"--clip needs a multiple of img_num={img_num} "
                         f"images, got {len(img_files)}")
    print(f"To load model from {model_path}")
    model = create_deepfake_model_v4("efficientnet_deepfake_v4",
                                     num_classes=2, in_chans=12)
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, size, size, 12))
    if model_path and os.path.isdir(model_path):
        # sharded (--ckpt-sharded) training checkpoint directory; prefers
        # the EMA stream like the reference's released model_half
        from ..train.checkpoint import load_sharded_for_eval
        variables = load_sharded_for_eval(model_path, variables)
    elif model_path:
        variables = load_checkpoint(variables, model_path, strict=False)
    print("Model loaded!")
    if dtype not in ("f32", "float32"):
        from ..serving.quant import quant_summary, quantize_tree
        variables = quantize_tree(variables, dtype)
        print(f"Quantized weights to {dtype}: {quant_summary(variables)}")
    score_fn = make_score_fn(model, variables)
    scores_out: List[float] = []
    if clip:
        for i in range(0, len(img_files), img_num):
            group = list(img_files[i:i + img_num])
            scores = np.asarray(score_fn(jnp.asarray(
                preprocess_clip(group, size))))
            fake_score = float(scores[0, 0])                # P(fake)
            scores_out.append(fake_score)
            print(f"clip {group}'s fake score:{fake_score}")
        return scores_out
    for img_file in img_files:
        scores = np.asarray(score_fn(jnp.asarray(preprocess(img_file, size))))
        fake_score = float(scores[0, 0])                    # P(fake)
        scores_out.append(fake_score)
        print(f"{img_file}'s fake score:{fake_score}")
    return scores_out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="deepfake single-image inference")
    p.add_argument("images", nargs="*")
    p.add_argument("--model-path", default="")
    p.add_argument("--image-size", type=int, default=image_max_height)
    p.add_argument("--clip", action="store_true",
                   help=f"score groups of img_num={img_num} distinct "
                        f"frames as temporal clips instead of replicating "
                        f"each image")
    p.add_argument("--dtype", default="f32",
                   choices=["f32", "bf16", "int8"],
                   help="post-training quantization of the loaded f32 "
                        "weights (serving/quant.py): f32 = reference "
                        "parity, bf16/int8 = the engine's PTQ serving "
                        "modes (tools/quant_parity.py measures the drift)")
    args = p.parse_args(argv)
    if not args.images:
        print("Please input your images. e.g. python -m "
              "deepfake_detection_tpu.runners.test image1 image2")
        return
    test_img(args.model_path or None, args.images, size=args.image_size,
             clip=args.clip, dtype=args.dtype)


if __name__ == "__main__":
    main(sys.argv[1:])
