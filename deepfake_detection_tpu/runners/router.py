"""Fleet router entrypoint: N shared-nothing replicas behind one
routing tier.

Where ``runners/serve.py`` is ONE process (and therefore one GIL's
worth of HTTP+dispatch host work, the measured ~200–250 req/s ceiling on
this class of box), this runner fronts a *fleet*: stateless ``/score``
load-balances by least queue depth, ``/streams/*`` sessions pin to
replicas by consistent hash, health is scraped off each replica's
``/readyz`` + ``/metrics``, and draining a replica live-migrates its
stream sessions.  The router process itself NEVER imports jax — every
replica is its own process with its own engine.

Usage::

    # attach to running replicas
    python -m deepfake_detection_tpu.runners.router \
        --replicas 127.0.0.1:8377,127.0.0.1:8379 [--port 8380]

    # or spawn a local fleet of 4 serve children
    python -m deepfake_detection_tpu.runners.router --spawn 4 \
        --replica-args "--model vit_tiny_patch16_224 --image-size 32 \
                        --single-thread-xla"

    curl -s -X POST --data-binary @face.jpg -H 'Content-Type: image/jpeg' \
        http://127.0.0.1:8380/score
    curl -s http://127.0.0.1:8380/replicas
    curl -s -X POST http://127.0.0.1:8380/replicas/127.0.0.1:8377/drain
"""

from __future__ import annotations

import logging
import signal
import sys
import threading
from typing import List, Optional, Sequence

_logger = logging.getLogger(__name__)

__all__ = ["build_router", "main"]


def build_router(cfg):
    """RouterConfig → (RouterServer, spawned ReplicaProcess list).

    The server is not yet started; spawned children are launched but not
    awaited (the health scraper's readiness view is the wait)."""
    from ..fleet.controller import (HealthScraper, ReplicaProcess,
                                    free_port)
    from ..fleet.metrics import RouterMetrics
    from ..fleet.registry import Registry
    from ..fleet.router import make_router_server

    registry = Registry(vnodes=cfg.virtual_nodes)
    spawned: List[ReplicaProcess] = []
    for _ in range(int(cfg.spawn)):
        child = ReplicaProcess(cfg.spawn_runner, free_port(),
                               cfg.replica_args)
        spawned.append(child)
        r = registry.add(child.netloc, process=child)
        r.warming = True              # cold start, not down
    for url in cfg.replica_urls():
        registry.add(url)
    metrics = RouterMetrics()
    metrics.replicas_spawned_total.inc(len(spawned))
    scraper = HealthScraper(registry, metrics,
                            interval_s=cfg.scrape_interval_s,
                            fail_after=cfg.health_fail_after,
                            timeout_s=cfg.scrape_timeout_s,
                            spawn_grace_s=cfg.spawn_grace_s)
    server = make_router_server(
        cfg.host, cfg.port, registry, metrics, scraper,
        data_plane=cfg.data_plane,
        relay_workers=cfg.relay_workers,
        route_retries=cfg.route_retries,
        upstream_timeout_s=cfg.upstream_timeout_s,
        shed_retry_after_s=cfg.shed_retry_after_s,
        retry_jitter_s=cfg.retry_jitter_s,
        migrate_timeout_s=cfg.migrate_timeout_s,
        idle_timeout_s=cfg.idle_timeout_s,
        header_timeout_s=cfg.header_timeout_s,
        max_buffer_bytes=cfg.max_buffer_bytes,
        edge_cache_entries=cfg.edge_cache_entries,
        edge_cache_ttl_s=cfg.edge_cache_ttl_s)
    if int(cfg.edge_cache_entries) > 0:
        _logger.info("edge verdict cache: %d entries, ttl %.1fs "
                     "(keyed on the fleet weights-epoch)",
                     cfg.edge_cache_entries, cfg.edge_cache_ttl_s)
    if cfg.autoscale:
        from ..fleet.autoscaler import (Autoscaler, BackfillTenant,
                                        PolicyKnobs)
        tenant = None
        if cfg.backfill_tenant:
            tenant = BackfillTenant(
                manifest=cfg.backfill_tenant, out=cfg.backfill_out,
                extra_args=cfg.backfill_args,
                max_workers=cfg.backfill_max_workers, metrics=metrics,
                yield_timeout_s=cfg.backfill_yield_timeout_s)
        server.autoscaler = Autoscaler(
            registry, metrics, scraper,
            knobs=PolicyKnobs(
                slo_p99_ms=cfg.slo_p99_ms,
                min_replicas=cfg.min_replicas,
                max_replicas=cfg.max_replicas,
                up_samples=cfg.autoscale_up_samples,
                down_samples=cfg.autoscale_down_samples,
                up_cooldown_s=cfg.autoscale_up_cooldown_s,
                down_cooldown_s=cfg.autoscale_down_cooldown_s,
                shed_high=cfg.autoscale_shed_high,
                depth_high=cfg.autoscale_depth_high,
                depth_low=cfg.autoscale_depth_low),
            spawn_runner=cfg.spawn_runner,
            replica_args=cfg.replica_args,
            interval_s=cfg.autoscale_interval_s,
            tenant=tenant, trace_path=cfg.autoscale_trace,
            migrate_timeout_s=cfg.migrate_timeout_s,
            settle_timeout_s=cfg.settle_timeout_s,
            standby_replicas=cfg.standby_replicas)
        _logger.info(
            "autoscaler: slo p99 %.0fms, %d..%d replicas%s%s%s",
            cfg.slo_p99_ms, cfg.min_replicas, cfg.max_replicas,
            f", {cfg.standby_replicas} warm standby(s)"
            if int(cfg.standby_replicas) > 0 else "",
            f", backfill tenant on {cfg.backfill_tenant}"
            if tenant is not None else "",
            f", trace -> {cfg.autoscale_trace}"
            if cfg.autoscale_trace else "")
    return server, spawned


def main(argv: Optional[Sequence[str]] = None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    # the serving runner's GIL-switch tuning: many proxy threads on few
    # cores convoy tail latency at the default 5 ms interval
    sys.setswitchinterval(0.002)
    from ..config import RouterConfig
    cfg = RouterConfig.from_args(argv)
    server, spawned = build_router(cfg)
    server.scraper.start()
    if server.autoscaler is not None:
        server.autoscaler.start()

    stop = threading.Event()

    def _sig(signum, frame):
        _logger.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    host, port = server.server_address[:2]
    _logger.info(
        "routing on http://%s:%d [%s data plane%s] over %d replica(s): "
        "%s (POST /score, /streams/*, GET /healthz /readyz /metrics "
        "/replicas, POST /replicas/<id>/drain)", host, port,
        cfg.data_plane,
        (f", {cfg.relay_workers} shards"
         if cfg.data_plane == "evloop" and int(cfg.relay_workers) > 1
         else ""),
        len(server.registry.ids()), ", ".join(server.registry.ids()))
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True)
    t.start()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        server.shutdown()
        if server.autoscaler is not None:
            # stops the control loop AND yields the backfill tenant's
            # workers (SIGTERM -> exit-75 lease release)
            server.autoscaler.stop()
        server.scraper.stop()
        # the autoscaler may have spawned children past the launch set —
        # the registry's process-attached replicas are the whole truth
        children = {id(c): c for c in spawned}
        for r in server.registry.all():
            if r.process is not None:
                children.setdefault(id(r.process), r.process)
        if cfg.drain_on_exit and children:
            from ..fleet.migrate import drain_replica
            for child in children.values():
                try:
                    drain_replica(server.registry, server.metrics,
                                  child.netloc,
                                  timeout_s=cfg.migrate_timeout_s)
                except Exception:                  # noqa: BLE001
                    _logger.exception("drain of %s on exit failed",
                                      child.netloc)
        for child in children.values():
            child.stop()
        server.server_close()
        _logger.info("bye")


if __name__ == "__main__":
    main(sys.argv[1:])
