"""Long-lived inference server entrypoint.

Where ``runners/test.py`` is the reference's one-shot CLI (pay interpreter
start + model build + XLA compile per invocation), this runner keeps one
process alive: params resident on device, every batch bucket AOT-compiled
before the first request, arrival-order traffic coalesced into those
buckets, overload shed with 429, and weights hot-swappable from a watched
checkpoint dir — the serving half of the ROADMAP's "heavy traffic" north
star, chip-independent (runs on CPU JAX identically).

Usage::

    python -m deepfake_detection_tpu.runners.serve \
        --model-path model.msgpack [--port 8377] [--buckets 1,4,16,64] \
        [--batch-deadline-ms 5] [--max-queue 128] [--reload-dir ckpts/]

    curl -s -X POST --data-binary @face.jpg -H 'Content-Type: image/jpeg' \
        http://127.0.0.1:8377/score

Scores are exactly ``runners/test.py``'s: same model build, same
checkpoint load paths, same preprocess split host/device
(tests/test_serving.py pins server == CLI bit-for-bit).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time
from typing import Optional, Sequence

_logger = logging.getLogger(__name__)

__all__ = ["build_engine", "build_server", "main"]


def _skeleton_variables(model, image_size, in_chans):
    """Zero-compile variable skeleton: ``jax.eval_shape`` traces the
    init without building or running an executable, and host zeros fill
    the shapes.  ONLY valid under a strict (complete) checkpoint load,
    which overwrites every leaf — see ``_load_model_variables``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _init(rng, dummy):
        return model.init({"params": rng, "dropout": rng}, dummy,
                          training=False)

    shapes = jax.eval_shape(
        _init, jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((1, image_size, image_size, in_chans),
                             jnp.float32))
    return jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), shapes)


def _load_model_variables(model, model_path, *, image_size, in_chans,
                          use_ema, name):
    """Checkpoint load for one model-table entry, mirroring
    ``runners/test.py::test_img``."""
    import jax

    from ..models import init_model
    from ..models.helpers import load_checkpoint

    if model_path and os.path.isfile(model_path):
        # warm-start fast path (ISSUE 19): a checkpoint that strict-load
        # accepts overwrites EVERY leaf, so the init values are dead
        # weight — eval_shape skips the init jit (the bulk of the
        # params_load stage wall and its backend compile).  Any strict
        # failure (missing keys, shape drift) falls back to the real
        # init + lenient merge below, loudly.
        try:
            return load_checkpoint(
                _skeleton_variables(model, image_size, in_chans),
                model_path, use_ema=use_ema, strict=True)
        except Exception as e:                     # noqa: BLE001
            _logger.warning(
                "skeleton params load of %r failed (%s) — paying the "
                "full init for the lenient merge", name, e)
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, image_size, image_size, in_chans))
    if model_path and os.path.isdir(model_path):
        from ..train.checkpoint import load_sharded_for_eval
        variables = load_sharded_for_eval(model_path, variables)
    elif model_path:
        variables = load_checkpoint(variables, model_path,
                                    use_ema=use_ema, strict=False)
    else:
        _logger.warning("no checkpoint for model %r: serving a seed-0 "
                        "random init (bench/demo mode)", name)
    return variables


def build_engine(cfg):
    """Model table → warmed engine + micro-batcher + metrics — the device
    half every front end shares (``runners/serve.py``'s single-request
    HTTP server and ``runners/stream.py``'s streaming pipeline both sit
    on exactly this stack).  The primary --model is the flagship entry;
    every --models spec adds one more, all AOT-warmed before ready."""
    t_entry = time.monotonic()
    from ..models import create_model          # pays the jax import
    from ..serving.batcher import MicroBatcher
    from ..serving.engine import InferenceEngine
    from ..serving.metrics import (ServingMetrics,
                                   install_backend_compile_listener)

    # the probe must see EVERY compile this process pays — including
    # the params-load init jit — so the warm path's zero-backend-compile
    # contract is checked against the whole start, not just the engine
    install_backend_compile_listener()

    if cfg.compile_cache_dir:
        # jax's persistent compilation cache: the fallback tier under
        # the AOT executable store — must be configured before the first
        # compile (PERF.md §9; size/time floors dropped so CPU-sized
        # serving programs actually persist)
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(cfg.compile_cache_dir))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    t_import = time.monotonic()
    _logger.info("building %s (in_chans=%d, canvas %d², dtype=%s)",
                 cfg.model, cfg.in_chans, cfg.image_size, cfg.dtype)
    model = create_model(cfg.model, num_classes=cfg.num_classes,
                         in_chans=cfg.in_chans)
    variables = _load_model_variables(
        model, cfg.model_path, image_size=cfg.image_size,
        in_chans=cfg.in_chans, use_ema=cfg.use_ema, name=cfg.model)
    metrics = ServingMetrics(throughput_window_s=cfg.throughput_window_s)
    warmstart = None
    if cfg.warmstart_dir:
        from ..serving.warmstart import ExecutableStore
        warmstart = ExecutableStore(cfg.warmstart_dir)
        _logger.info("warm-start executable store: %s", warmstart.root)
    engine = InferenceEngine(
        model, variables, image_size=cfg.image_size, img_num=cfg.img_num,
        buckets=cfg.buckets, metrics=metrics, wire=cfg.wire,
        multi_frame=not cfg.single_frame_only,
        dtype=cfg.dtype, model_id=cfg.model, warmup=False,
        watchdog_timeout_s=cfg.watchdog_timeout_s,
        breaker_threshold=cfg.breaker_threshold,
        breaker_open_s=cfg.breaker_open_s,
        reload_drift_tol=cfg.reload_drift_tol,
        retry_jitter_s=cfg.retry_jitter_s,
        warmstart=warmstart,
        warm_priority=cfg.warm_priority_buckets() or None,
        warm_parallel=cfg.warm_parallel)
    specs = cfg.model_specs()
    for spec in specs:
        in_chans = 3 * spec["img_num"]
        _logger.info("adding model %r: %s (in_chans=%d, canvas %d², "
                     "dtype=%s)", spec["id"], spec["family"], in_chans,
                     spec["size"], spec["dtype"])
        extra = create_model(spec["family"], num_classes=cfg.num_classes,
                             in_chans=in_chans)
        extra_vars = _load_model_variables(
            extra, spec["path"], image_size=spec["size"],
            in_chans=in_chans, use_ema=cfg.use_ema, name=spec["id"])
        engine.add_model(spec["id"], extra, extra_vars,
                         image_size=spec["size"], img_num=spec["img_num"],
                         dtype=spec["dtype"])
    # cold-start stage walls up to here (the engine stamps compile/warm
    # inside warmup; main() stamps spawn/ready around the whole build)
    t_params = time.monotonic()
    metrics.warmup_seconds["import"] = t_import - t_entry
    metrics.warmup_seconds["params_load"] = t_params - t_import
    _logger.info("AOT-warming buckets %s × %d model(s)%s ...",
                 list(cfg.buckets), 1 + len(specs),
                 " (staged)" if cfg.warm_staged else "")
    engine.warmup(staged=cfg.warm_staged)
    if engine.chaos.active:
        _logger.warning("DFD_CHAOS active: %s", sorted(engine.chaos.points))
    cache = None
    if int(cfg.cache_entries) > 0:
        from ..cache import VerdictCache
        cache = VerdictCache(cfg.cache_entries, cfg.cache_ttl_s,
                             near_dup=cfg.cache_near_dup,
                             near_radius=cfg.cache_near_radius,
                             on_expired=metrics.cache_expired_total.inc,
                             on_evicted=metrics.cache_evicted_total.inc)
        # engine.start() hands the cache + fingerprint resolver to the
        # batcher; holding it on the engine also lets a reload commit
        # purge (and count) the entries its fingerprint bump orphaned
        engine.verdict_cache = cache
        _logger.info("verdict cache: %d entries, ttl %.0fs%s",
                     cfg.cache_entries, cfg.cache_ttl_s,
                     (f", near-dup radius {cfg.cache_near_radius}"
                      if cfg.cache_near_dup else ""))
    batcher = MicroBatcher(max_batch=cfg.max_batch_size,
                           deadline_ms=cfg.batch_deadline_ms,
                           max_queue=cfg.max_queue, metrics=metrics,
                           retry_jitter_s=cfg.retry_jitter_s,
                           cache=cache)
    if cfg.reload_dir:
        engine.start_reload_watcher(cfg.reload_dir,
                                    interval_s=cfg.reload_interval_s,
                                    use_ema=cfg.use_ema)
        _logger.info("hot-reload watcher on %s (every %.1fs)",
                     cfg.reload_dir, cfg.reload_interval_s)
    for spec in specs:
        if spec["reload"]:
            engine.start_reload_watcher(spec["reload"],
                                        interval_s=cfg.reload_interval_s,
                                        use_ema=cfg.use_ema,
                                        model_id=spec["id"])
            _logger.info("hot-reload watcher for model %r on %s",
                         spec["id"], spec["reload"])
    return engine, batcher, metrics


def build_server(cfg):
    """Wire model table → engine → batcher → (optional cascade) → HTTP
    server; returns the (not yet started) :class:`ServingServer` with
    engine/batcher attached."""
    from ..serving.http import make_server

    engine, batcher, metrics = build_engine(cfg)
    cascade = None
    if cfg.cascade:
        from ..serving.cascade import CascadeRouter
        cascade = CascadeRouter(
            batcher, metrics, student_id=cfg.cascade,
            flagship_id=engine.default_model_id,
            low=cfg.cascade_low, high=cfg.cascade_high,
            timeout_s=cfg.request_timeout_ms / 1000.0)
        _logger.info("cascade: student %r triages, suspect band "
                     "[%.3f, %.3f] escalates to %r", cfg.cascade,
                     cfg.cascade_low, cfg.cascade_high,
                     engine.default_model_id)
    return make_server(cfg.host, cfg.port, engine, batcher, metrics,
                       request_timeout_s=cfg.request_timeout_ms / 1000.0,
                       cascade=cascade)


def main(argv: Optional[Sequence[str]] = None) -> None:
    t_main = time.time()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    # many handler threads + the engine share few cores; the default 5 ms
    # GIL switch interval convoys tail latency badly under load
    sys.setswitchinterval(0.002)
    from ..config import ServeConfig
    cfg = ServeConfig.from_args(argv)
    if cfg.single_thread_xla:
        # must land before the first jax import (build_server's) initializes
        # the backend; see ServeConfig.single_thread_xla
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_cpu_multi_thread_eigen" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_cpu_multi_thread_eigen=false").strip()
    server = build_server(cfg)
    # spawn/ready stage walls: a parent (fleet controller, bench) stamps
    # DFD_SPAWN_T at fork so the breakdown starts at the true spawn; a
    # bare launch starts at main() entry (spawn stage reads 0)
    try:
        spawn_t = float(os.environ.get("DFD_SPAWN_T", "") or t_main)
    except ValueError:
        spawn_t = t_main
    m = server.engine.metrics
    m.warmup_seconds["spawn"] = max(0.0, t_main - spawn_t)
    m.warmup_seconds["ready"] = max(0.0, time.time() - spawn_t)
    server.engine.start(server.batcher)

    stop = threading.Event()

    def _sig(signum, frame):
        _logger.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    host, port = server.server_address[:2]
    _logger.info("serving on http://%s:%d (POST /score, GET /healthz "
                 "/readyz /metrics)", host, port)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True)
    t.start()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        server.shutdown()
        server.engine.stop()
        server.batcher.close()
        server.server_close()
        _logger.info("bye")


if __name__ == "__main__":
    main(sys.argv[1:])
