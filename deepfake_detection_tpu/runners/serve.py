"""Long-lived inference server entrypoint.

Where ``runners/test.py`` is the reference's one-shot CLI (pay interpreter
start + model build + XLA compile per invocation), this runner keeps one
process alive: params resident on device, every batch bucket AOT-compiled
before the first request, arrival-order traffic coalesced into those
buckets, overload shed with 429, and weights hot-swappable from a watched
checkpoint dir — the serving half of the ROADMAP's "heavy traffic" north
star, chip-independent (runs on CPU JAX identically).

Usage::

    python -m deepfake_detection_tpu.runners.serve \
        --model-path model.msgpack [--port 8377] [--buckets 1,4,16,64] \
        [--batch-deadline-ms 5] [--max-queue 128] [--reload-dir ckpts/]

    curl -s -X POST --data-binary @face.jpg -H 'Content-Type: image/jpeg' \
        http://127.0.0.1:8377/score

Scores are exactly ``runners/test.py``'s: same model build, same
checkpoint load paths, same preprocess split host/device
(tests/test_serving.py pins server == CLI bit-for-bit).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
from typing import Optional, Sequence

_logger = logging.getLogger(__name__)

__all__ = ["build_engine", "build_server", "main"]


def _load_variables(model, cfg):
    """Checkpoint load, mirroring ``runners/test.py::test_img``."""
    import jax

    from ..models import init_model
    from ..models.helpers import load_checkpoint

    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, cfg.image_size, cfg.image_size, cfg.in_chans))
    if cfg.model_path and os.path.isdir(cfg.model_path):
        from ..train.checkpoint import load_sharded_for_eval
        variables = load_sharded_for_eval(cfg.model_path, variables)
    elif cfg.model_path:
        variables = load_checkpoint(variables, cfg.model_path,
                                    use_ema=cfg.use_ema, strict=False)
    else:
        _logger.warning("no --model-path: serving a seed-0 random init "
                        "(bench/demo mode)")
    return variables


def build_engine(cfg):
    """Model → warmed engine + micro-batcher + metrics — the device half
    every front end shares (``runners/serve.py``'s single-request HTTP
    server and ``runners/stream.py``'s streaming pipeline both sit on
    exactly this stack)."""
    from ..models import create_model
    from ..serving.batcher import MicroBatcher
    from ..serving.engine import InferenceEngine
    from ..serving.metrics import ServingMetrics

    _logger.info("building %s (in_chans=%d, canvas %d²)", cfg.model,
                 cfg.in_chans, cfg.image_size)
    model = create_model(cfg.model, num_classes=cfg.num_classes,
                         in_chans=cfg.in_chans)
    variables = _load_variables(model, cfg)
    metrics = ServingMetrics(throughput_window_s=cfg.throughput_window_s)
    _logger.info("AOT-warming buckets %s ...", list(cfg.buckets))
    engine = InferenceEngine(
        model, variables, image_size=cfg.image_size, img_num=cfg.img_num,
        buckets=cfg.buckets, metrics=metrics, wire=cfg.wire,
        multi_frame=not cfg.single_frame_only,
        watchdog_timeout_s=cfg.watchdog_timeout_s,
        breaker_threshold=cfg.breaker_threshold,
        breaker_open_s=cfg.breaker_open_s,
        reload_drift_tol=cfg.reload_drift_tol,
        retry_jitter_s=cfg.retry_jitter_s)
    if engine.chaos.active:
        _logger.warning("DFD_CHAOS active: %s", sorted(engine.chaos.points))
    batcher = MicroBatcher(max_batch=cfg.max_batch_size,
                           deadline_ms=cfg.batch_deadline_ms,
                           max_queue=cfg.max_queue, metrics=metrics,
                           retry_jitter_s=cfg.retry_jitter_s)
    if cfg.reload_dir:
        engine.start_reload_watcher(cfg.reload_dir,
                                    interval_s=cfg.reload_interval_s,
                                    use_ema=cfg.use_ema)
        _logger.info("hot-reload watcher on %s (every %.1fs)",
                     cfg.reload_dir, cfg.reload_interval_s)
    return engine, batcher, metrics


def build_server(cfg):
    """Wire model → engine → batcher → HTTP server; returns the (not yet
    started) :class:`ServingServer` with engine/batcher attached."""
    from ..serving.http import make_server

    engine, batcher, metrics = build_engine(cfg)
    return make_server(cfg.host, cfg.port, engine, batcher, metrics,
                       request_timeout_s=cfg.request_timeout_ms / 1000.0)


def main(argv: Optional[Sequence[str]] = None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    # many handler threads + the engine share few cores; the default 5 ms
    # GIL switch interval convoys tail latency badly under load
    sys.setswitchinterval(0.002)
    from ..config import ServeConfig
    cfg = ServeConfig.from_args(argv)
    if cfg.single_thread_xla:
        # must land before the first jax import (build_server's) initializes
        # the backend; see ServeConfig.single_thread_xla
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_cpu_multi_thread_eigen" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_cpu_multi_thread_eigen=false").strip()
    server = build_server(cfg)
    server.engine.start(server.batcher)

    stop = threading.Event()

    def _sig(signum, frame):
        _logger.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    host, port = server.server_address[:2]
    _logger.info("serving on http://%s:%d (POST /score, GET /healthz "
                 "/readyz /metrics)", host, port)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True)
    t.start()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        server.shutdown()
        server.engine.stop()
        server.batcher.close()
        server.server_close()
        _logger.info("bye")


if __name__ == "__main__":
    main(sys.argv[1:])
