"""Corpus-scale offline backfill runner: saturation-first scoring.

Where ``runners/serve.py`` optimizes request latency (micro-batch
deadlines, sheds, per-request books) and ``runners/stream.py`` optimizes
stream latency, this runner optimizes ONE thing: clips/s over an
archived corpus.  There is no HTTP, no batcher deadline, and no
per-request bookkeeping in the hot loop — a leased manifest shard
(``deepfake_detection_tpu/backfill``) is driven through a deadline-free
pipeline at full fixed batch:

    mmap/decode (thread pool, overlapped)  →  slab memcpy  →
    uint8 wire + fused normalize inside ONE AOT-compiled program
    (optional ``--stem-s2d`` pixel shuffle folded in)  →
    batch-sharded inference on the unified ('batch','model') mesh  →
    per-shard ``dfd.backfill.verdict.v1`` JSONL

with double-buffered staging (slab k+1 assembles and dispatches while
batch k executes — the DeviceLoader / serving-engine idiom) and zero
steady-state recompiles (one bucket, compiled once, asserted through
the backend-compile probe serving/metrics.py installs).

Resume/books contract: workers lease shards atomically, heartbeat while
scoring, and commit each shard's verdicts with an atomic done marker —
SIGTERM exits 75 at a batch boundary (the train/resilience.py restart
contract) and a relaunch resumes at shard granularity; a dead host's
lease expires by mtime and its partially written shard is re-leased,
torn tail repaired, surviving records kept.  At corpus completion the
books must balance EXACTLY: ``manifest clips == scored + failed +
skipped_dup``, no clip twice, none missing — imbalance is exit 1 with
the discrepancies named, never a summary that rounds them away.

``--dedup`` (packed source only) runs a content-hash pass over the pack
slabs before scoring: a clip whose canonical pixel bytes already occur
earlier in the manifest never enters a device batch — it books a
``skipped_dup`` verdict row pointing at the canonical clip (the same
content addressing the serving verdict cache uses, ``cache/content``).
Archival corpora are full of re-encoded reposts; paying inference per
COPY instead of per CONTENT is the whole point of the cache tier.

Usage::

    python tools/make_lists.py /data/frames --manifest corpus.json \
        --shard-clips 256 [--packed /ssd/pack]
    python -m deepfake_detection_tpu.runners.backfill \
        --manifest corpus.json --data-packed /ssd/pack --out run/ \
        --model-path model.msgpack --batch-size 64
    # more workers = more hosts/processes pointing at the same run dir
"""

from __future__ import annotations

import logging
import os
import queue
import signal
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

__all__ = ["run_backfill", "main", "EXIT_PREEMPTED"]

EXIT_PREEMPTED = 75       # keep in sync with train/resilience.py


class _LeaseLost(RuntimeError):
    """Our shard lease expired and was legitimately stolen while we
    were stalled: the stealer's books win; ours must stop writing."""


def _load_variables(model, cfg, shape):
    """Checkpoint load, mirroring ``runners/serve.py``."""
    import jax

    from ..models import init_model
    from ..models.helpers import load_checkpoint

    variables = init_model(model, jax.random.PRNGKey(0), shape)
    if cfg.model_path and os.path.isdir(cfg.model_path):
        from ..train.checkpoint import load_sharded_for_eval
        variables = load_sharded_for_eval(cfg.model_path, variables)
    elif cfg.model_path:
        variables = load_checkpoint(variables, cfg.model_path,
                                    use_ema=cfg.use_ema, strict=False)
    else:
        _logger.warning("no --model-path: scoring with a seed-0 random "
                        "init (bench/smoke mode)")
    return variables


class _Pipeline:
    """The compiled fixed-bucket score path + its double-buffer state."""

    def __init__(self, cfg, frames: int, hw: Tuple[int, int]):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..params import img_mean, img_std
        from ..parallel.mesh import make_train_mesh
        from ..parallel.sharding import batch_sharding, replicated_sharding

        self.batch = int(cfg.batch_size)
        self.frames = int(frames)
        self.hw = hw
        self.chans = 3 * self.frames
        self.mesh = make_train_mesh()
        dp = self.mesh.shape["batch"]
        if self.batch % dp:
            raise ValueError(
                f"--batch-size {self.batch} does not divide the mesh's "
                f"batch axis ({dp} devices) — the fixed bucket must "
                f"shard evenly")
        self._rep = replicated_sharding(self.mesh)
        self._bsh = batch_sharding(self.mesh)

        from ..models import create_model
        kwargs: Dict[str, Any] = {}
        if cfg.stem_s2d:
            kwargs["stem_s2d"] = True
        model = create_model(cfg.model, num_classes=cfg.num_classes,
                             in_chans=self.chans, **kwargs)
        variables = _load_variables(
            model, cfg, (1, hw[0], hw[1], self.chans))
        self.variables = jax.device_put(variables, self._rep)
        # tiled mean/std ride the call as ARGUMENTS (serving-engine
        # idiom: a constant divisor would strength-reduce to a
        # reciprocal multiply, drifting from the host arithmetic)
        self._mean = jax.device_put(
            jnp.asarray(np.tile(img_mean, self.frames)), self._rep)
        self._std = jax.device_put(
            jnp.asarray(np.tile(img_std, self.frames)), self._rep)

        if cfg.stem_s2d:
            from ..ops.conv import space_to_depth
        else:
            space_to_depth = None

        def _score(variables, x_u8, mean, std):
            x = (x_u8.astype(jnp.float32) - mean) / std
            if space_to_depth is not None:
                x = space_to_depth(x)
            logits = model.apply(variables, x, training=False)
            return jax.nn.softmax(logits, axis=-1)

        t0 = time.monotonic()
        x_spec = jax.ShapeDtypeStruct(
            (self.batch, hw[0], hw[1], self.chans), jnp.dtype(np.uint8))
        # ISSUE 19: the AOT executable store — a hit replaces the whole
        # lower+compile with a deserialize, gated by the golden-batch
        # canary below; ANY unusable entry is a counted loud fallback to
        # the fresh compile, never a crash, never silently wrong
        self.warm_source = "compile"
        self.warm_fallback = ""
        store = fields = manifest = None
        if getattr(cfg, "warmstart_dir", ""):
            from ..serving.warmstart import ExecutableStore, WarmstartMiss
            store = ExecutableStore(cfg.warmstart_dir)
            fields = self._store_fields(cfg, model)
            try:
                compiled, manifest = store.load(fields)
                self.warm_source = "store"
            except WarmstartMiss as miss:
                compiled = None
                if miss.reason != "absent":
                    self.warm_fallback = miss.reason
                    _logger.warning(
                        "warm store entry unusable (%s) — falling back "
                        "to fresh compile: %s", miss.reason, miss)
        else:
            compiled = None
        if compiled is not None and \
                not self._canary_ok(compiled, store, fields, manifest):
            compiled = None
            self.warm_source = "compile"
            self.warm_fallback = "canary-reject"
        if compiled is None:
            compiled = jax.jit(
                _score,
                in_shardings=(self._rep, self._bsh, self._rep,
                              self._rep),
                out_shardings=self._rep).lower(
                    self.variables, x_spec, self._mean,
                    self._std).compile()
        self._compiled = compiled
        # warm once: first-run allocation paths + the persistent-cache
        # hit land before the steady-state recompile probe arms
        jax.block_until_ready(self._compiled(
            self.variables,
            jax.device_put(np.zeros((self.batch,) + hw + (self.chans,),
                                    np.uint8), self._bsh),
            self._mean, self._std))
        self.compile_s = time.monotonic() - t0
        if store is not None and self.warm_source == "compile":
            # re-serialize after every miss AND every fallback so the
            # next worker (or the next corrupted-entry recovery) hits
            scores = np.asarray(jax.block_until_ready(self._compiled(
                self.variables, jax.device_put(
                    self._golden_input(), self._bsh),
                self._mean, self._std)))
            if store.save(fields, self._compiled, golden_scores=scores,
                          params_fingerprint=self._fingerprint()):
                _logger.info("warm store: serialized %s", fields["bucket"])

    # ------------------------------------------------------------------
    def _store_fields(self, cfg, model):
        """The complete executable identity (serving-engine idiom):
        program structure + geometry + sharding signature — params
        VALUES stay out (they ride the call as arguments)."""
        import hashlib

        import jax
        import jax.numpy as jnp

        from ..serving import warmkey

        h = hashlib.sha256()
        h.update(repr(model).encode())
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.variables)[0]:
            h.update(jax.tree_util.keystr(path).encode())
            h.update(str(jnp.shape(leaf)).encode())
            h.update(str(jnp.result_type(leaf)).encode())
        import numpy as np
        h.update(np.asarray(self._mean).tobytes())
        h.update(np.asarray(self._std).tobytes())
        return warmkey.key_fields(
            backend=jax.default_backend(),
            device_kind=jax.devices()[0].device_kind,
            program=h.hexdigest(),
            geometry={"hw": list(self.hw), "frames": self.frames,
                      "stem_s2d": bool(cfg.stem_s2d),
                      "model_class": type(model).__name__,
                      "pipeline": "backfill"},
            bucket=self.batch, chans=self.chans, wire="uint8",
            quant="none",
            sharding=repr(sorted(dict(self.mesh.shape).items())))

    def _fingerprint(self) -> str:
        import jax
        import numpy as np

        from ..cache.content import tree_fingerprint
        leaves = jax.tree_util.tree_flatten_with_path(self.variables)[0]
        return tree_fingerprint(
            ((jax.tree_util.keystr(path), np.asarray(leaf))
             for path, leaf in leaves))

    def _golden_input(self):
        import numpy as np
        rng = np.random.default_rng(0xCA9A87)
        return rng.integers(0, 256, (self.batch,) + self.hw
                            + (self.chans,), dtype=np.uint8)

    def _canary_ok(self, compiled, store, fields, manifest) -> bool:
        """Golden-batch gate on a deserialized executable: must execute,
        score finite at the right shape, and — when the manifest was
        stamped by THIS checkpoint — bit-identically to the recorded
        scores.  A fingerprint-skew pass re-stamps the manifest."""
        import jax
        import numpy as np

        from ..serving import warmkey
        try:
            scores = np.asarray(jax.block_until_ready(compiled(
                self.variables,
                jax.device_put(self._golden_input(), self._bsh),
                self._mean, self._std)))
        except Exception as e:                     # noqa: BLE001
            _logger.error("warm store canary: deserialized executable "
                          "failed to run (%s) — recompiling", e)
            return False
        if scores.ndim != 2 or scores.shape[0] != self.batch \
                or not np.all(np.isfinite(scores)):
            _logger.error("warm store canary: bad golden scores "
                          "(shape %s) — recompiling", scores.shape)
            return False
        fp = self._fingerprint()
        if manifest.get("params_fingerprint") == fp:
            ref = warmkey.decode_array(manifest["golden_scores"])
            if ref.shape != scores.shape or \
                    not np.array_equal(ref, scores):
                _logger.error("warm store canary: golden scores drifted "
                              "from the manifest — recompiling")
                return False
        else:
            store.refresh_manifest(fields, golden_scores=scores,
                                   params_fingerprint=fp)
        return True

    def dispatch(self, slab):
        """Async: host→device transfer + compiled call; returns the
        not-yet-materialized device result."""
        import jax
        return self._compiled(
            self.variables, jax.device_put(slab, self._bsh),
            self._mean, self._std)


def _build_dup_map(source, manifest) -> Dict[Tuple[str, int, str], str]:
    """Content-hash pass over the pack: manifest-order duplicate index.

    Hashes every clip's canonical uint8 bytes (``cache/content``'s exact
    addressing — the serving verdict cache's key) straight off the mmap
    slabs, no decode.  The FIRST manifest occurrence of each content
    hash is canonical; every later occurrence maps to its
    ``kind/root/clip`` string.  Manifest order is deterministic, so N
    workers build the identical map independently — no coordination
    file, no races, and a killed+resumed run books the same skips.

    A clip that fails to load is simply absent from the index (it will
    be booked ``ok=false`` by the score path like any damaged clip).
    """
    from ..backfill import manifest_entries
    from ..cache.content import content_hash

    first: Dict[str, Tuple[str, int, str]] = {}
    dup_of: Dict[Tuple[str, int, str], str] = {}
    for entry in manifest_entries(manifest):
        kind, ri, name, _num = entry
        try:
            h = content_hash([source.load(entry)])
        except Exception:                          # noqa: BLE001
            continue
        key = (kind, int(ri), name)
        canon = first.get(h)
        if canon is None:
            first[h] = key
        else:
            dup_of[key] = "/".join(map(str, canon))
    return dup_of


def run_backfill(cfg, stop: Optional[threading.Event] = None
                 ) -> Dict[str, Any]:
    """One worker's pass over the manifest; returns the run summary
    (books, throughput, recompile delta).  ``stop`` (set by the SIGTERM
    handler or a test) stops at the next batch boundary."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from ..backfill import (LeaseDir, ShardVerdictWriter, collect_books,
                            load_manifest, manifest_entries,
                            verify_manifest_source)
    from ..backfill.source import PackSource, TreeSource
    from ..chaos import chaos_from_env
    from ..obs.events import EventLog
    # the probe must observe EVERY compile in this process, including
    # the pipeline's own AOT build — install before any jit
    from ..serving.metrics import (backend_compile_count,
                                   install_backend_compile_listener)

    cfg.validate_required()
    if getattr(cfg, "compile_cache_dir", ""):
        # jax persistent compilation cache: the fallback tier under the
        # AOT executable store (PERF.md §9) — before the first compile
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(cfg.compile_cache_dir))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    install_backend_compile_listener()
    stop = stop if stop is not None else threading.Event()
    chaos = chaos_from_env()
    if chaos.active:
        _logger.warning("DFD_CHAOS active: %s", sorted(chaos.points))

    manifest = load_manifest(cfg.manifest)
    if cfg.data_packed:
        verify_manifest_source(manifest, pack_dir=cfg.data_packed)
        source: Any = PackSource(cfg.data_packed)
        frames = source.frames_per_clip
    else:
        verify_manifest_source(manifest, roots=cfg.data)
        source = TreeSource(cfg.data, frames_per_clip=cfg.frames,
                            image_size=cfg.image_size)
        frames = source.frames_per_clip
    dup_of: Dict[Tuple[str, int, str], str] = {}
    if cfg.dedup:
        t_h = time.monotonic()
        dup_of = _build_dup_map(source, manifest)
        _logger.info(
            "dedup index: hashed %d clips in %.1fs — %d duplicate(s) "
            "will skip the device and book skipped_dup",
            manifest["num_clips"], time.monotonic() - t_h, len(dup_of))
    run_dir = cfg.out
    os.makedirs(run_dir, exist_ok=True)
    owner = cfg.worker_name or f"{socket.gethostname()}-{os.getpid()}"
    lease = LeaseDir(run_dir, owner, ttl_s=cfg.lease_ttl_s)
    # one telemetry stream PER WORKER: N processes share the run dir,
    # and EventLog's open-time torn-tail repair must never truncate a
    # live peer's in-flight write.  tools/obs_report.py merges every
    # telemetry*.jsonl it finds in the dir.
    log = EventLog(os.path.join(run_dir, f"telemetry-{owner}.jsonl"))

    pending = lease.pending_shards(manifest)
    summary: Dict[str, Any] = {
        "worker": owner, "shards_this_proc": 0, "clips_this_proc": 0,
        "failed_this_proc": 0, "skipped_dup_this_proc": 0,
        "lease_lost": 0, "lease_steals": 0,
        "steady_recompiles": 0, "clips_per_s": 0.0, "elapsed_s": 0.0,
        "warmstart_source": "", "warmstart_fallback": "",
    }
    pipe: Optional[_Pipeline] = None
    if pending:
        if source.sample_hw is None:
            # raw tree with no --image-size: the first LOADABLE clip
            # fixes the bucket geometry (every later clip must match,
            # loudly).  A corrupt first clip must not wedge the corpus —
            # it will be booked ok=false like any other failed clip.
            probe_err: Optional[Exception] = None
            for entry in manifest_entries(manifest):
                try:
                    source.load(entry)
                    break
                except Exception as e:             # noqa: BLE001
                    probe_err = e
            if source.sample_hw is None:
                raise RuntimeError(
                    f"no clip in the manifest could be decoded to fix "
                    f"the batch geometry (last error: {probe_err}) — "
                    f"set --image-size explicitly or repair the corpus")
        pipe = _Pipeline(cfg, frames, source.sample_hw)
        summary["warmstart_source"] = pipe.warm_source
        summary["warmstart_fallback"] = pipe.warm_fallback
        _logger.info(
            "bucket %s in %.1fs: batch %d × %dx%d × %dch on mesh "
            "%s; %d/%d shards pending",
            ("deserialized from the warm store"
             if pipe.warm_source == "store" else "compiled"),
            pipe.compile_s, pipe.batch,
            source.sample_hw[1], source.sample_hw[0], pipe.chans,
            dict(pipe.mesh.shape), len(pending), len(manifest["shards"]))
    log.event("run_start", mode="backfill", manifest=cfg.manifest,
              fingerprint=manifest["fingerprint"],
              num_clips=manifest["num_clips"],
              shards_total=len(manifest["shards"]),
              shards_pending=len(pending), worker=owner,
              batch_size=cfg.batch_size,
              mesh_shape=list(pipe.mesh.devices.shape) if pipe else None,
              axis_names=list(pipe.mesh.axis_names) if pipe else None)

    pool = ThreadPoolExecutor(max(1, int(cfg.workers or 0)
                                  or (os.cpu_count() or 4)))
    batch_seq = 0         # device-batch counter (the chaos step)
    acquire_seq = 0       # lease-attempt counter (lease_race chaos step)
    compiles_steady0 = backend_compile_count()
    t_first: Optional[float] = None
    t_last = time.monotonic()

    def _safe_load(entry):
        try:
            return entry, source.load(entry), ""
        except Exception as e:                     # noqa: BLE001
            # a single unreadable clip must cost ONE failed book entry,
            # never the shard (the corpus is archival; damage happens)
            return entry, None, f"{type(e).__name__}: {e}"

    def _process_shard(sid: str) -> bool:
        """Score one leased shard; True iff committed."""
        nonlocal batch_seq, t_first, t_last
        t0 = time.monotonic()
        writer = ShardVerdictWriter(run_dir, sid)
        entries = list(manifest_entries(manifest, sid))
        todo = [e for e in entries
                if (e[0], e[1], e[2]) not in writer.scored_keys]
        resumed = len(entries) - len(todo)
        failed0 = writer.failed       # inherited from a predecessor's
        # surviving records — not this process's doing
        skipped0 = writer.skipped
        if dup_of:
            # book the shard's duplicates up front, before any batch
            # dispatches: the skip rows land in one write, and a kill
            # right after still resumes exactly (scored_keys covers them)
            dups = [e for e in todo if (e[0], e[1], e[2]) in dup_of]
            if dups:
                writer.append_dups(
                    [(kind, ri, name, 0 if kind == "fake" else 1,
                      dup_of[(kind, ri, name)])
                     for kind, ri, name, _num in dups])
                todo = [e for e in todo
                        if (e[0], e[1], e[2]) not in dup_of]
        if resumed:
            _logger.info("%s: resuming a partial shard — %d/%d verdicts "
                         "survive (%d torn bytes dropped)", sid, resumed,
                         len(entries), writer.torn_bytes_dropped)
        B = pipe.batch
        hw, chans = pipe.hw, pipe.chans
        data_wait = device_wait = host_s = 0.0

        q: "queue.Queue" = queue.Queue(maxsize=2)
        shard_stop = threading.Event()     # abandons the producer when
        # the consumer bails early (lost lease, SIGTERM)

        def _halted() -> bool:
            return stop.is_set() or shard_stop.is_set()

        def _put(item) -> bool:
            while not _halted():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        # thread fan-out only pays when a clip is real work (JPEG decode,
        # or a memcpy big enough to release the GIL meaningfully); for
        # small packed clips the per-task scheduling overhead exceeds the
        # mmap read itself
        clip_nbytes = hw[0] * hw[1] * chans
        fan_out = not getattr(source, "zero_decode", False) or \
            clip_nbytes >= (1 << 18)

        def produce():
            for ci in range(0, len(todo), B):
                if _halted():
                    return
                chunk = todo[ci:ci + B]
                loaded = list(pool.map(_safe_load, chunk)) if fan_out \
                    else [_safe_load(e) for e in chunk]
                ok = [(e, a) for e, a, _err in loaded if a is not None]
                fails = [(e, err) for e, a, err in loaded if a is None]
                slab = None
                if ok:
                    # fresh slab every batch: jax CPU device_put
                    # zero-copies aligned host memory, so reuse would
                    # race the still-executing previous batch (the
                    # data/loader.py hazard)
                    slab = np.zeros((B,) + hw + (chans,), np.uint8)
                    for j, (_e, a) in enumerate(ok):
                        slab[j] = a          # the slab memcpy
                if not _put(([e for e, _ in ok], slab, fails)):
                    return
            _put(None)

        producer = threading.Thread(target=produce, daemon=True,
                                    name=f"backfill-produce-{sid}")
        producer.start()

        #: heartbeat/ownership cadence: frequent enough that a live
        #: worker's lease mtime is always far younger than the TTL
        beat_every = min(1.0, cfg.lease_ttl_s / 10.0)
        last_beat = 0.0

        def _confirm_owner() -> None:
            if not lease.still_owner(sid):
                raise _LeaseLost(sid)

        def _beat(now: float) -> None:
            """Heartbeat + ownership on the time cadence.  Runs before
            EVERY write (and in the main loop), so no stall — device,
            data, or cumulative — can exceed ``beat_every`` between an
            ownership confirmation and an append: a TTL-starved worker
            abandons instead of appending duplicates of clips the
            stealer is re-scoring."""
            nonlocal last_beat
            if now - last_beat >= beat_every:
                last_beat = now
                lease.heartbeat(sid)
                _confirm_owner()

        def _complete(staged) -> None:
            nonlocal device_wait, host_s
            ok_entries, fails, out, seq = staged
            t_dev = time.monotonic()
            scores = np.asarray(out) if out is not None else None
            dt = time.monotonic() - t_dev
            device_wait += dt
            _beat(time.monotonic())       # BEFORE the append, always
            t_host = time.monotonic()
            rows = []
            for j, (kind, ri, name, _num) in enumerate(ok_entries):
                s = float(scores[j, 0])                 # P(fake)
                if np.isfinite(s):
                    rows.append((kind, ri, name,
                                 0 if kind == "fake" else 1, s, ""))
                else:
                    # a non-finite score is NEVER served (the serving
                    # engine's contract): book the clip failed instead
                    # of crashing the strict-JSON writer
                    rows.append((kind, ri, name,
                                 0 if kind == "fake" else 1, None,
                                 "NonFiniteScore: model produced a "
                                 "non-finite probability"))
            rows += [(kind, ri, name, 0 if kind == "fake" else 1,
                      None, err)
                     for (kind, ri, name, _num), err in fails]
            writer.append_many(rows)
            host_s += time.monotonic() - t_host
            if chaos.active and chaos.fires("backfill_torn_shard", seq):
                # tear the stream exactly as a mid-write kill would:
                # half a record, no newline, then a hard death that
                # leaves the lease behind (a dead host, not a SIGTERM)
                writer.tear()
                _logger.error("chaos: torn shard %s at batch %d; hard "
                              "exit", sid, seq)
                os._exit(int(chaos.arg("backfill_torn_shard", 137)))

        #: dispatched-but-uncompleted batches, oldest first.  Depth 2 =
        #: batch k+1's transfer AND execution overlap batch k's (two
        #: programs genuinely run concurrently on the CPU backend's
        #: execution pool; on an accelerator this is the classic
        #: stage-ahead queue) while the host appends k-1's verdicts —
        #: the DeviceLoader / serving-engine idiom, one stage deeper.
        inflight: List[Tuple] = []
        committed = False
        lost = False
        try:
            while True:
                t_q = time.monotonic()
                item = None
                while not stop.is_set():
                    try:
                        item = q.get(timeout=0.1)
                        break
                    except queue.Empty:
                        # a data-side stall (slow decode, wedged NFS)
                        # must not let a LIVE worker's lease age into
                        # stealable: keep beating while we wait
                        _beat(time.monotonic())
                        continue
                waited = time.monotonic() - t_q
                data_wait += waited
                if item is None:          # end of shard, or SIGTERM
                    break
                ok_entries, slab, fails = item
                seq = batch_seq
                batch_seq += 1
                if chaos.active and chaos.fires("backfill_kill", seq):
                    # a preemption mid-corpus: deliver a REAL SIGTERM so
                    # the production handler (stop at batch boundary,
                    # release leases, exit 75) is what gets exercised
                    _logger.error("chaos: SIGTERM to self at batch %d",
                                  seq)
                    os.kill(os.getpid(), signal.SIGTERM)
                # dispatch k+1 BEFORE blocking on k-1: transfer + compute
                # overlap the older batches' completion
                out = pipe.dispatch(slab) if slab is not None else None
                if t_first is None:
                    t_first = time.monotonic()
                inflight.append((ok_entries, fails, out, seq))
                if len(inflight) > 2:
                    _complete(inflight.pop(0))
                # liveness + ownership ride the same time cadence during
                # decode-only stretches too (at saturation _beat's two
                # syscalls per cadence are the only ones left in the
                # hot loop)
                _beat(time.monotonic())
            while inflight and not lost:
                _complete(inflight.pop(0))
            t_last = time.monotonic()
            need = {(e[0], e[1], e[2]) for e in entries}
            if not lost and not stop.is_set() and \
                    need <= writer.scored_keys:
                # every manifest clip of this shard has a record (set
                # containment, not a count — an alien record must never
                # mask a missing clip): commit
                book = writer.finalize()
                committed = lease.mark_done(sid, book)
        except _LeaseLost:
            # TTL-starved: another worker legitimately stole the shard —
            # its books win; ours stop here, uncommitted
            _logger.error("%s: lease lost mid-shard (TTL %.0fs too short "
                          "for this batch cadence?); abandoning",
                          sid, cfg.lease_ttl_s)
            summary["lease_lost"] += 1
            lost = True
            t_last = time.monotonic()
        finally:
            shard_stop.set()
            writer.close()
            if not committed:
                lease.release(sid)
        wall = time.monotonic() - t0
        done_clips = writer.records - resumed
        log.metrics(
            shard=sid, clips=len(entries), scored=writer.records -
            writer.failed - writer.skipped, failed=writer.failed,
            skipped_dup=writer.skipped, resumed=resumed,
            committed=committed, wall_s=round(wall, 3),
            clips_per_s=round(done_clips / wall, 2) if wall else None,
            data_wait_s=round(data_wait, 3),
            device_wait_s=round(device_wait, 3),
            host_s=round(host_s, 3),
            backend_compiles=backend_compile_count() - compiles_steady0,
            torn_bytes_dropped=writer.torn_bytes_dropped,
            worker=owner)
        summary["clips_this_proc"] += done_clips
        summary["failed_this_proc"] += writer.failed - failed0
        summary["skipped_dup_this_proc"] += writer.skipped - skipped0
        return committed

    rival: Optional[LeaseDir] = None
    try:
        while not stop.is_set():
            pending = lease.pending_shards(manifest)
            if not pending:
                break
            if cfg.max_shards and \
                    summary["shards_this_proc"] >= cfg.max_shards:
                break
            progressed = False
            for sid in pending:
                if stop.is_set():
                    break
                if cfg.max_shards and \
                        summary["shards_this_proc"] >= cfg.max_shards:
                    break
                if chaos.active and \
                        chaos.fires("backfill_lease_race", acquire_seq):
                    # a rival worker wins the race for THIS shard an
                    # instant before us: our acquire must lose cleanly
                    # and move on; the rival's lease then expires by TTL
                    # and the stale-break path re-leases it
                    if rival is None:
                        rival = LeaseDir(run_dir, "chaos-rival",
                                         ttl_s=cfg.lease_ttl_s)
                    rival.acquire(sid)
                    _logger.error("chaos: rival leased %s ahead of us",
                                  sid)
                acquire_seq += 1
                if not lease.acquire(sid):
                    continue
                if lease.last_steal is not None:
                    summary["lease_steals"] += 1
                    log.event("lease_steal", shard=sid,
                              prev_owner=lease.last_steal.get("owner"))
                    lease.last_steal = None
                if _process_shard(sid):
                    summary["shards_this_proc"] += 1
                    progressed = True
            if not progressed and not stop.is_set() and \
                    lease.pending_shards(manifest):
                # everything left is leased elsewhere (or freshly
                # rivaled): wait out a fraction of the TTL and re-sweep
                stop.wait(min(1.0, cfg.lease_ttl_s / 4.0))
    finally:
        pool.shutdown(wait=False)

    summary["steady_recompiles"] = backend_compile_count() - \
        compiles_steady0
    if t_first is not None:
        summary["elapsed_s"] = round(t_last - t_first, 3)
        if summary["elapsed_s"] > 0:
            summary["clips_per_s"] = round(
                summary["clips_this_proc"] / summary["elapsed_s"], 2)
    books = collect_books(run_dir, manifest)
    summary["books"] = books
    summary["preempted"] = stop.is_set()
    log.event("run_end", **{k: v for k, v in summary.items()})
    log.close()
    if summary["steady_recompiles"]:
        _logger.error("backend compiled %d time(s) AFTER the bucket "
                      "warmup — the zero-recompile contract broke",
                      summary["steady_recompiles"])
    return summary


def main(argv: Optional[Sequence[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    from ..config import BackfillConfig
    cfg = BackfillConfig.from_args(argv)

    stop = threading.Event()

    def _sig(signum, frame):
        _logger.info("signal %d: stopping at the next batch boundary",
                     signum)
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    summary = run_backfill(cfg, stop=stop)
    books = summary["books"]
    _logger.info(
        "worker %s: %d shard(s), %d clip(s) this process at %.1f "
        "clips/s; corpus %d/%d shards done — books: %d manifest == %d "
        "scored + %d failed + %d skipped_dup (%s)", summary["worker"],
        summary["shards_this_proc"], summary["clips_this_proc"],
        summary["clips_per_s"], books["shards_done"],
        books["shards_total"], books["manifest_clips"], books["scored"],
        books["failed"], books["skipped_dup"],
        "BALANCED" if books["balanced"] else
        ("incomplete" if not books["complete"] else "IMBALANCED"))
    if summary["preempted"]:
        return EXIT_PREEMPTED
    if books["complete"] and not books["balanced"]:
        _logger.error("books do not balance: missing=%s duplicated=%s "
                      "alien=%s", books["missing"][:5],
                      books["duplicated"][:5], books["alien"][:5])
        return 1
    if summary["steady_recompiles"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
