"""Distributed training runner.

Re-design of ``/root/reference/dfd/runners/train.py`` (819 LoC) for TPU:

* ``launch_main`` (:769-816) — arg parse, cluster config, output-dir setup,
  linear LR scaling — maps to :func:`launch_main`.  The ``mp.spawn``
  per-GPU process fan-out and the NCCL file rendezvous disappear: one process
  per *host* drives all local devices through the mesh, and
  ``jax.distributed.initialize`` handles multi-host (parallel/mesh.py).
* ``main`` (:256-592) — model/optimizer/scheduler/dataset construction,
  resume, epoch loop — maps to :func:`main`.
* apex AMP O1 (:353) → bfloat16 compute policy (``--compute-dtype``), no
  loss scaling needed on TPU.
* apex DDP (:402) → the jitted train step over the mesh (train/steps.py).

Safety deviation: the reference's rank-0 setup *deletes* an existing output
dir (``dfd/utils.py:77-80``); here collisions get a ``-N`` suffix instead
(utils.get_outdir(inc=True)).

Usage::

    python -m deepfake_detection_tpu.runners.train \
        --data /path/DFDC --model efficientnet_deepfake_v4 \
        --input-size-v2 12,600,600 -b 3 --opt rmsproptf --basic-lr 5e-7 \
        --sched step --decay-epochs 2 --decay-rate .92 --amp \
        --reprob 0.2 --remax 0.05 --flicker 0.05 --rotate-range 5 \
        --blur-prob 0.05 --bn-momentum 0.001 --mixup 0.1 --label-balance \
        --eval-metric loss      # == scripts/train.sh:3-22
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ClusterConfig, TrainConfig
from ..data import (DeepFakeClipDataset, FastCollateMixup, SyntheticDataset,
                    create_deepfake_loader_v3, resolve_data_config)
from ..losses import create_loss_fn, cross_entropy
from ..models import (create_deepfake_model, create_deepfake_model_v3,
                      create_deepfake_model_v4, create_model, init_model)
from ..optim import create_optimizer
from ..parallel import (batch_sharding, data_axis_name,
                        initialize_distributed, make_mesh, make_train_mesh,
                        place_train_state, replicated_sharding,
                        train_state_shardings, transformer_tp_sharding)
from ..scheduler import create_scheduler
from ..train import (EXIT_PREEMPTED, CheckpointCorrupt, CheckpointSaver,
                     Preempted, Resilience, RewindRequested,
                     ShardedCheckpointSaver, create_train_state,
                     find_resume_candidates, make_eval_step,
                     make_train_step, replicate_for_save, restore_resharded,
                     set_learning_rate, train_one_epoch, validate,
                     wait_pending_saves)
from ..utils import get_outdir, setup_default_logging, update_summary

_logger = logging.getLogger("train")

_MODEL_FACTORIES = {
    "": create_model,
    "v1": create_deepfake_model,
    "v3": create_deepfake_model_v3,
    "v4": create_deepfake_model_v4,
}


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[name]


def build_model(cfg: TrainConfig, in_chans: int):
    """Model construction (reference train.py:305-320)."""
    factory = _MODEL_FACTORIES.get(cfg.model_version, create_model)
    kwargs: Dict[str, Any] = dict(
        pretrained=cfg.pretrained, num_classes=cfg.num_classes,
        in_chans=in_chans, drop_rate=cfg.drop,
        drop_path_rate=cfg.drop_path, drop_block_rate=cfg.drop_block,
        bn_tf=cfg.bn_tf,
        bn_momentum=cfg.bn_momentum, bn_eps=cfg.bn_eps,
        global_pool=cfg.gp,
        remat_policy=cfg.checkpoint_policy,
        fused_depthwise=cfg.fused_depthwise,
        stem_s2d=cfg.stem_s2d,
        dtype=_dtype(cfg.compute_dtype) if (cfg.amp or
                                            cfg.compute_dtype != "float32")
        else None)
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    if cfg.split_bn:
        # AdvProp split BN (reference train.py:335-337): a separate BN per
        # augmentation split — meaningless without >1 split.  ValueError,
        # not assert: CLI validation must survive python -O
        if not (cfg.aug_splits > 1 or cfg.resplit):
            raise ValueError("--split-bn needs --aug-splits > 1 or "
                             "--resplit")
        kwargs["norm_layer"] = f"split{max(cfg.aug_splits, 2)}"
    if cfg.attn_impl:
        if cfg.attn_impl in ("ring", "ring_flash", "ulysses"):
            raise ValueError(
                f"--attn-impl {cfg.attn_impl}: sequence-parallel attention "
                f"needs an sp mesh and token-sharded inputs — construct the "
                f"model with sp_mesh/seq_axis directly (models/vit.py); the "
                f"CLI supports 'full' and 'flash'")
        kwargs["attn_impl"] = cfg.attn_impl   # ViT/TimeSformer families
    if factory is create_model:
        return create_model(cfg.model, **kwargs)
    return factory(cfg.model, **kwargs)


def build_datasets(cfg: TrainConfig, input_size, pack_dir=None,
                   pack_image_size=None) -> Tuple[Any, Any]:
    """Train/eval dataset construction (reference train.py:422-504).

    ``pack_dir`` (``--data-packed``, resolved through
    ``data/config.py::resolve_data_config``) swaps the JPEG-decode clip
    source for the packed pre-decoded cache (``data/packed.py``) — the
    split/balance/RNG machinery is shared, so downstream batches are
    bit-identical at matching pack resolution.  A stale or mismatched
    pack raises at construction, never trains on skewed data.
    """
    c, h, w = input_size
    if cfg.dataset == "synthetic":
        if pack_dir:
            raise ValueError("--data-packed requires --dataset deepfake_v3")
        n = max(cfg.batch_size * 8, 16)
        return (SyntheticDataset(n, (h, w, c), cfg.num_classes, cfg.seed),
                SyntheticDataset(max(n // 2, 8), (h, w, c), cfg.num_classes,
                                 cfg.seed + 1))
    if cfg.dataset == "deepfake_v3":
        common = dict(frames_per_clip=max(1, c // 3),
                      label_balance=cfg.label_balance,
                      noise_fake=cfg.noise_fake > 0,
                      split_seed=cfg.split_seed)
        if pack_dir:
            from ..data import PackedDataset
            packed = dict(roots=cfg.data or None,
                          image_size=pack_image_size)

            def make_train(**kw):
                return PackedDataset(pack_dir, **packed, **kw)
        else:
            def make_train(**kw):
                return DeepFakeClipDataset(cfg.data, **kw)
        if cfg.eval_data:
            # a separate eval root always reads through the decode path:
            # the pack is fingerprinted against the TRAIN lists only
            train_ds = make_train(**common)
            eval_ds = DeepFakeClipDataset(cfg.eval_data,
                                          frames_per_clip=max(1, c // 3),
                                          split_seed=cfg.split_seed)
        else:  # seeded split out of the train roots (reference :424-438)
            train_ds = make_train(
                train_split=True, train_ratio=cfg.train_split,
                is_training=True, **common)
            eval_ds = make_train(
                train_split=True, train_ratio=cfg.train_split,
                is_training=False, frames_per_clip=max(1, c // 3),
                split_seed=cfg.split_seed)
        return train_ds, eval_ds
    raise ValueError(f"unknown dataset {cfg.dataset!r}")


def main(cfg: TrainConfig) -> Dict[str, float]:
    """Train to completion; returns the best eval metrics."""
    if cfg.compile_cache_dir:
        # jax persistent compilation cache (PERF.md §9): restarted runs
        # skip the XLA compile wall — must land before the first compile
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(cfg.compile_cache_dir))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    rank = jax.process_index()
    if cfg.tp_size > 1:
        if cfg.mesh_shape is not None or cfg.fsdp:
            raise ValueError(
                "--tp-size conflicts with an explicit --mesh-shape/--fsdp; "
                "configure one parallelism layout at a time")
        # dp×tp on the unified mesh; parameter shardings applied after
        # init below (transformer_tp_sharding names the 'model' axis)
        mesh = make_train_mesh(batch=-1, model=cfg.tp_size)
    elif cfg.mesh_shape is not None or tuple(cfg.mesh_axes) != ("data",):
        # explicit legacy layout: honored verbatim (tests / sp meshes)
        mesh = make_mesh(cfg.mesh_shape, cfg.mesh_axes)
    else:
        # the default: ONE 2-D ('batch', 'model') mesh — the same program
        # compiles for 1 chip and a pod (ISSUE 12)
        mesh = make_train_mesh()
    n_dev = int(mesh.size)
    batch_axis = data_axis_name(mesh)
    # the data-parallel degree: batch and linear-LR scaling follow it, not
    # the raw device count (a tp group is ONE model replica)
    dp_size = int(mesh.shape.get(batch_axis, n_dev))
    _logger.info("Training with %d devices, mesh %s, process %d/%d",
                 n_dev, dict(mesh.shape), rank, jax.process_count())
    if cfg.fused_depthwise == "pallas" and n_dev > 1 and \
            jax.default_backend() == "tpu":
        # chip-gated residue of the GSPMD migration (ROADMAP chip-debt):
        # the compiled Mosaic pallas_call has no SPMD partitioning rule,
        # so embedding it in the unified jit over a >1-chip mesh would at
        # best replicate the batch around every dw stage and at worst
        # fail to lower — the old shard_map wrapper that guaranteed
        # per-device execution is gone.  Interpret mode (off-TPU CI)
        # partitions fine; on real multi-chip, fail loudly until the
        # kernel grows its own partitioning (shard_map island or
        # custom_partitioning).
        raise NotImplementedError(
            "--fused-depthwise pallas on a multi-chip mesh is not yet "
            "verified under the unified GSPMD step; run with "
            "--fused-depthwise off (or a single chip) until the kernel's "
            "multi-chip migration lands")
    if cfg.split_bn and dp_size > 1:
        # the loader's split-major batch layout ([all clean, all aug])
        # does not survive contiguous per-device sharding — device d
        # would feed its main BN augmented samples, corrupting exactly
        # the clean/aug separation AdvProp split BN exists for
        raise NotImplementedError(
            "--split-bn requires a single data-parallel replica "
            "(dp=1); an interleaved per-device batch layout is needed "
            "for dp>1 and is not implemented")

    # ONE seed for every host: params are logically replicated, so init must
    # be identical everywhere (the reference's per-rank seed, train.py:299,
    # was safe only because DDP broadcast rank-0's weights; SPMD has no such
    # broadcast).  The unified step draws dropout noise over the GLOBAL
    # batch from one mesh-replicated key (the key is pinned replicated
    # before the loop below) — do NOT re-add a per-device fold; it would
    # break the replicated-key in_shardings contract.
    rng = jax.random.PRNGKey(cfg.seed)
    data_config = resolve_data_config(cfg.to_dict(), verbose=rank == 0)
    input_size = data_config["input_size"]
    in_chans = input_size[0]
    img_num = max(1, in_chans // 3)

    model = build_model(cfg, in_chans)
    init_rng, rng = jax.random.split(rng)
    variables = init_model(model, init_rng,
                           (1, input_size[1], input_size[2], in_chans),
                           training=True)
    n_params = sum(x.size for x in jax.tree.leaves(variables["params"]))
    _logger.info("Model %s created, param count: %d", cfg.model, n_params)

    # per-sample forward FLOPs for the live MFU gauge (obs/telemetry.py):
    # an abstract jaxpr walk, so it must run while ``variables`` is alive
    # (create_train_state donates the buffers).  The shape is what the
    # LOADER feeds the model — pixel-shuffled under --stem-s2d.
    fwd_flops = 0.0
    if not cfg.no_telemetry:
        from ..obs import forward_flops_per_sample
        flop_shape = (1, input_size[1] // 2, input_size[2] // 2,
                      4 * in_chans) if cfg.stem_s2d else \
            (1, input_size[1], input_size[2], in_chans)
        fwd_flops = forward_flops_per_sample(model, variables, flop_shape)

    if cfg.initial_checkpoint:
        # pretrained weights into the fresh tree (reference train.py:316 /
        # helpers.py:31-44): non-strict — head/in_chans mismatches drop,
        # but loudly, and a checkpoint matching NOTHING is an error (a
        # silent from-scratch "fine-tune" is worse than failing)
        from ..models.helpers import (_flatten, expand_split_bn,
                                      filter_shape_mismatch,
                                      load_state_dict)
        loaded = load_state_dict(cfg.initial_checkpoint)
        if cfg.split_bn:
            # plain-BN checkpoints fan out into main + aux BNs, like the
            # reference's load-then-convert order (split_batchnorm.py:41)
            loaded = expand_split_bn(loaded, variables)
        n_init = len(_flatten(variables))
        n_hit = len(set(_flatten(variables)) & set(_flatten(loaded)))
        variables, dropped = filter_shape_mismatch(variables, loaded)
        applied = n_hit - dropped
        if applied == 0:
            raise ValueError(
                f"--initial-checkpoint {cfg.initial_checkpoint} matches no "
                f"parameter of model {cfg.model!r} — wrong architecture?")
        _logger.info(
            "Loaded initial checkpoint %s: %d/%d leaves applied "
            "(%d shape-mismatched, %d missing keep their fresh init)",
            cfg.initial_checkpoint, applied, n_init, dropped,
            n_init - n_hit)

    def apply_tp(params):
        # place params under the Megatron-paired TP shardings; non-matching
        # leaves (and non-transformer models) stay replicated
        shardings = transformer_tp_sharding(params, mesh, axis="model")
        return jax.device_put(params, shardings)

    if cfg.tp_size > 1:
        variables = dict(variables)
        variables["params"] = apply_tp(variables["params"])
        _logger.info("Tensor parallelism: params sharded over 'model' "
                     "axis (tp_size=%d)", cfg.tp_size)

    # linear LR scaling: per-device batch × total devices (train.py:814)
    # effective batch per optimizer step includes the accumulated
    # microbatches — the linear rule must see it, or the flagship config
    # trains with an LR grad_accum-times below the reference's
    lr = cfg.resolved_lr(world_size=dp_size * cfg.grad_accum)
    tx = create_optimizer(cfg, learning_rate=lr)
    state = create_train_state(variables, tx, with_ema=cfg.model_ema)
    # the sharding-rule table (parallel/sharding.py): every TrainState leaf
    # gets its NamedSharding — params replicated/FSDP/TP per rule, opt
    # moments and EMA following their params, BN stats and step replicated
    # — and the state is laid onto the mesh accordingly.  Everything
    # downstream (the jitted step's in/out_shardings, checkpoint restore
    # re-layout, the guard's rewind template) reads layout from this one
    # table.
    state_shardings = train_state_shardings(state, mesh, fsdp=cfg.fsdp,
                                            axis=batch_axis)
    state = place_train_state(state, state_shardings)

    lr_scheduler, num_epochs = create_scheduler(cfg, base_lr=lr)
    start_epoch = cfg.start_epoch or 0

    # output dir + config dump (reference :785-808, :527-532) — built
    # BEFORE resume handling so --auto-resume can consult the run
    # directory's recovery snapshots at startup
    output_dir, saver = "", None
    if rank == 0 or cfg.ckpt_sharded or cfg.auto_resume:
        exp_name = cfg.experiment or "-".join(
            [cfg.model_version or cfg.model,
             os.path.basename(cfg.data.split(":")[0]) or cfg.dataset])
        # the sharded saver is COLLECTIVE: every rank drives it and all
        # must agree on the directory, so multi-process sharded runs skip
        # the auto-increment (a per-rank race) — name runs via --experiment.
        # --auto-resume equally needs a STABLE directory across relaunches
        # (the -N increment would "resume" into a fresh empty dir).
        multiproc_sharded = cfg.ckpt_sharded and jax.process_count() > 1
        output_dir = get_outdir(cfg.output, exp_name,
                                inc=not (multiproc_sharded or
                                         cfg.auto_resume))
        if multiproc_sharded and rank == 0 and not cfg.resume and \
                not cfg.auto_resume and \
                os.path.exists(os.path.join(output_dir, "args.yaml")):
            # inc=False means a rerun would silently overwrite the
            # previous run's checkpoints and records.  Rank 0 ONLY: other
            # ranks would race against rank 0's own args.yaml write of
            # THIS run; rank 0's failure propagates through the
            # coordination service
            raise ValueError(
                f"{output_dir} already holds a run; multi-process "
                "--ckpt-sharded disables output-dir auto-increment — "
                "name this run with --experiment, or --resume it")
        if rank == 0:
            with open(os.path.join(output_dir, "args.yaml"), "w") as f:
                f.write(cfg.to_yaml())
        if rank == 0 or cfg.ckpt_sharded:
            decreasing = cfg.eval_metric == "loss"
            saver_cls = ShardedCheckpointSaver if cfg.ckpt_sharded \
                else CheckpointSaver
            saver = saver_cls(
                checkpoint_dir=output_dir, bak_dir=os.path.join(
                    output_dir, "_bak"), decreasing=decreasing)

    def _restore_any(path: str, template, load_opt: Optional[bool] = None):
        if load_opt is None:
            load_opt = not cfg.no_resume_opt
        if os.path.isdir(path):
            # sharded (Orbax) checkpoint directory: collective restore
            # directly into the template's shardings — re-layout
            # (incl. a different tp_size) happens inside the read
            from ..train import restore_sharded_checkpoint
            st, meta_r = restore_sharded_checkpoint(
                path, template, load_opt=load_opt)
            # re-own every restored leaf before it reaches the donating
            # step: with the sharding table pinning ALL template leaves,
            # the restore no longer demotes anything to host numpy, and
            # orbax/tensorstore-backed buffers donated by the step
            # corrupt the heap (observed: glibc abort on --ckpt-sharded
            # resume).  jnp.copy preserves each leaf's sharding.
            st = jax.tree.map(
                lambda x: jnp.copy(x)
                if isinstance(x, (jax.Array, np.ndarray)) else x, st)
            return st, meta_r
        # msgpack: host arrays re-laid onto the template's sharding-table
        # annotations (train/checkpoint.py) — a (1,1)-mesh checkpoint
        # restores onto this run's mesh and vice versa
        return restore_resharded(path, template, load_opt=load_opt)

    def _restore_with_fallback(template, load_opt: Optional[bool] = None):
        """Walk the resume ladder (recovery snapshots newest-first, then
        the _bak best-copy, then model_best), skipping torn/corrupt files
        instead of crashing on them.  Returns (state, meta, path) or
        None."""
        # an in-flight async recovery write hasn't renamed into place yet
        # — join it BEFORE listing, or a guard rewind a step or two after
        # the snapshot finds an empty ladder (loads already join; the
        # listing must too)
        wait_pending_saves()
        cands = find_resume_candidates(
            output_dir, bak_dir=os.path.join(output_dir, "_bak"),
            sharded=cfg.ckpt_sharded)
        for path in cands:
            try:
                st, meta_r = _restore_any(path, template, load_opt)
                return st, meta_r, path
            except (CheckpointCorrupt, FileNotFoundError) as e:
                _logger.warning("auto-resume: skipping unusable "
                                "checkpoint %s (%s)", path, e)
        return None

    resume_batch = 0
    resumed_from = ""
    if cfg.resume:
        state, meta = _restore_any(cfg.resume, state)
        start_epoch = cfg.start_epoch if cfg.start_epoch is not None \
            else int(meta.get("epoch", -1)) + 1   # helpers.py:47-73
        _logger.info("Resumed from %s (epoch %d)", cfg.resume, start_epoch)
    if cfg.auto_resume:
        # newer than any --resume argument when present: a relaunch after
        # preemption continues from its own recovery snapshot, not the
        # checkpoint the run was originally seeded from
        restored = _restore_with_fallback(state)
        if restored is not None:
            state, meta_r, path = restored
            resumed_from = path
            if "batch_idx" in meta_r:
                # recovery snapshot: exact mid-epoch loop position
                start_epoch = int(meta_r["epoch"])
                resume_batch = int(meta_r["batch_idx"]) + 1
            else:                       # epoch-boundary checkpoint
                start_epoch = int(meta_r.get("epoch", -1)) + 1
            _logger.info("Auto-resumed from %s (epoch %d, batch %d)",
                         path, start_epoch, resume_batch)
        else:
            _logger.info("--auto-resume: nothing to resume in %s; "
                         "starting fresh", output_dir)
    train_ds, eval_ds = build_datasets(
        cfg, input_size, pack_dir=data_config.get("pack_dir"),
        pack_image_size=data_config.get("pack_image_size"))
    sharding = batch_sharding(mesh)
    # loaders produce the *per-process* slice of the global batch; the device
    # prologue assembles the global sharded array
    # grad_accum microbatches ride inside one compiled step: the loader
    # assembles the full effective batch per step (train only — eval is a
    # single forward, so it must NOT inherit the accumulation factor)
    global_batch = cfg.batch_size * dp_size * cfg.grad_accum
    local_batch = global_batch // jax.process_count()
    eval_local_batch = cfg.batch_size * dp_size * 2 // jax.process_count()
    loader_kwargs = dict(
        mean=data_config["mean"], std=data_config["std"],
        num_workers=cfg.workers, seed=cfg.seed,
        dtype=_dtype(cfg.compute_dtype), sharding=sharding,
        distributed=jax.process_count() > 1,
        num_shards=jax.process_count(), shard_index=rank,
        prefetch_depth=cfg.prefetch_depth,
        loader_backend=cfg.loader_backend, ring_depth=cfg.ring_depth,
        worker_heartbeat=cfg.worker_heartbeat, stem_s2d=cfg.stem_s2d)
    collate_mixup = FastCollateMixup(cfg.mixup, cfg.smoothing,
                                     cfg.num_classes) if cfg.mixup > 0 \
        else None
    train_loader = create_deepfake_loader_v3(
        train_ds, input_size, local_batch, is_training=True,
        re_prob=cfg.reprob, re_mode=cfg.remode, re_count=cfg.recount,
        re_split=cfg.resplit, re_max=cfg.remax, color_jitter=cfg.color_jitter,
        num_aug_splits=cfg.aug_splits, collate_mixup=collate_mixup,
        flicker=cfg.flicker, rotate_range=cfg.rotate_range,
        blur_radius=1, blur_prob=cfg.blur_prob,
        device_color_jitter=not cfg.host_color_jitter,
        fused_geom=not cfg.host_geom,
        augment_device=cfg.augment_device == "on", **loader_kwargs)
    eval_loader = create_deepfake_loader_v3(
        eval_ds, input_size, eval_local_batch, is_training=False,
        eval_crop=cfg.eval_crop,
        **loader_kwargs)                          # eval bs ×2 (train.py:492)

    train_loss_fn = create_loss_fn(cfg)
    # tp runs use global-BN semantics: the transformer families carry no
    # BN, so local-stat grouping would only add layout churn for nothing
    bn_mode = "global" if (cfg.sync_bn or cfg.tp_size > 1) else "local"
    if cfg.dist_bn:
        _logger.info("--dist-bn %s accepted for flag parity; BN stats are "
                     "pmean-reduced inside every train step here, which "
                     "supersedes the reference's per-epoch distribute_bn",
                     cfg.dist_bn)
    train_step = make_train_step(
        model, tx, train_loss_fn, mesh=mesh, axis=batch_axis,
        bn_mode=bn_mode,
        ema_decay=cfg.model_ema_decay if cfg.model_ema else 0.0,
        clip_grad=cfg.clip_grad, grad_accum=cfg.grad_accum,
        nonfinite_guard=cfg.guard_nonfinite == "skip",
        state_shardings=state_shardings)
    eval_step = make_eval_step(model, cross_entropy)
    eval_step_ema = make_eval_step(model, cross_entropy, use_ema=True) \
        if cfg.model_ema else None

    # a recovery snapshot taken at the LAST batch of an epoch resumes at
    # the next epoch's first batch
    if resume_batch >= len(train_loader) > 0:
        start_epoch += 1
        resume_batch = 0
    if lr_scheduler is not None and start_epoch > 0 and resume_batch == 0:
        # mid-epoch resume keeps the snapshot's injected LR exactly (it
        # already carries any per-update scheduling); epoch-boundary
        # resume re-derives it like the reference (train.py:416-417).
        # Must run AFTER the last-batch normalization above: a snapshot
        # taken at the final batch of epoch E resumes as (E+1, batch 0)
        # and needs E+1's LR, not the snapshot's epoch-E value.
        state = set_learning_rate(
            state, lr_scheduler.step(start_epoch))

    if jax.process_count() > 1:
        # all host-side setup (datasets, eager init, output dir) is done —
        # meet here so a fast rank doesn't reach the first collective while
        # a slow one is still initializing: cross-process collective-context
        # creation (gloo on CPU; similar rendezvous on DCN) has a short
        # deadline that host-side skew alone can blow
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("train_start")

    # the jitted step declares its rng argument replicated over the mesh
    # (in_shardings); fold_in of a mesh-replicated key yields another
    # mesh-replicated key, so one placement here covers every step of the
    # run (a committed single-device key would be an in_shardings
    # mismatch).  own_and_place owns the bytes and covers multi-host,
    # where every process holds the same host key.
    from ..parallel import own_and_place
    rng = own_and_place(np.asarray(rng), replicated_sharding(mesh))

    meta = {"arch": cfg.model, "version": 2}
    best_metric, best_epoch = None, None
    eval_metrics: Dict[str, float] = {}
    exit_code: Optional[int] = None
    resilience = Resilience.from_config(cfg, output_dir=output_dir)

    # observability (obs/): default-on telemetry tracker + JSONL event log
    # (rank 0 — one coherent stream per run dir), optional --metrics-port
    # Prometheus endpoint, on-demand profiler capture triggers
    telemetry, obs_server, profiler = None, None, None
    if not cfg.no_telemetry:
        from ..obs import (EventLog, ProfilerCapture, TrainTelemetry,
                           loader_collector, native_warp_collector,
                           peak_flops, resilience_collector,
                           start_metrics_server)
        event_log = EventLog(os.path.join(output_dir, "telemetry.jsonl")) \
            if output_dir and rank == 0 else None
        telemetry = TrainTelemetry(
            event_log=event_log, flops_per_sample=fwd_flops,
            # throughput is measured on the GLOBAL batch (the loader
            # assembles the global sharded array), so the MFU denominator
            # is the whole MESH's peak — n_dev == mesh.size, which a
            # sub-mesh run may set below the visible device count
            peak_flops=peak_flops() * n_dev,
            meta=dict(model=cfg.model, global_batch=global_batch,
                      mesh_shape=[int(s) for s in mesh.shape.values()],
                      axis_names=list(mesh.axis_names)))
        telemetry.register_collector(loader_collector(train_loader))
        telemetry.register_collector(native_warp_collector())
        telemetry.register_collector(resilience_collector(resilience))
        if cfg.metrics_port:
            obs_server = start_metrics_server(telemetry,
                                              port=cfg.metrics_port)
        if output_dir and cfg.profile_capture > 0:
            profiler = ProfilerCapture(output_dir,
                                       num_steps=cfg.profile_capture,
                                       telemetry=telemetry)
            telemetry.profiler = profiler
        telemetry.event("run_start", model=cfg.model, epochs=num_epochs,
                        start_epoch=start_epoch, global_batch=global_batch,
                        world_size=n_dev,
                        mesh_shape=[int(s) for s in mesh.shape.values()],
                        axis_names=list(mesh.axis_names))
        if resumed_from:
            telemetry.event("resume", path=resumed_from,
                            epoch=start_epoch, batch=resume_batch)
    try:
        with resilience:
            if profiler is not None and not profiler.install():
                _logger.warning("not in the main thread: SIGUSR2 profiler "
                                "trigger not installed (the PROFILE file "
                                "trigger still works)")
            epoch = start_epoch
            while epoch < num_epochs:
                train_loader.set_epoch(epoch)      # reference :549
                if resume_batch:
                    train_loader.fast_forward(resume_batch)
                epoch_rng = jax.random.fold_in(rng, epoch)
                # note, not heartbeat: a beat here would end the watchdog's
                # first-compile grace window before the first step compiles
                resilience.note(f"epoch {epoch} start "
                                f"(batch {resume_batch})")
                try:
                    state, train_metrics = train_one_epoch(
                        epoch, train_step, state, train_loader, cfg,
                        epoch_rng, lr_scheduler=lr_scheduler, saver=saver,
                        output_dir=output_dir, meta=meta, world_size=n_dev,
                        start_batch=resume_batch, resilience=resilience,
                        telemetry=telemetry)
                except RewindRequested as e:
                    # K consecutive bad steps: continuing would train on
                    # (or EMA-blend in) corrupted state — reload the last
                    # good snapshot and fast-forward back to position.
                    # Multi-process, the verdict was max-reduced in-band
                    # (Resilience.sync_verdicts at the drain cadence), so
                    # every host raises at the SAME boundary and the
                    # collective restore stays in lockstep.
                    if jax.process_count() > 1 and not (
                            cfg.ckpt_sharded or cfg.auto_resume):
                        # rank != 0 has no output_dir on this layout
                        # (inc=True names are rank-0-local), so a per-rank
                        # restore would diverge — one rank reloading while
                        # others error is a guaranteed collective hang.
                        # The config-derived condition is identical on
                        # every host: ALL ranks abort in lockstep instead.
                        raise RuntimeError(
                            "guard rewind on a multi-process run needs a "
                            "rank-agnostic run dir: relaunch with "
                            "--auto-resume (+--experiment) or "
                            "--ckpt-sharded") from e
                    resilience.start_rewind(str(e))  # raises budget-spent
                    # load_opt=True always: a rewind restores the run's OWN
                    # snapshot (--no-resume-opt governs seeding from a
                    # foreign checkpoint), and the --no-resume-opt
                    # substitution would copy opt/step leaves out of the
                    # template — here the epoch-entry state, whose buffers
                    # the donating train step already deleted
                    restored = _restore_with_fallback(state, load_opt=True)
                    if restored is None:
                        raise RuntimeError(
                            "rewind requested but no loadable recovery "
                            "snapshot exists — enable --recovery-interval "
                            "so the guard has somewhere to rewind to"
                        ) from e
                    state, meta_r, path = restored
                    _logger.warning("rewound to %s", path)
                    if telemetry is not None:
                        telemetry.event("rewind", reason=str(e),
                                        restored_from=path)
                    if "batch_idx" in meta_r:
                        epoch = int(meta_r["epoch"])
                        resume_batch = int(meta_r["batch_idx"]) + 1
                        if resume_batch >= len(train_loader):
                            epoch += 1
                            resume_batch = 0
                    else:
                        epoch = int(meta_r.get("epoch", -1)) + 1
                        resume_batch = 0
                    if lr_scheduler is not None and resume_batch == 0 \
                            and epoch > 0:
                        # same rule as the startup resume path: an
                        # epoch-boundary re-entry re-derives the LR
                        state = set_learning_rate(
                            state, lr_scheduler.step(epoch))
                    continue
                resume_batch = 0

                eval_metrics = validate(eval_step, state, eval_loader, cfg,
                                        resilience=resilience)
                if eval_step_ema is not None:
                    # EMA eval *replaces* the metrics (reference :563-569)
                    eval_metrics = validate(eval_step_ema, state,
                                            eval_loader, cfg,
                                            log_suffix=" (EMA)",
                                            resilience=resilience)

                if lr_scheduler is not None:
                    new_lr = lr_scheduler.step(
                        epoch + 1, eval_metrics[cfg.eval_metric])  # :571-573
                    state = set_learning_rate(state, new_lr)

                if output_dir and rank == 0:
                    csv_path = os.path.join(output_dir, "summary.csv")
                    # header iff the file doesn't exist yet: an epoch
                    # counter (the old rule) or a process-local flag would
                    # append a second header mid-file on every auto-resume
                    # relaunch, corrupting the CSV for plot_csv/pandas
                    update_summary(epoch, train_metrics, eval_metrics,
                                   csv_path,
                                   os.path.join(output_dir, "plots"),
                                   write_header=not os.path.exists(csv_path))
                # sharded saver: the collective save IS the cross-host path
                # — no gather. Otherwise multi-host TP/EP: every rank
                # gathers model-sharded leaves so rank 0 can serialize;
                # no-op else
                collective = saver is not None and saver.collective
                save_state = replicate_for_save(state) \
                    if jax.process_count() > 1 and not collective else state
                if saver is not None:
                    best_metric, best_epoch = saver.save_checkpoint(
                        save_state, meta, epoch,
                        metric=eval_metrics[cfg.eval_metric])
                if telemetry is not None:
                    telemetry.event("epoch_end", epoch=epoch,
                                    train=dict(train_metrics),
                                    eval=dict(eval_metrics))
                resilience.heartbeat(f"epoch {epoch} done")
                epoch += 1
    except Preempted as e:
        # the recovery snapshot is already on disk (written synchronously
        # at the step boundary); exit with the distinct preemption code so
        # scripts/train.sh's restart wrapper relaunches into --auto-resume
        _logger.warning("%s — exiting with code %d", e, EXIT_PREEMPTED)
        exit_code = EXIT_PREEMPTED
        if telemetry is not None:
            telemetry.event("preempted", epoch=e.epoch, batch=e.batch_idx,
                            signum=e.signum)
    except KeyboardInterrupt:                      # reference :588
        pass
    finally:
        # shm-backend loaders own worker processes + a shared-memory
        # segment; release them even on interrupt (thread backend: no-op),
        # and flush any in-flight async recovery write on EVERY exit path
        # — flushing after this block skipped it on exceptions, silently
        # discarding the newest snapshot
        train_loader.close()
        eval_loader.close()
        wait_pending_saves()
        if profiler is not None:
            profiler.close()            # stops a live trace, restores SIGUSR2
        if obs_server is not None:
            obs_server.shutdown()
            obs_server.server_close()
        if telemetry is not None:
            telemetry.event("run_end", exit_code=exit_code,
                            best_metric=best_metric, best_epoch=best_epoch)
            telemetry.close()
    if exit_code is not None:
        raise SystemExit(exit_code)
    if best_metric is not None:
        _logger.info("*** Best metric: %s (epoch %s)", best_metric,
                     best_epoch)
    return {"best_metric": best_metric, "best_epoch": best_epoch,
            **eval_metrics}


def _looks_like_torch_checkpoint(path: str) -> bool:
    """Lexical suffixes torch users actually ship (.pth/.pt/.tar/.bin and
    compounds), plus a magic sniff for existing files: torch's zip format
    starts 'PK\\x03\\x04', its legacy format is a protocol-2+ pickle
    (0x80 0x02..0x05 — a flax msgpack stream can't start with that pair:
    0x80 is the EMPTY fixmap).  Cheap, runs before mesh construction."""
    if not path:
        return False
    if path.endswith((".pth", ".pth.tar", ".pt", ".tar", ".bin")):
        return True
    try:
        with open(path, "rb") as f:
            magic = f.read(4)
    except OSError:
        return False
    return magic[:4] == b"PK\x03\x04" or (
        len(magic) >= 2 and magic[0] == 0x80 and 2 <= magic[1] <= 5)


def launch_main(argv=None) -> Dict[str, float]:
    """CLI entry (reference launch_main, train.py:769-816)."""
    setup_default_logging()
    cfg = TrainConfig.from_args(argv)
    if _looks_like_torch_checkpoint(cfg.initial_checkpoint):
        # fail before mesh construction and the (relay-expensive) jitted
        # init, not minutes into main() with a cryptic msgpack error
        raise ValueError(
            f"--initial-checkpoint {cfg.initial_checkpoint} is a torch "
            "checkpoint; convert it first: python "
            "tools/convert_torch_checkpoint.py <file> <out.msgpack> "
            f"--model {cfg.model} --verify")
    if cfg.json_file:
        cluster = ClusterConfig.from_json(cfg.json_file)
        initialize_distributed(cluster, local_rank=cfg.local_rank)
    return main(cfg)


def cli(argv=None) -> None:
    """Console-script entry: discard launch_main's metrics dict so the
    setuptools wrapper's ``sys.exit(...)`` sees None (exit 0)."""
    launch_main(argv)


if __name__ == "__main__":
    launch_main(sys.argv[1:])
