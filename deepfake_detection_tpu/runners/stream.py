"""Streaming-video scoring server entrypoint.

Where ``runners/serve.py`` answers one request with one score,
this runner keeps whole *streams* alive: chunked frame ingest →
face localization + greedy-IoU tracking → per-track temporal windows of
``img_num`` distinct frames → the SAME AOT-warmed serving engine →
EMA + hysteresis verdict state machines emitting schema-versioned
events.  The device half is ``runners/serve.py``'s ``build_engine``
verbatim — fixed buckets, zero post-warmup recompiles, load shedding —
so a stream mix can never recompile or starve the engine.

Usage::

    python -m deepfake_detection_tpu.runners.stream \
        --model-path model.msgpack [--port 8378] [--img-num 4] \
        [--window-hop 4] [--fake-enter 0.8] [--localizer full_frame]

    curl -s -X POST http://127.0.0.1:8378/streams          # open
    curl -s -X POST --data-binary @chunk.mjpeg \
        -H 'Content-Type: multipart/x-mixed-replace; boundary=frame' \
        http://127.0.0.1:8378/streams/<id>/frames          # push + poll
    curl -s http://127.0.0.1:8378/streams/<id>             # status
    curl -s -X DELETE http://127.0.0.1:8378/streams/<id>   # close

Window scores on the default ``--wire float32`` are bit-identical to
scoring the same clip via ``runners/test.py --clip``
(tests/test_streaming_e2e.py pins it).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
from typing import Optional, Sequence

_logger = logging.getLogger(__name__)

__all__ = ["build_stream_server", "main"]


def build_stream_server(cfg):
    """Wire engine + batcher + dispatcher + session manager + HTTP server;
    returns the (not yet started) :class:`StreamServer`."""
    from ..streaming.ingest import StreamManager, make_stream_server
    from ..streaming.metrics import StreamingMetrics
    from ..streaming.windows import WindowDispatcher
    from .serve import build_engine

    engine, batcher, serving_metrics = build_engine(cfg)
    metrics = StreamingMetrics()
    manager_box = []

    def on_result(job, scores, error):
        manager_box[0].on_result(job, scores, error)

    def on_drop(job, reason):
        manager_box[0].on_drop(job, reason)

    dispatcher = WindowDispatcher(
        batcher, max_pending=cfg.max_inflight_windows,
        request_timeout_s=cfg.request_timeout_ms / 1000.0,
        on_result=on_result, on_drop=on_drop)
    manager = StreamManager(cfg, dispatcher, metrics,
                            image_size=cfg.image_size, wire=cfg.wire)
    manager_box.append(manager)
    server = make_stream_server(cfg.host, cfg.port, manager, engine,
                                serving_metrics, metrics)
    server.batcher = batcher
    server.dispatcher = dispatcher
    return server


def main(argv: Optional[Sequence[str]] = None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    # the serving runner's GIL-switch tuning: many ingest threads + the
    # engine share few cores
    sys.setswitchinterval(0.002)
    from ..config import StreamConfig
    cfg = StreamConfig.from_args(argv)
    if cfg.single_thread_xla:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_cpu_multi_thread_eigen" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_cpu_multi_thread_eigen=false").strip()
    server = build_stream_server(cfg)
    if cfg.state_dir:
        # resume sessions a previous instance snapshotted on its way down
        # — BEFORE traffic starts, so the first chunk of a resumed stream
        # continues its verdict machines instead of resetting them
        restored = server.manager.restore_state(cfg.state_dir)
        if restored:
            _logger.info("restored %d stream session(s) from %s",
                         restored, cfg.state_dir)
    server.engine.start(server.batcher)
    server.dispatcher.start()
    server.manager.start_evictor()

    stop = threading.Event()

    def _sig(signum, frame):
        _logger.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    host, port = server.server_address[:2]
    _logger.info(
        "streaming on http://%s:%d (POST /streams, POST|GET|DELETE "
        "/streams/<id>[/frames], GET /healthz /readyz /metrics) — "
        "localizer=%s img_num=%d hop=%d wire=%s", host, port,
        cfg.localizer, cfg.img_num,
        cfg.window_hop or cfg.img_num * cfg.window_stride, cfg.wire)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True)
    t.start()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        server.shutdown()
        # quiesce result delivery BEFORE snapshotting: a window score
        # folding in after its session was serialized would desync the
        # snapshot from the event log (the snapshot books in-flight
        # windows dropped — nothing may score behind its back)
        server.dispatcher.stop()
        if cfg.state_dir:
            # snapshot BEFORE the manager closes sessions: a SIGTERM
            # bounce must resume these verdict streams, not reset them
            server.manager.save_state(cfg.state_dir)
        server.manager.shutdown()
        server.engine.stop()
        server.batcher.close()
        server.server_close()
        _logger.info("bye")


if __name__ == "__main__":
    main(sys.argv[1:])
