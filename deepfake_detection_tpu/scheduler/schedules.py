"""LR schedulers.

Re-design of ``/root/reference/dfd/timm/scheduler/`` (scheduler.py, step_lr.py,
cosine_lr.py, tanh_lr.py, plateau_lr.py).  The reference's scheduler family is
already "as stateless as possible" — ``_get_lr(t)`` is a pure function of the
epoch/update index — so here each scheduler IS a pure ``lr(t)`` function plus
a thin host-side driver that keeps the epoch/update bookkeeping and the
(inherently stateful) plateau logic.

The produced lr is a plain Python float the runner writes into
``opt_state.hyperparams['learning_rate']`` (optax ``inject_hyperparams``) or
passes as a scalar argument to the jitted train step — either way no
recompilation, mirroring the reference's in-place ``param_group['lr']``
rewrite (scheduler.py:81-85).

Dual granularity kept (scheduler.py:67-79): ``step(epoch, metric)`` at epoch
end, ``step_update(num_updates)`` after each optimizer update; a scheduler
listens on one of the two depending on ``t_in_epochs``.

Seeded LR noise (scheduler.py:87-105): per-t RNG seeded with ``seed + t``,
normal resampled until ``|n| < noise_pct`` (or uniform in ±noise_pct), applied
multiplicatively ``lr * (1 + n)``.  Numeric parity with torch's generator is
not possible (different bit generators); semantics and distribution match.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = [
    "StepSchedule", "CosineSchedule", "TanhSchedule", "PlateauSchedule",
    "Scheduler",
]


class Scheduler:
    """Base: pure ``_get_lr(t)`` + noise + epoch/update dispatch."""

    #: set True in a subclass whose ``step_update`` consumes ``metric`` —
    #: the trainer then drains its buffered device metrics first so the
    #: value is fresh (train/trainer.py), at the cost of a host sync per
    #: optimizer update
    wants_update_metric: bool = False

    def __init__(self, base_lr: float, t_in_epochs: bool = True,
                 noise_range_t=None, noise_type: str = "normal",
                 noise_pct: float = 0.67, noise_std: float = 1.0,
                 noise_seed: int = 42):
        self.base_lr = float(base_lr)
        self.t_in_epochs = t_in_epochs
        self.noise_range_t = noise_range_t
        self.noise_type = noise_type
        self.noise_pct = noise_pct
        self.noise_std = noise_std
        self.noise_seed = noise_seed
        self.last_lr = float(base_lr)

    # -- override -----------------------------------------------------------
    def _get_lr(self, t: int) -> float:
        raise NotImplementedError

    # -- public API (scheduler.py:67-79) ------------------------------------
    def step(self, epoch: int, metric: Optional[float] = None) -> float:
        """Call at epoch end with next epoch index; returns the lr to use."""
        if self.t_in_epochs:
            self.last_lr = self._add_noise(self._get_lr(epoch), epoch)
        return self.last_lr

    def step_update(self, num_updates: int,
                    metric: Optional[float] = None) -> float:
        if not self.t_in_epochs:
            self.last_lr = self._add_noise(self._get_lr(num_updates),
                                           num_updates)
        return self.last_lr

    # -- noise (scheduler.py:87-105) ----------------------------------------
    def _in_noise_range(self, t: int) -> bool:
        r = self.noise_range_t
        if r is None:
            return False
        if isinstance(r, (list, tuple)):
            return r[0] <= t < r[1]
        return t >= r

    def _add_noise(self, lr: float, t: int) -> float:
        if not self._in_noise_range(t):
            return lr
        rng = np.random.default_rng(self.noise_seed + t)
        if self.noise_type == "normal":
            while True:
                noise = float(rng.standard_normal() * self.noise_std)
                if abs(noise) < self.noise_pct:
                    break
        else:
            noise = 2 * (float(rng.random()) - 0.5) * self.noise_pct
        return lr + lr * noise

    # -- checkpointing ------------------------------------------------------
    def state_dict(self) -> dict:
        return {"last_lr": self.last_lr}

    def load_state_dict(self, sd: dict) -> None:
        self.last_lr = sd.get("last_lr", self.last_lr)


def _warmup(t: int, warmup_t: int, warmup_lr_init: float,
            warmup_step: float) -> float:
    return warmup_lr_init + t * warmup_step


class StepSchedule(Scheduler):
    """Linear warmup then ``base * decay_rate ** (t // decay_t)``
    (step_lr.py:40-45).  The canonical run: decay_t=2, decay_rate=0.92."""

    def __init__(self, base_lr: float, decay_t: float, decay_rate: float = 1.0,
                 warmup_t: int = 0, warmup_lr_init: float = 0.0, **kw):
        super().__init__(base_lr, **kw)
        self.decay_t = decay_t
        self.decay_rate = decay_rate
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_step = ((base_lr - warmup_lr_init) / warmup_t
                            if warmup_t else 1.0)
        if warmup_t:
            self.last_lr = warmup_lr_init

    def _get_lr(self, t: int) -> float:
        if t < self.warmup_t:
            return _warmup(t, self.warmup_t, self.warmup_lr_init,
                           self.warmup_step)
        return self.base_lr * (self.decay_rate ** (t // self.decay_t))


class _CyclicSchedule(Scheduler):
    """Shared restart/cycle plumbing of cosine_lr.py / tanh_lr.py."""

    def __init__(self, base_lr: float, t_initial: int, t_mul: float = 1.0,
                 lr_min: float = 0.0, decay_rate: float = 1.0,
                 warmup_t: int = 0, warmup_lr_init: float = 0.0,
                 warmup_prefix: bool = False, cycle_limit: int = 0, **kw):
        super().__init__(base_lr, **kw)
        assert t_initial > 0 and lr_min >= 0
        self.t_initial = t_initial
        self.t_mul = t_mul
        self.lr_min = lr_min
        self.decay_rate = decay_rate
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_prefix = warmup_prefix
        self.cycle_limit = cycle_limit
        self.warmup_step = ((base_lr - warmup_lr_init) / warmup_t
                            if warmup_t else 1.0)
        if warmup_t:
            self.last_lr = warmup_lr_init

    def _cycle(self, t: int):
        """(cycle index i, position in cycle t_curr, cycle length t_i)."""
        if self.t_mul != 1:
            i = math.floor(math.log(1 - t / self.t_initial * (1 - self.t_mul),
                                    self.t_mul))
            t_i = self.t_mul ** i * self.t_initial
            t_curr = t - (1 - self.t_mul ** i) / (1 - self.t_mul) * self.t_initial
        else:
            i = t // self.t_initial
            t_i = self.t_initial
            t_curr = t - self.t_initial * i
        return i, t_curr, t_i

    def get_cycle_length(self, cycles: int = 0) -> int:
        cycles = cycles or self.cycle_limit
        assert cycles > 0
        if self.t_mul == 1.0:
            return self.t_initial * cycles
        return int(math.floor(-self.t_initial * (self.t_mul ** cycles - 1)
                              / (1 - self.t_mul)))

    def _get_lr(self, t: int) -> float:
        if t < self.warmup_t:
            return _warmup(t, self.warmup_t, self.warmup_lr_init,
                           self.warmup_step)
        if self.warmup_prefix:
            t = t - self.warmup_t
        i, t_curr, t_i = self._cycle(t)
        if self.cycle_limit and i >= self.cycle_limit:
            return self._exhausted_lr()
        gamma = self.decay_rate ** i
        return self._cycle_lr(self.base_lr * gamma, self.lr_min * gamma,
                              t_curr / t_i)

    def _cycle_lr(self, lr_max: float, lr_min: float, frac: float) -> float:
        raise NotImplementedError

    def _exhausted_lr(self) -> float:
        return self.lr_min


class CosineSchedule(_CyclicSchedule):
    """SGDR cosine decay with restarts (cosine_lr.py:12-110)."""

    def _cycle_lr(self, lr_max, lr_min, frac):
        return lr_min + 0.5 * (lr_max - lr_min) * (1 + math.cos(math.pi * frac))


class TanhSchedule(_CyclicSchedule):
    """Hyperbolic-tangent decay (tanh_lr.py:12-115), bounds lb=-6, ub=4."""

    def __init__(self, base_lr: float, t_initial: int, lb: float = -6.0,
                 ub: float = 4.0, **kw):
        assert lb < ub
        self.lb, self.ub = lb, ub
        super().__init__(base_lr, t_initial, **kw)

    def _cycle_lr(self, lr_max, lr_min, frac):
        return lr_min + 0.5 * (lr_max - lr_min) * (
            1 - math.tanh(self.lb * (1.0 - frac) + self.ub * frac))

    def _exhausted_lr(self):
        return self.lr_min * (self.decay_rate ** self.cycle_limit)


class PlateauSchedule(Scheduler):
    """Decay when the eval metric plateaus (plateau_lr.py:6-60).

    Re-implements torch ReduceLROnPlateau semantics (mode=min, rel threshold)
    with explicit state so it checkpoints cleanly.
    """

    def __init__(self, base_lr: float, decay_rate: float = 0.1,
                 patience_t: int = 10, threshold: float = 1e-4,
                 cooldown_t: int = 0, warmup_t: int = 0,
                 warmup_lr_init: float = 0.0, lr_min: float = 0.0,
                 mode: str = "min", **kw):
        super().__init__(base_lr, **kw)
        self.decay_rate = decay_rate
        self.patience_t = patience_t
        self.threshold = threshold
        self.cooldown_t = cooldown_t
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.lr_min = lr_min
        self.mode = mode
        self.warmup_step = ((base_lr - warmup_lr_init) / warmup_t
                            if warmup_t else 1.0)
        self.best: Optional[float] = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.current_lr = base_lr if not warmup_t else warmup_lr_init
        self.last_lr = self.current_lr

    def _is_better(self, metric: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return metric < self.best * (1 - self.threshold)
        return metric > self.best * (1 + self.threshold)

    def step(self, epoch: int, metric: Optional[float] = None) -> float:
        if epoch <= self.warmup_t and self.warmup_t:
            self.last_lr = _warmup(epoch, self.warmup_t, self.warmup_lr_init,
                                   self.warmup_step)
            return self.last_lr
        if metric is not None:
            if self._is_better(metric):
                self.best = metric
                self.num_bad = 0
            else:
                self.num_bad += 1
            # torch semantics: cooldown ticks down every epoch it is active,
            # improving or not, and bad epochs inside it don't count
            if self.cooldown_counter > 0:
                self.cooldown_counter -= 1
                self.num_bad = 0
            if self.cooldown_counter == 0 and self.num_bad > self.patience_t:
                self.current_lr = max(self.current_lr * self.decay_rate,
                                      self.lr_min)
                self.cooldown_counter = self.cooldown_t
                self.num_bad = 0
        self.last_lr = self.current_lr
        return self.last_lr

    def state_dict(self) -> dict:
        return {"best": self.best, "num_bad": self.num_bad,
                "cooldown_counter": self.cooldown_counter,
                "current_lr": self.current_lr, "last_lr": self.last_lr}

    def load_state_dict(self, sd: dict) -> None:
        self.__dict__.update({k: v for k, v in sd.items()
                              if k in self.state_dict()})
