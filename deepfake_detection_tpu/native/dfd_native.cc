// Native data-loader core: threaded JPEG decode (file → RGB) for the input
// pipeline.
//
// Role in the framework: SURVEY.md §7 hard part #4 — the flagship config
// feeds 4 JPEG frames per sample at 600²×3 each; at ≥70% MFU the host must
// decode ~50 MB/s/chip of JPEG without stalling device dispatch.  The
// reference leans on torch's C++ DataLoader worker processes (multiprocess
// fork + pickle IPC).  Here the equivalent is an in-process C++ thread pool:
// decode happens outside the GIL (ctypes releases it during the call), frames
// of one clip decode in parallel, and there is no serialization overhead.
//
// Functionality:
//   * libjpeg decode with DCT-domain scaling (scale_denom ∈ {1,2,4,8}):
//     decoding directly to 1/2, 1/4, 1/8 size is ~4/16/64× cheaper than
//     decode-then-resize, which the PIL path (and the reference) pays.
//   * persistent worker pool with a simple mutex/condvar work queue.
//   * pure C ABI (no pybind11 in this image) — consumed via ctypes from
//     deepfake_detection_tpu/data/native.py.
//
// Build: g++ -O3 -shared -fPIC dfd_native.cc -ljpeg -lpthread
// (driven by data/native.py on first import; see _build_library there).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>  // requires size_t/FILE declared first

#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// single-image decode
// ---------------------------------------------------------------------------

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void silent_output(j_common_ptr) {}  // drop libjpeg warnings from stderr

// Decode a JPEG byte buffer to tightly-packed RGB8.  Returns a malloc'd
// buffer (caller frees via dfd_free) or nullptr on any decode error.
uint8_t* decode_buffer(const uint8_t* data, size_t size, int scale_denom,
                       int* out_w, int* out_h) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  // volatile: modified between setjmp and longjmp — without it the
  // error-path free() would see an indeterminate value and leak every
  // corrupt frame's row buffer
  uint8_t* volatile out = nullptr;

  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(out);
    return nullptr;
  }

  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = 1;
  cinfo.scale_denom = scale_denom > 0 ? scale_denom : 1;
  // trade fidelity knobs the same direction PIL's draft mode does
  cinfo.dct_method = JDCT_ISLOW;
  jpeg_start_decompress(&cinfo);

  const int w = static_cast<int>(cinfo.output_width);
  const int h = static_cast<int>(cinfo.output_height);
  const int stride = w * 3;
  out = static_cast<uint8_t*>(std::malloc(static_cast<size_t>(stride) * h));
  if (!out) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out_w = w;
  *out_h = h;
  return out;
}

uint8_t* decode_file(const char* path, int scale_denom, int* w, int* h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long len = std::ftell(f);
  if (len <= 0) {
    std::fclose(f);
    return nullptr;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(len));
  size_t got = std::fread(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  if (got != buf.size()) return nullptr;
  return decode_buffer(buf.data(), buf.size(), scale_denom, w, h);
}

}  // namespace

void dfd_free(uint8_t* p) { std::free(p); }

// Bumped on any signature change; the python bridge refuses to drive a
// stale .so whose symbols still resolve but whose argument layout moved
// (extern "C" has no mangling to catch that).
// v3: warp functions take source pixel strides, so packed-cache mmap
// channel-slice views ((H, W, 3k) clips) warp without a contiguous copy.
int dfd_abi_version(void) { return 3; }

// ---------------------------------------------------------------------------
// affine warp (bilinear, RGB8, black fill)
// ---------------------------------------------------------------------------
//
// One-pass replacement for the host pipeline's rotate→flip→resize→crop
// chain (data/transforms.py::MultiFusedGeometric): coef = (A,B,C,D,E,F)
// maps output pixel (x, y) to source coords (A·x+B·y+C, D·x+E·y+F); taps
// outside the source read as black, matching PIL's expand/pad fill.

namespace {

// dst_stride: bytes between consecutive output PIXELS (3 for a tight RGB
// buffer; 3*num_frames when each frame writes its channel slice of a packed
// (H, W, 3*F) clip so the loader never pays a concat copy).
// src_stride: same, for the SOURCE — 3 for a tight buffer, 3*num_frames
// when the source is a channel-slice view of a packed clip (e.g. the
// packed-cache mmap views), so reading pays no ascontiguousarray copy
// either.  Source rows are assumed dense: row stride == sw * src_stride,
// which holds for any channel slice of a C-contiguous (H, W, 3*F) array.
void warp_affine_rgb8(const uint8_t* src, int sw, int sh, int src_stride,
                      uint8_t* dst, int dw, int dh, int dst_stride,
                      const double* coef) {
  // 16.16 fixed point: source coords step by a constant per output x, so
  // the whole inner loop is integer adds/shifts; weights use 8 fractional
  // bits (wx*wy fits 16) — ±1 LSB vs float bilinear, invisible after the
  // uint8 round.
  const int64_t kOne = 1 << 16;
  const int64_t Ai = static_cast<int64_t>(std::llround(coef[0] * kOne));
  const int64_t Di = static_cast<int64_t>(std::llround(coef[3] * kOne));
  const size_t ss = static_cast<size_t>(src_stride > 0 ? src_stride : 3);
  const size_t src_row = static_cast<size_t>(sw) * ss;
  for (int y = 0; y < dh; ++y) {
    int64_t sx = static_cast<int64_t>(
        std::llround((coef[1] * y + coef[2]) * kOne));
    int64_t sy = static_cast<int64_t>(
        std::llround((coef[4] * y + coef[5]) * kOne));
    uint8_t* row = dst + static_cast<size_t>(y) * dw * dst_stride;
    for (int x = 0; x < dw; ++x, sx += Ai, sy += Di) {
      const int x0 = static_cast<int>(sx >> 16);   // floor for sx >= 0 ...
      const int y0 = static_cast<int>(sy >> 16);   // ... and for sx < 0 too
      uint8_t* px = row + static_cast<size_t>(dst_stride) * x;
      const uint32_t wx1 = (sx >> 8) & 0xff, wx0 = 256 - wx1;
      const uint32_t wy1 = (sy >> 8) & 0xff, wy0 = 256 - wy1;
      const uint8_t* r0 = src + static_cast<size_t>(y0) * src_row +
                          static_cast<size_t>(x0) * ss;
      if (x0 >= 0 && y0 >= 0 && x0 + 1 < sw && y0 + 1 < sh) {
        // fast path: all four taps in bounds (the vast majority)
        const uint8_t* r1 = r0 + src_row;
        const uint32_t w00 = wx0 * wy0, w10 = wx1 * wy0;
        const uint32_t w01 = wx0 * wy1, w11 = wx1 * wy1;
        px[0] = static_cast<uint8_t>((w00 * r0[0] + w10 * r0[ss] +
                                      w01 * r1[0] + w11 * r1[ss] +
                                      32768) >> 16);
        px[1] = static_cast<uint8_t>((w00 * r0[1] + w10 * r0[ss + 1] +
                                      w01 * r1[1] + w11 * r1[ss + 1] +
                                      32768) >> 16);
        px[2] = static_cast<uint8_t>((w00 * r0[2] + w10 * r0[ss + 2] +
                                      w01 * r1[2] + w11 * r1[ss + 2] +
                                      32768) >> 16);
        continue;
      }
      if (x0 < -1 || y0 < -1 || x0 >= sw || y0 >= sh) {
        px[0] = px[1] = px[2] = 0;
        continue;
      }
      // boundary: taps outside read as black
      const bool in_x0 = x0 >= 0, in_x1 = x0 + 1 < sw;
      const bool in_y0 = y0 >= 0, in_y1 = y0 + 1 < sh;
      const uint8_t* r1 = r0 + src_row;
      for (size_t c = 0; c < 3; ++c) {
        uint32_t v = 0;
        if (in_y0) {
          if (in_x0) v += wx0 * wy0 * r0[c];
          if (in_x1) v += wx1 * wy0 * r0[ss + c];
        }
        if (in_y1) {
          if (in_x0) v += wx0 * wy1 * r1[c];
          if (in_x1) v += wx1 * wy1 * r1[ss + c];
        }
        px[c] = static_cast<uint8_t>((v + 32768) >> 16);
      }
    }
  }
}

}  // namespace

void dfd_warp_affine(const uint8_t* src, int sw, int sh, int src_stride,
                     uint8_t* dst, int dw, int dh, int dst_stride,
                     const double* coef) {
  warp_affine_rgb8(src, sw, sh, src_stride > 0 ? src_stride : 3, dst, dw,
                   dh, dst_stride > 0 ? dst_stride : 3, coef);
}

uint8_t* dfd_decode_jpeg(const uint8_t* data, size_t size, int scale_denom,
                         int* out_w, int* out_h) {
  return decode_buffer(data, size, scale_denom, out_w, out_h);
}

uint8_t* dfd_decode_jpeg_file(const char* path, int scale_denom, int* out_w,
                              int* out_h) {
  return decode_file(path, scale_denom, out_w, out_h);
}

// ---------------------------------------------------------------------------
// worker pool
// ---------------------------------------------------------------------------

namespace {

class Pool {
 public:
  explicit Pool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this] { Run(); });
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Run() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

struct Latch {
  explicit Latch(int n) : count(n) {}
  void Done() {
    std::unique_lock<std::mutex> lk(mu);
    if (--count == 0) cv.notify_all();
  }
  void Wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return count == 0; });
  }
  int count;
  std::mutex mu;
  std::condition_variable cv;
};

}  // namespace

void* dfd_pool_new(int n_threads) {
  if (n_threads < 1) n_threads = 1;
  return new Pool(n_threads);
}

void dfd_pool_free(void* pool) { delete static_cast<Pool*>(pool); }

// Decode n files in parallel on the pool; blocks until all complete.
// outs[i] = malloc'd RGB buffer or nullptr; ws/hs filled per image.
void dfd_pool_decode_files(void* pool, int n, const char** paths,
                           int scale_denom, uint8_t** outs, int* ws,
                           int* hs) {
  Pool* p = static_cast<Pool*>(pool);
  Latch latch(n);
  for (int i = 0; i < n; ++i) {
    p->Submit([&, i] {
      outs[i] = decode_file(paths[i], scale_denom, &ws[i], &hs[i]);
      latch.Done();
    });
  }
  latch.Wait();
}

// Same, over in-memory buffers.
void dfd_pool_decode_buffers(void* pool, int n, const uint8_t** datas,
                             const size_t* sizes, int scale_denom,
                             uint8_t** outs, int* ws, int* hs) {
  Pool* p = static_cast<Pool*>(pool);
  Latch latch(n);
  for (int i = 0; i < n; ++i) {
    p->Submit([&, i] {
      outs[i] = decode_buffer(datas[i], sizes[i], scale_denom, &ws[i],
                              &hs[i]);
      latch.Done();
    });
  }
  latch.Wait();
}

// Warp n same-coef frames in parallel (one clip's frames share the draw).
// dsts[i] must be preallocated writable buffers honoring dst_stride: tight
// dw*dh*3 allocations with dst_stride=3, or interior pointers (base + 3*i)
// into ONE dw*dh*3*n packed clip with dst_stride=3*n.  src_strides[i] is
// the per-frame SOURCE pixel stride (nullptr or 0 entries mean tight RGB):
// channel-slice views of a packed (H, W, 3*F) clip pass 3*F and skip the
// contiguous staging copy.
void dfd_pool_warp_affine(void* pool, int n, const uint8_t** srcs,
                          const int* sws, const int* shs,
                          const int* src_strides, uint8_t** dsts,
                          int dw, int dh, int dst_stride,
                          const double* coef) {
  Pool* p = static_cast<Pool*>(pool);
  const int stride = dst_stride > 0 ? dst_stride : 3;
  Latch latch(n);
  for (int i = 0; i < n; ++i) {
    p->Submit([&, i] {
      const int ss = src_strides != nullptr && src_strides[i] > 0
                         ? src_strides[i]
                         : 3;
      warp_affine_rgb8(srcs[i], sws[i], shs[i], ss, dsts[i], dw, dh,
                       stride, coef);
      latch.Done();
    });
  }
  latch.Wait();
}

}  // extern "C"
