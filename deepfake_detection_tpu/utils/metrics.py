"""Metrics.

Parity with ``/root/reference/dfd/timm/utils.py``: ``AverageMeter`` (:152),
``accuracy`` top-k percentage (:170-186).  ``accuracy`` is pure jnp so it runs
*inside* the jitted train/eval step; the reference instead pulled logits to
Python each step.  Cross-replica averaging is a ``lax.pmean`` at the call
site, replacing ``reduce_tensor`` (:256-260).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

__all__ = ["AverageMeter", "LatencyHistogram", "accuracy", "auc",
           "masked_mean"]


class AverageMeter:
    """Running average (reference :152-167)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0.0

    def update(self, val: float, n: float = 1) -> None:
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


#: moved to utils/prometheus.py (the jax-free observability floor the
#: fleet router shares); re-exported here for existing callers
from .prometheus import LatencyHistogram  # noqa: E402,F401


def accuracy(output: jnp.ndarray, target: jnp.ndarray,
             topk: Sequence[int] = (1,),
             weight: Optional[jnp.ndarray] = None
             ) -> Union[jnp.ndarray, list]:
    """Top-k precision in percent (reference :170-186).

    Soft targets (same shape as output) collapse to their argmax, matching the
    reference's mixup path (:177-178).  ``weight`` masks padded eval samples
    (the reference's duplicated-sample error doesn't exist here).
    """
    maxk = max(topk)
    if target.shape == output.shape:
        target = jnp.argmax(target, axis=-1)
    # top-k indices, descending
    pred = jnp.argsort(-output, axis=-1)[:, :maxk]            # (B, maxk)
    correct = pred == target[:, None]                          # (B, maxk)
    if weight is None:
        denom = target.shape[0]
        w = 1.0
    else:
        w = weight[:, None].astype(jnp.float32)
        denom = jnp.maximum(weight.sum(), 1)
    accs = [(correct[:, :k] * w).sum() * 100.0 / denom for k in topk]
    return accs[0] if len(topk) == 1 else accs


def auc(scores: jnp.ndarray, labels: jnp.ndarray,
        weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """ROC AUC via the rank statistic (Mann–Whitney U).

    The reference never computes AUC in code, but its released checkpoint is
    evaluated by AUC (README.md:35-40) and the north-star quality gate is
    "AUC ≥ the released GPU checkpoint" (BASELINE.md) — so the framework
    ships the metric.  Pure jnp, O(n log n), static-shaped (ties get the
    usual midrank treatment), so it can run inside a jitted eval epoch.

    ``weight`` is a {0, 1} VALIDITY MASK (padded samples from the ordered
    sharded eval sampler), not a general sample weight: midranks are
    computed unweighted, so fractional weights would silently produce a
    wrong AUC.  Anything > 0 is treated as valid.

    ``scores``: higher ⇒ more positive; ``labels``: {0, 1}.
    """
    scores = scores.astype(jnp.float32).reshape(-1)
    labels = labels.reshape(-1)
    w = (jnp.ones_like(scores) if weight is None
         else (weight.reshape(-1) > 0).astype(jnp.float32))
    # midranks of the scores, computed without dynamic shapes: for each
    # element, rank = (#strictly-smaller) + (#equal + 1) / 2, with masked
    # entries pushed out of the comparison by ±inf on either side
    s = jnp.where(w > 0, scores, jnp.inf)
    order = jnp.argsort(s)
    sorted_s = s[order]
    n = scores.shape[0]
    first = jnp.searchsorted(sorted_s, sorted_s, side="left")
    last = jnp.searchsorted(sorted_s, sorted_s, side="right")
    midrank_sorted = (first + last + 1) / 2.0          # 1-based midranks
    ranks = jnp.zeros(n).at[order].set(midrank_sorted)
    pos = (labels > 0).astype(jnp.float32) * w
    neg = (labels == 0).astype(jnp.float32) * w
    n_pos = pos.sum()
    n_neg = neg.sum()
    u = (ranks * pos).sum() - n_pos * (n_pos + 1) / 2.0
    return u / jnp.maximum(n_pos * n_neg, 1.0)


def masked_mean(x: jnp.ndarray, weight: Optional[jnp.ndarray] = None
                ) -> jnp.ndarray:
    """Mean over valid entries (padded-eval masking helper)."""
    if weight is None:
        return x.mean()
    w = weight.astype(x.dtype)
    return (x * w).sum() / jnp.maximum(w.sum(), 1.0)
