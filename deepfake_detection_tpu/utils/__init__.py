"""Training utilities (SURVEY.md §2.6): metrics, EMA, reporting, logging.

PEP-562 lazy exports (the ``data/``/``obs/``/``serving/`` idiom): the
package itself imports nothing, so jax-free consumers — the fleet
router's ``utils.prometheus`` use is the motivating one (dfdlint
DFD001) — can reach the stdlib-pure submodules without paying for (or
accidentally loading) the jax-importing ones (``metrics``, ``ema``).
"""

from __future__ import annotations

_LAZY = {
    "AverageMeter": "metrics",
    "LatencyHistogram": "metrics",
    "accuracy": "metrics",
    "auc": "metrics",
    "masked_mean": "metrics",
    "init_ema": "ema",
    "update_ema": "ema",
    "FormatterNoInfo": "log",
    "setup_default_logging": "log",
    "get_outdir": "summary",
    "natural_key": "summary",
    "plot_csv": "summary",
    "update_summary": "summary",
    "Counter": "prometheus",
    "PromText": "prometheus",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
