"""Prometheus text-exposition rendering, shared by serving and training.

The serving subsystem grew the first renderer inline (serving/metrics.py);
the training telemetry subsystem (obs/telemetry.py) exposes the same
``GET /metrics`` surface, so the formatting lives here once.  Everything
is stdlib — no prometheus_client dependency, just the text format
(https://prometheus.io/docs/instrumenting/exposition_formats/).

:class:`PromText` is a line accumulator: callers append counter/gauge/
histogram families in catalog order and :meth:`render` joins them.  The
helpers reproduce the serving renderer's byte layout exactly (HELP/TYPE
re-emitted per histogram label set, ``le`` bounds formatted with
``repr``), locked by the byte-identity test in tests/test_obs.py — a
scrape-side dashboard must not notice the refactor.
"""

from __future__ import annotations

import bisect
import threading
from typing import List, Sequence, Tuple

__all__ = ["Counter", "LatencyHistogram", "PromText"]


class Counter:
    """Monotonic counter; int ops under the GIL are atomic enough, the lock
    is for the read-modify-write of labeled maps."""

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class LatencyHistogram:
    """Thread-safe fixed-bucket latency histogram (host side, stdlib only).

    The serving-path companion to ``utils.metrics.AverageMeter``: where
    the meter tracks a running average inside the train loop, the
    histogram tracks the full latency distribution of a long-lived server
    (:class:`PromText` renders it in Prometheus ``histogram`` text
    format, so the bucket layout is cumulative-``le`` by construction).

    Lives here — not utils/metrics.py, its original home — because this
    module is the jax-free floor of the observability stack: the fleet
    router (dfdlint DFD001: never imports jax) and the serving/streaming
    registries share it.  ``utils.metrics.LatencyHistogram`` remains as a
    re-export for existing callers.

    Buckets are upper bounds in seconds; observations above the last bound
    land in the implicit ``+Inf`` bucket.
    """

    #: default bounds: 1 ms .. 30 s, roughly log-spaced (Prometheus idiom)
    DEFAULT_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                      0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS):
        self.bounds: Tuple[float, ...] = tuple(sorted(float(b)
                                                      for b in bounds))
        if not self.bounds:
            raise ValueError("LatencyHistogram needs at least one bound")
        self._counts = [0] * (len(self.bounds) + 1)   # [+Inf] is last
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        i = bisect.bisect_left(self.bounds, seconds)
        with self._lock:
            self._counts[i] += 1
            self.sum += seconds
            self.count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) — one consistent view."""
        with self._lock:
            return list(self._counts), self.sum, self.count

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count), ...] with +Inf last (le=inf)."""
        counts, _, _ = self.snapshot()
        out, acc = [], 0
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Approximate quantile: upper bound of the bucket holding rank q
        (the resolution any fixed-bucket histogram has; good enough for a
        p50/p95/p99 serving report)."""
        counts, _, total = self.snapshot()
        if total == 0:
            return float("nan")
        rank = q * total
        acc = 0
        for b, c in zip(self.bounds, counts):
            acc += c
            if acc >= rank:
                return b
        return float("inf")

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self.sum = 0.0
            self.count = 0


class PromText:
    """Accumulates one exposition document under a metric-name prefix."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lines: List[str] = []

    # -- raw pieces (labeled families interleave header and samples) ----
    def header(self, name: str, help_: str, type_: str) -> None:
        self.lines.append(f"# HELP {self.prefix}_{name} {help_}")
        self.lines.append(f"# TYPE {self.prefix}_{name} {type_}")

    def sample(self, name: str, labels: str, value) -> None:
        self.lines.append(f"{self.prefix}_{name}{labels} {value}")

    # -- one-shot families ---------------------------------------------
    def counter(self, name: str, help_: str, value, labels: str = "") -> None:
        self.header(name, help_, "counter")
        self.sample(name, labels, value)

    def gauge(self, name: str, help_: str, value) -> None:
        self.header(name, help_, "gauge")
        self.sample(name, "", value)

    def histogram(self, name: str, help_, hist, labels: str = "") -> None:
        """One ``histogram`` family block from a LatencyHistogram.

        ``labels`` is the pre-formatted inner label list (e.g.
        ``'stage="queue"'``).  Buckets, sum and count come from ONE
        snapshot: mixing live reads could make the +Inf bucket exceed
        ``_count`` within a single exposition (spec violation that breaks
        ``histogram_quantile`` exactly under load).
        """
        full = f"{self.prefix}_{name}"
        self.lines.append(f"# HELP {full} {help_}")
        self.lines.append(f"# TYPE {full} histogram")
        counts, s, c = hist.snapshot()
        pre = f"{labels}," if labels else ""
        acc = 0
        for bound, n in zip(hist.bounds, counts):
            acc += n
            self.lines.append(f'{full}_bucket{{{pre}le="{bound!r}"}} {acc}')
        self.lines.append(f'{full}_bucket{{{pre}le="+Inf"}} {c}')
        self.lines.append(f'{full}_sum{{{labels}}} {s}')
        self.lines.append(f'{full}_count{{{labels}}} {c}')

    # ------------------------------------------------------------------
    def render(self) -> str:
        return "\n".join(self.lines) + "\n"
