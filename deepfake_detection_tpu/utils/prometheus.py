"""Prometheus text-exposition rendering, shared by serving and training.

The serving subsystem grew the first renderer inline (serving/metrics.py);
the training telemetry subsystem (obs/telemetry.py) exposes the same
``GET /metrics`` surface, so the formatting lives here once.  Everything
is stdlib — no prometheus_client dependency, just the text format
(https://prometheus.io/docs/instrumenting/exposition_formats/).

:class:`PromText` is a line accumulator: callers append counter/gauge/
histogram families in catalog order and :meth:`render` joins them.  The
helpers reproduce the serving renderer's byte layout exactly (HELP/TYPE
re-emitted per histogram label set, ``le`` bounds formatted with
``repr``), locked by the byte-identity test in tests/test_obs.py — a
scrape-side dashboard must not notice the refactor.
"""

from __future__ import annotations

import threading
from typing import List

__all__ = ["Counter", "PromText"]


class Counter:
    """Monotonic counter; int ops under the GIL are atomic enough, the lock
    is for the read-modify-write of labeled maps."""

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class PromText:
    """Accumulates one exposition document under a metric-name prefix."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lines: List[str] = []

    # -- raw pieces (labeled families interleave header and samples) ----
    def header(self, name: str, help_: str, type_: str) -> None:
        self.lines.append(f"# HELP {self.prefix}_{name} {help_}")
        self.lines.append(f"# TYPE {self.prefix}_{name} {type_}")

    def sample(self, name: str, labels: str, value) -> None:
        self.lines.append(f"{self.prefix}_{name}{labels} {value}")

    # -- one-shot families ---------------------------------------------
    def counter(self, name: str, help_: str, value, labels: str = "") -> None:
        self.header(name, help_, "counter")
        self.sample(name, labels, value)

    def gauge(self, name: str, help_: str, value) -> None:
        self.header(name, help_, "gauge")
        self.sample(name, "", value)

    def histogram(self, name: str, help_, hist, labels: str = "") -> None:
        """One ``histogram`` family block from a LatencyHistogram.

        ``labels`` is the pre-formatted inner label list (e.g.
        ``'stage="queue"'``).  Buckets, sum and count come from ONE
        snapshot: mixing live reads could make the +Inf bucket exceed
        ``_count`` within a single exposition (spec violation that breaks
        ``histogram_quantile`` exactly under load).
        """
        full = f"{self.prefix}_{name}"
        self.lines.append(f"# HELP {full} {help_}")
        self.lines.append(f"# TYPE {full} histogram")
        counts, s, c = hist.snapshot()
        pre = f"{labels}," if labels else ""
        acc = 0
        for bound, n in zip(hist.bounds, counts):
            acc += n
            self.lines.append(f'{full}_bucket{{{pre}le="{bound!r}"}} {acc}')
        self.lines.append(f'{full}_bucket{{{pre}le="+Inf"}} {c}')
        self.lines.append(f'{full}_sum{{{labels}}} {s}')
        self.lines.append(f'{full}_count{{{labels}}} {c}')

    # ------------------------------------------------------------------
    def render(self) -> str:
        return "\n".join(self.lines) + "\n"
