"""Run-directory reporting: summary.csv + per-metric plots.

Parity with ``/root/reference/dfd/timm/utils.py``: ``get_outdir`` (:188),
``update_summary`` (:238-248), ``plot_csv`` (:224), ``plot_figure`` (:205),
``natural_key`` (:251).  Plots are optional (matplotlib imported lazily, and
failures are swallowed like the reference's bare try/except around savefig).
"""

from __future__ import annotations

import csv
import os
import re
from collections import OrderedDict
from typing import Dict, Optional

__all__ = ["get_outdir", "update_summary", "plot_csv", "natural_key"]


def get_outdir(path: str, *paths: str, inc: bool = False) -> str:
    """mkdir -p with optional ``-N`` suffix increment (reference :188-202)."""
    outdir = os.path.join(path, *paths)
    if not os.path.exists(outdir):
        # exist_ok: with a collective (sharded) saver every rank calls
        # this concurrently on a shared filesystem
        os.makedirs(outdir, exist_ok=True)
    elif inc:
        count = 1
        outdir_inc = f"{outdir}-{count}"
        while os.path.exists(outdir_inc):
            count += 1
            outdir_inc = f"{outdir}-{count}"
            assert count < 100
        outdir = outdir_inc
        os.makedirs(outdir)
    return outdir


def _plot_figure(x_data, y_data, name: str, plots_dir: str) -> None:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(6, 6))
    ax.set_title(name, color="red")
    ax.set_xlabel("epoch", fontsize=15, color="gray")
    ax.set_ylabel(name, fontsize=15, color="gray")
    ax.plot(x_data, y_data, "ro-")
    ax.grid(True)
    try:
        plt.savefig(os.path.join(plots_dir, f"{name}.jpg"))
    except Exception:
        pass
    plt.close(fig)


def plot_csv(filename: str, plots_dir: str) -> None:
    """Regenerate one plot per csv column (reference :224-235)."""
    os.makedirs(plots_dir, exist_ok=True)
    with open(filename) as f:
        rows = list(csv.DictReader(f))
    if not rows:
        return
    x = [float(r["epoch"]) for r in rows]
    for column in rows[0].keys():
        if column == "epoch":
            continue
        try:
            y = [float(r[column]) for r in rows]
        except (TypeError, ValueError):
            continue
        _plot_figure(x, y, column, plots_dir)


def update_summary(epoch: int, train_metrics: Dict, eval_metrics: Dict,
                   filename: str, plots_dir: Optional[str] = None,
                   write_header: bool = False) -> None:
    """Append one epoch row and refresh plots (reference :238-248)."""
    rowd = OrderedDict(epoch=epoch)
    rowd.update([("train_" + k, v) for k, v in train_metrics.items()])
    rowd.update([("eval_" + k, v) for k, v in eval_metrics.items()])
    with open(filename, "a") as cf:
        dw = csv.DictWriter(cf, fieldnames=rowd.keys())
        if write_header:
            dw.writeheader()
        dw.writerow(rowd)
    if plots_dir:
        try:
            plot_csv(filename, plots_dir)
        except Exception:
            pass


def natural_key(string_: str):
    """Human sort key (reference :251-253)."""
    return [int(s) if s.isdigit() else s
            for s in re.split(r"(\d+)", string_.lower())]
