"""EfficientNet family (Flax/NHWC/TPU-native).

Re-design of ``/root/reference/dfd/timm/models/efficientnet.py`` (1,696 LoC):
the generic EfficientNet covering B0–B8/L2, EdgeTPU, CondConv, MixNet,
MNasNet-A1/B1/small, FBNet-C, Single-Path-NAS — plus the custom deepfake
configs ``efficientnet_deepfake_v3``/``_v4`` (12 input channels = 4 RGB frames,
600×600, B7 width/depth scaling with stem 256 / features 256; reference
:806-848, :1178-1196) and ``efficientnet_b7_deepfake`` (:93-94).

TPU notes:
* NHWC layout + HWIO kernels; bfloat16 compute via ``dtype``.
* TF-"SAME" padding is XLA-native — no Conv2dSame shim.
* Cross-replica (sync) BN = pass ``bn_axis_name='data'``; replaces both apex
  SyncBN and epoch-boundary ``distribute_bn``.
* The whole forward is one ``jit`` region; XLA fuses BN+Swish+SE epilogues
  into the convs.  Use ``jax.checkpoint`` at stage boundaries for remat.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.activations import get_act_fn
from ..ops.conv import Conv2d, dense_init_goog, space_to_depth
from ..ops.norm import BatchNorm2d, GroupNorm, resolve_bn_args
from ..ops.pool import SelectAdaptivePool2d, adaptive_pool_feat_mult
from ..registry import register_model
from .efficientnet_blocks import (ConvBnAct, ConvBnActS2d, CondConvResidual,
                                  DepthwiseSeparableConv, EdgeResidual,
                                  InvertedResidual, round_channels)
from .efficientnet_builder import build_block_configs, decode_arch_def

__all__ = ["EfficientNet"]

IMAGENET_DEFAULT_MEAN = (0.485, 0.456, 0.406)
IMAGENET_DEFAULT_STD = (0.229, 0.224, 0.225)
IMAGENET_INCEPTION_MEAN = (0.5, 0.5, 0.5)
IMAGENET_INCEPTION_STD = (0.5, 0.5, 0.5)


def _cfg(url: str = "", **kwargs) -> Dict[str, Any]:
    cfg = dict(url=url, num_classes=1000, input_size=(3, 224, 224),
               pool_size=(7, 7), crop_pct=0.875, interpolation="bicubic",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="conv_stem", classifier="classifier")
    cfg.update(kwargs)
    return cfg


default_cfgs: Dict[str, Dict[str, Any]] = {
    **{f"efficientnet_b{i}": _cfg(input_size=(3, r, r))
       for i, r in enumerate([224, 240, 260, 300, 380, 456, 528, 600, 672])},
    "efficientnet_l2": _cfg(input_size=(3, 800, 800), crop_pct=0.961),
    # custom deepfake cfgs (reference efficientnet.py:93-98)
    "efficientnet_b7_deepfake": _cfg(input_size=(3, 450, 800), num_classes=2),
    "efficientnet_deepfake_v3": _cfg(input_size=(12, 600, 600), num_classes=2),
    "efficientnet_deepfake_v4": _cfg(input_size=(12, 600, 600), num_classes=2),
    **{f"tf_efficientnet_b{i}": _cfg(input_size=(3, r, r))
       for i, r in enumerate([224, 240, 260, 300, 380, 456, 528, 600, 672])},
    "efficientnet_es": _cfg(), "efficientnet_em": _cfg(input_size=(3, 240, 240)),
    "efficientnet_el": _cfg(input_size=(3, 300, 300)),
    "efficientnet_cc_b0_4e": _cfg(), "efficientnet_cc_b0_8e": _cfg(),
    "efficientnet_cc_b1_8e": _cfg(input_size=(3, 240, 240)),
    "mixnet_s": _cfg(), "mixnet_m": _cfg(), "mixnet_l": _cfg(),
    "mixnet_xl": _cfg(),
    "mnasnet_050": _cfg(), "mnasnet_075": _cfg(), "mnasnet_100": _cfg(),
    "mnasnet_140": _cfg(), "mnasnet_small": _cfg(),
    "semnasnet_050": _cfg(), "semnasnet_075": _cfg(), "semnasnet_100": _cfg(),
    "semnasnet_140": _cfg(), "mnasnet_a1": _cfg(), "mnasnet_b1": _cfg(),
    "fbnetc_100": _cfg(), "spnasnet_100": _cfg(),
}

_BLOCK_TYPES = {
    "ir": InvertedResidual,
    "ds": DepthwiseSeparableConv,
    "er": EdgeResidual,
    "cn": ConvBnAct,
    "cc": CondConvResidual,
}


class EfficientNet(nn.Module):
    """Generic EfficientNet (reference ``EfficientNet`` class, efficientnet.py:246-352).

    ``block_configs`` comes from :func:`build_block_configs` — a list of stages,
    each a list of block-kwarg dicts with a ``block_type`` key.
    """
    block_configs: Any
    num_classes: int = 1000
    num_features: int = 1280
    in_chans: int = 3
    stem_size: int = 32
    act: Any = "relu"
    drop_rate: float = 0.0
    global_pool: str = "avg"
    head_type: str = "efficientnet"   # 'efficientnet' | 'mobilenetv3'
    head_bias: bool = True
    se_kwargs: Any = None             # SE overrides (MobileNetV3: hard-sigmoid gate)
    norm_layer: str = "bn"
    # '' = torch static symmetric padding (the non-tf families);
    # 'same' = TF/XLA SAME (the tf_* weight-compat variants)
    pad_type: str = ""
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    # rematerialization policy (consumes TrainConfig.checkpoint_policy):
    # 'none' — save all activations; 'full' — recompute every block in the
    # backward pass; 'dots' — save only matmul/conv outputs
    # (checkpoint_dots_with_no_batch_dims keeps weight-only dots).  At the
    # flagship 12×600×600/B7 scale 'dots' trades ~⅓ more FLOPs for the HBM
    # needed to fit a useful per-chip batch.
    remat_policy: str = "none"
    # step-time optimization layer (PERF.md post-fusion roofline):
    # fused_depthwise 'pallas' routes every eligible dw → BN → act stage
    # through the VMEM-resident kernel (ops/depthwise_pallas.py);
    # stem_s2d rewrites the stride-2 stem as a stride-1 conv over 2×2
    # pixel-shuffled input (accepts raw NHWC — shuffles in-model — or
    # loader-preshuffled (B, H/2, W/2, 4C) batches).  Both default off and
    # keep the parameter tree identical to the stock paths.
    fused_depthwise: str = "off"
    stem_s2d: bool = False
    dtype: Any = None
    default_cfg: Any = None

    def _bn_kwargs(self):
        return dict(norm_layer=self.norm_layer, bn_momentum=self.bn_momentum,
                    bn_eps=self.bn_eps, bn_axis_name=self.bn_axis_name,
                    dtype=self.dtype)

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        if self.stem_s2d and x.shape[-1] == 4 * self.in_chans:
            pass            # loader prologue already pixel-shuffled
        else:
            assert x.shape[-1] == self.in_chans, \
                f"expected {self.in_chans} input channels (NHWC), got {x.shape}"
            if self.stem_s2d:
                x = space_to_depth(x)
        act = get_act_fn(self.act)
        bnk = self._bn_kwargs()
        from .helpers import maybe_remat
        block_types = {k: maybe_remat(v, self.remat_policy)
                       for k, v in _BLOCK_TYPES.items()}
        # stem: conv 3x3 s2 (reference efficientnet.py:275-279), or its
        # space-to-depth rewrite — same conv_stem parameter either way
        if self.stem_s2d:
            x = ConvBnActS2d(self.stem_size, act=self.act,
                             pad_type=self.pad_type, **bnk,
                             name="conv_stem")(x, training=training)
        else:
            x = ConvBnAct(self.stem_size, 3, stride=2, act=self.act,
                          pad_type=self.pad_type, **bnk,
                          name="conv_stem")(x, training=training)
        stage_feats: List[Any] = []
        for si, stage in enumerate(self.block_configs):
            for bi, cfg in enumerate(stage):
                cfg = dict(cfg)
                btype = cfg.pop("block_type")
                if self.pad_type:      # tf variants: SAME everywhere
                    cfg["pad_type"] = self.pad_type
                block_act = cfg.pop("act", self.act)
                if btype == "cn":
                    for k in ("noskip", "dw_kernel_size", "se_ratio",
                              "drop_path_rate"):
                        cfg.pop(k, None)
                elif self.se_kwargs is not None:
                    cfg.setdefault("se_kwargs", self.se_kwargs)
                if btype in ("ir", "ds"):
                    cfg.setdefault("fused_depthwise", self.fused_depthwise)
                block = block_types[btype](**cfg, **bnk, act=block_act,
                                           name=f"blocks_{si}_{bi}")
                x = block(x, training)
            stage_feats.append(x)
        if features_only:
            return stage_feats
        if self.head_type == "mobilenetv3":
            # pool → conv_head(1x1, bias) → act → classifier (mobilenetv3.py:65+)
            x = SelectAdaptivePool2d(self.global_pool, flatten=False,
                                     name="global_pool")(x)
            x = Conv2d(self.num_features, 1, use_bias=self.head_bias,
                       dtype=self.dtype, name="conv_head")(x)
            x = act(x)
            feat = x[:, 0, 0, :]
        else:
            # conv_head → bn → act → pool (efficientnet.py:292-299,320-334)
            x = Conv2d(self.num_features, 1, dtype=self.dtype,
                       name="conv_head")(x)
            from .efficientnet_blocks import _norm
            x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                      self.bn_axis_name, self.dtype,
                      "bn2")(x, training=training)
            x = act(x)
            if not pool:
                return x
            feat = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0.0:
            feat = nn.Dropout(rate=self.drop_rate,
                              deterministic=not training)(feat)
        if self.num_classes <= 0:
            return feat
        return nn.Dense(self.num_classes, kernel_init=dense_init_goog,
                        dtype=self.dtype, name="classifier")(feat)


# ---------------------------------------------------------------------------
# Generators (reference _gen_* functions)
# ---------------------------------------------------------------------------

def _make(arch_def, channel_multiplier=1.0, depth_multiplier=1.0,
          depth_trunc="ceil", experts_multiplier=1, fix_first_last=False,
          stem_size=32, num_features=None, num_features_base=1280,
          act="relu", output_stride=32, **kwargs) -> EfficientNet:
    """Shared generator plumbing: decode DSL, scale, round, build module."""
    variant = kwargs.pop("variant", None)
    bn_args = resolve_bn_args(kwargs)
    drop_path_rate = kwargs.pop("drop_path_rate", 0.0)
    # reference factory maps legacy drop_connect_rate → drop_path (factory.py:46-50)
    dcr = kwargs.pop("drop_connect_rate", None)
    if dcr is not None:
        drop_path_rate = dcr
    kwargs.pop("pretrained", None)
    decoded = decode_arch_def(arch_def, depth_multiplier, depth_trunc,
                              experts_multiplier, fix_first_last)
    block_configs = build_block_configs(
        decoded, channel_multiplier=channel_multiplier,
        output_stride=output_stride, drop_path_rate=drop_path_rate,
        default_act=act)
    if num_features is None:
        # generators that scale the head pass num_features_base (reference
        # _gen_efficientnet: round_channels(1280, cm)); others pass a fixed
        # num_features — the reference EfficientNet class never scales it
        num_features = round_channels(num_features_base, channel_multiplier)
    # the stem is ALWAYS scaled (reference EfficientNet.__init__:273)
    stem_size = round_channels(stem_size, channel_multiplier)
    cfg = default_cfgs.get(variant, _cfg()) if variant else _cfg()
    known = dict(num_classes=kwargs.pop("num_classes", cfg.get("num_classes", 1000)),
                 in_chans=kwargs.pop("in_chans", 3),
                 drop_rate=kwargs.pop("drop_rate", 0.0),
                 global_pool=kwargs.pop("global_pool", "avg"),
                 norm_layer=kwargs.pop("norm_layer", "bn"),
                 bn_axis_name=kwargs.pop("bn_axis_name", None),
                 remat_policy=kwargs.pop("remat_policy", "none"),
                 dtype=kwargs.pop("dtype", None),
                 head_type=kwargs.pop("head_type", "efficientnet"),
                 head_bias=kwargs.pop("head_bias", True),
                 pad_type=kwargs.pop("pad_type", ""),
                 fused_depthwise=kwargs.pop("fused_depthwise", "off"),
                 stem_s2d=kwargs.pop("stem_s2d", False),
                 se_kwargs=kwargs.pop("se_kwargs", None))
    kwargs.pop("strict", None)
    if kwargs:
        raise TypeError(f"unexpected model kwargs: {sorted(kwargs)}")
    return EfficientNet(block_configs=block_configs, num_features=num_features,
                        stem_size=stem_size, act=act, default_cfg=cfg,
                        bn_momentum=bn_args.get("momentum", 0.1),
                        bn_eps=bn_args.get("eps", 1e-5), **known)


_EFFICIENTNET_ARCH = [
    ["ds_r1_k3_s1_e1_c16_se0.25"],
    ["ir_r2_k3_s2_e6_c24_se0.25"],
    ["ir_r2_k5_s2_e6_c40_se0.25"],
    ["ir_r3_k3_s2_e6_c80_se0.25"],
    ["ir_r3_k5_s1_e6_c112_se0.25"],
    ["ir_r4_k5_s2_e6_c192_se0.25"],
    ["ir_r1_k3_s1_e6_c320_se0.25"],
]


def _gen_efficientnet(variant, channel_multiplier=1.0, depth_multiplier=1.0,
                      **kwargs):
    """Standard compound-scaled EfficientNet (reference :700-760)."""
    return _make(_EFFICIENTNET_ARCH, channel_multiplier, depth_multiplier,
                 stem_size=32, act=kwargs.pop("act", "swish"),
                 variant=variant, **kwargs)


def _gen_efficientnet_deepfake(variant, channel_multiplier=2.0,
                               depth_multiplier=3.1, **kwargs):
    """Custom deepfake config (reference :806-848): B7 width/depth scaling,
    ``stem_size=round_channels(128, 2.0)=256`` (the class scales every stem,
    reference :273) and ``num_features=round_channels(128,2.0)=256``, Swish
    activations, BatchNorm (the norm-free variant is dead code in the
    reference's active path, :544-554)."""
    return _make(_EFFICIENTNET_ARCH, channel_multiplier, depth_multiplier,
                 stem_size=128, num_features_base=128,
                 act=kwargs.pop("act", "swish"), variant=variant, **kwargs)


_EDGE_ARCH = [
    ["er_r1_k3_s1_e4_c24_fc24_noskip"],
    ["er_r2_k3_s2_e8_c32"],
    ["er_r4_k3_s2_e8_c48"],
    ["ir_r5_k5_s2_e8_c96"],
    ["ir_r4_k5_s1_e8_c144"],
    ["ir_r2_k5_s2_e8_c192"],
]


def _gen_efficientnet_edge(variant, channel_multiplier=1.0,
                           depth_multiplier=1.0, **kwargs):
    return _make(_EDGE_ARCH, channel_multiplier, depth_multiplier,
                 stem_size=32, act="relu", variant=variant, **kwargs)


_CONDCONV_ARCH = [
    ["ds_r1_k3_s1_e1_c16_se0.25"],
    ["ir_r2_k3_s2_e6_c24_se0.25"],
    ["ir_r2_k5_s2_e6_c40_se0.25"],
    ["ir_r3_k3_s2_e6_c80_se0.25"],
    ["ir_r3_k5_s1_e6_c112_se0.25_cc4"],
    ["ir_r4_k5_s2_e6_c192_se0.25_cc4"],
    ["ir_r1_k3_s1_e6_c320_se0.25_cc4"],
]


def _gen_efficientnet_condconv(variant, channel_multiplier=1.0,
                               depth_multiplier=1.0, experts_multiplier=1,
                               **kwargs):
    return _make(_CONDCONV_ARCH, channel_multiplier, depth_multiplier,
                 experts_multiplier=experts_multiplier, stem_size=32,
                 act="swish", variant=variant, **kwargs)


def _gen_mnasnet_b1(variant, channel_multiplier=1.0, **kwargs):
    arch = [
        ["ds_r1_k3_s1_c16_noskip"],
        ["ir_r3_k3_s2_e3_c24"],
        ["ir_r3_k5_s2_e3_c40"],
        ["ir_r3_k5_s2_e6_c80"],
        ["ir_r2_k3_s1_e6_c96"],
        ["ir_r4_k5_s2_e6_c192"],
        ["ir_r1_k3_s1_e6_c320_noskip"],
    ]
    return _make(arch, channel_multiplier, depth_trunc="round", stem_size=32,
                 num_features=1280, act="relu", variant=variant, **kwargs)


def _gen_mnasnet_a1(variant, channel_multiplier=1.0, **kwargs):
    arch = [
        ["ds_r1_k3_s1_c16_noskip"],
        ["ir_r2_k3_s2_e6_c24"],
        ["ir_r3_k5_s2_e3_c40_se0.25"],
        ["ir_r4_k3_s2_e6_c80"],
        ["ir_r2_k3_s1_e6_c112_se0.25"],
        ["ir_r3_k5_s2_e6_c160_se0.25"],
        ["ir_r1_k3_s1_e6_c320"],
    ]
    return _make(arch, channel_multiplier, depth_trunc="round", stem_size=32,
                 num_features=1280, act="relu", variant=variant, **kwargs)


def _gen_mnasnet_small(variant, channel_multiplier=1.0, **kwargs):
    arch = [
        ["ds_r1_k3_s1_c8"],
        ["ir_r1_k3_s2_e3_c16"],
        ["ir_r2_k3_s2_e6_c16"],
        ["ir_r4_k5_s2_e6_c32_se0.25"],
        ["ir_r3_k3_s1_e6_c32_se0.25"],
        ["ir_r3_k5_s2_e6_c88_se0.25"],
        ["ir_r1_k3_s1_e6_c144"],
    ]
    return _make(arch, channel_multiplier, depth_trunc="round", stem_size=8,
                 num_features=1280, act="relu", variant=variant, **kwargs)


_MOBILENETV2_ARCH = [
    ["ds_r1_k3_s1_c16"],
    ["ir_r2_k3_s2_e6_c24"],
    ["ir_r3_k3_s2_e6_c32"],
    ["ir_r4_k3_s2_e6_c64"],
    ["ir_r3_k3_s1_e6_c96"],
    ["ir_r3_k3_s2_e6_c160"],
    ["ir_r1_k3_s1_e6_c320"],
]


def _gen_mobilenet_v2(variant, channel_multiplier=1.0, depth_multiplier=1.0,
                      **kwargs):
    """MobileNet-V2 (reference efficientnet.py:669-692): ReLU6, stem 32."""
    return _make(_MOBILENETV2_ARCH, channel_multiplier, depth_multiplier,
                 stem_size=32, num_features=1280, act="relu6",
                 variant=variant, **kwargs)


def _gen_fbnetc(variant, channel_multiplier=1.0, **kwargs):
    arch = [
        ["ir_r1_k3_s1_e1_c16"],
        ["ir_r1_k3_s2_e6_c24", "ir_r2_k3_s1_e1_c24"],
        ["ir_r1_k5_s2_e6_c32", "ir_r1_k5_s1_e3_c32", "ir_r1_k3_s1_e6_c32",
         "ir_r1_k5_s1_e6_c32"],
        ["ir_r1_k5_s2_e6_c64", "ir_r1_k5_s1_e3_c64", "ir_r2_k5_s1_e6_c64"],
        ["ir_r3_k5_s1_e6_c112", "ir_r1_k5_s1_e3_c112"],
        ["ir_r4_k5_s2_e6_c184"],
        ["ir_r1_k3_s1_e6_c352"],
    ]
    return _make(arch, channel_multiplier, depth_trunc="round", stem_size=16,
                 num_features=1984, act="relu", variant=variant, **kwargs)


def _gen_spnasnet(variant, channel_multiplier=1.0, **kwargs):
    arch = [
        ["ds_r1_k3_s1_c16_noskip"],
        ["ir_r3_k3_s2_e3_c24"],
        ["ir_r1_k5_s2_e6_c40", "ir_r3_k3_s1_e3_c40"],
        ["ir_r1_k5_s2_e6_c80", "ir_r3_k3_s1_e3_c80"],
        ["ir_r1_k5_s1_e6_c96", "ir_r3_k5_s1_e3_c96"],
        ["ir_r4_k5_s2_e6_c192"],
        ["ir_r1_k3_s1_e6_c320_noskip"],
    ]
    return _make(arch, channel_multiplier, depth_trunc="round", stem_size=32,
                 num_features=1280, act="relu", variant=variant, **kwargs)


_MIXNET_S_ARCH = [
    ["ds_r1_k3_s1_e1_c16"],
    ["ir_r1_k3_a1.1_p1.1_s2_e6_c24", "ir_r1_k3_a1.1_p1.1_s1_e3_c24"],
    ["ir_r1_k3.5.7_s2_e6_c40_se0.5_nsw",
     "ir_r3_k3.5_a1.1_p1.1_s1_e6_c40_se0.5_nsw"],
    ["ir_r1_k3.5.7_p1.1_s2_e6_c80_se0.25_nsw",
     "ir_r2_k3.5_p1.1_s1_e6_c80_se0.25_nsw"],
    ["ir_r1_k3.5.7_a1.1_p1.1_s1_e6_c120_se0.5_nsw",
     "ir_r2_k3.5.7.9_a1.1_p1.1_s1_e3_c120_se0.5_nsw"],
    ["ir_r1_k3.5.7.9.11_s2_e6_c200_se0.5_nsw",
     "ir_r2_k3.5.7.9_p1.1_s1_e6_c200_se0.5_nsw"],
]

_MIXNET_M_ARCH = [
    ["ds_r1_k3_s1_e1_c24"],
    ["ir_r1_k3.5.7_a1.1_p1.1_s2_e6_c32", "ir_r1_k3_a1.1_p1.1_s1_e3_c32"],
    ["ir_r1_k3.5.7.9_s2_e6_c40_se0.5_nsw",
     "ir_r3_k3.5_a1.1_p1.1_s1_e6_c40_se0.5_nsw"],
    ["ir_r1_k3.5.7_s2_e6_c80_se0.25_nsw",
     "ir_r3_k3.5.7.9_a1.1_p1.1_s1_e6_c80_se0.25_nsw"],
    ["ir_r1_k3_s1_e6_c120_se0.5_nsw",
     "ir_r3_k3.5.7.9_a1.1_p1.1_s1_e3_c120_se0.5_nsw"],
    ["ir_r1_k3.5.7.9_s2_e6_c200_se0.5_nsw",
     "ir_r3_k3.5.7.9_p1.1_s1_e6_c200_se0.5_nsw"],
]


def _gen_mixnet_s(variant, channel_multiplier=1.0, depth_multiplier=1.0,
                  **kwargs):
    return _make(_MIXNET_S_ARCH, channel_multiplier, depth_multiplier,
                 stem_size=16, num_features=1536, act="relu",
                 variant=variant, **kwargs)


def _gen_mixnet_m(variant, channel_multiplier=1.0, depth_multiplier=1.0,
                  **kwargs):
    return _make(_MIXNET_M_ARCH, channel_multiplier, depth_multiplier,
                 depth_trunc="round", stem_size=24,
                 num_features=1536, act="relu", variant=variant, **kwargs)


# ---------------------------------------------------------------------------
# Registered entrypoints
# ---------------------------------------------------------------------------

_B_SCALING = {  # (channel_multiplier, depth_multiplier)
    0: (1.0, 1.0), 1: (1.0, 1.1), 2: (1.1, 1.2), 3: (1.2, 1.4),
    4: (1.4, 1.8), 5: (1.6, 2.2), 6: (1.8, 2.6), 7: (2.0, 3.1), 8: (2.2, 3.6),
}


def _register_scaled(name, gen, cm, dm=1.0, tf=False, doc=""):
    def fn(pretrained=False, *, _name=name, _cm=cm, _dm=dm, _tf=tf,
           _gen=gen, **kwargs):
        if _tf:
            kwargs.setdefault("bn_tf", True)
            kwargs.setdefault("pad_type", "same")   # TF SAME, XLA-native
        return _gen(_name, _cm, _dm, **kwargs)
    fn.__name__ = name
    fn.__qualname__ = name
    fn.__module__ = __name__
    fn.__doc__ = doc or f"{name} (w={cm}, d={dm})."
    register_model(fn)


def _register_b_series():
    for i, (cm, dm) in _B_SCALING.items():
        _register_scaled(f"efficientnet_b{i}", _gen_efficientnet, cm, dm,
                         doc=f"EfficientNet-B{i} (w={cm}, d={dm}).")
        _register_scaled(f"tf_efficientnet_b{i}", _gen_efficientnet, cm, dm,
                         tf=True, doc=f"TF EfficientNet-B{i}.")
        # AdvProp / Noisy-Student weight variants (reference :1358-1530) —
        # same architectures, TF BN defaults
        if i <= 8:
            _register_scaled(f"tf_efficientnet_b{i}_ap", _gen_efficientnet,
                             cm, dm, tf=True,
                             doc=f"TF EfficientNet-B{i} AdvProp.")
        if i <= 7:
            _register_scaled(f"tf_efficientnet_b{i}_ns", _gen_efficientnet,
                             cm, dm, tf=True,
                             doc=f"TF EfficientNet-B{i} NoisyStudent.")


_register_b_series()

# crop-pct 'a' variants (reference :1106-1131) and TF L2 NoisyStudent
_register_scaled("efficientnet_b2a", _gen_efficientnet, 1.1, 1.2)
_register_scaled("efficientnet_b3a", _gen_efficientnet, 1.2, 1.4)
_register_scaled("tf_efficientnet_l2_ns", _gen_efficientnet, 4.3, 5.3,
                 tf=True, doc="TF EfficientNet-L2 NoisyStudent (:1544).")
_register_scaled("tf_efficientnet_l2_ns_475", _gen_efficientnet, 4.3, 5.3,
                 tf=True, doc="TF EfficientNet-L2 NS @475 (:1533).")
# TF edge / condconv / mixnet weight variants (reference :1555-1706)
_register_scaled("tf_efficientnet_es", _gen_efficientnet_edge, 1.0, 1.0,
                 tf=True)
_register_scaled("tf_efficientnet_em", _gen_efficientnet_edge, 1.0, 1.1,
                 tf=True)
_register_scaled("tf_efficientnet_el", _gen_efficientnet_edge, 1.2, 1.4,
                 tf=True)
_register_scaled("tf_mixnet_s", _gen_mixnet_s, 1.0, tf=True)
_register_scaled("tf_mixnet_m", _gen_mixnet_m, 1.0, tf=True)
_register_scaled("tf_mixnet_l", _gen_mixnet_m, 1.3, tf=True)
_register_scaled("mixnet_xxl", _gen_mixnet_m, 2.4, 1.3)
_register_scaled("mobilenetv2_100", _gen_mobilenet_v2, 1.0)


def _gen_condconv_tf(variant, channel_multiplier=1.0, depth_multiplier=1.0,
                     **kwargs):
    experts = 2 if variant.endswith("8e") else 1
    return _gen_efficientnet_condconv(variant, channel_multiplier,
                                      depth_multiplier, experts, **kwargs)


_register_scaled("tf_efficientnet_cc_b0_4e", _gen_condconv_tf, 1.0, 1.0,
                 tf=True)
_register_scaled("tf_efficientnet_cc_b0_8e", _gen_condconv_tf, 1.0, 1.0,
                 tf=True)
_register_scaled("tf_efficientnet_cc_b1_8e", _gen_condconv_tf, 1.0, 1.1,
                 tf=True)


@register_model
def efficientnet_l2(pretrained=False, **kwargs):
    return _gen_efficientnet("efficientnet_l2", 4.3, 5.3, **kwargs)


@register_model
def efficientnet_b7_deepfake(pretrained=False, **kwargs):
    """Reference efficientnet.py:93-94, :1169-1176: B7 scaling, 2 classes."""
    kwargs.setdefault("num_classes", 2)
    return _gen_efficientnet("efficientnet_b7_deepfake", 2.0, 3.1, **kwargs)


@register_model
def efficientnet_deepfake_v3(pretrained=False, **kwargs):
    """Reference efficientnet.py:1178-1185: deepfake config, 12-chan input."""
    kwargs.setdefault("num_classes", 2)
    kwargs.setdefault("in_chans", 12)
    return _gen_efficientnet_deepfake("efficientnet_deepfake_v3", **kwargs)


@register_model
def efficientnet_deepfake_v4(pretrained=False, **kwargs):
    """Reference efficientnet.py:1187-1196 — the flagship training config."""
    kwargs.setdefault("num_classes", 2)
    kwargs.setdefault("in_chans", 12)
    return _gen_efficientnet_deepfake("efficientnet_deepfake_v4", **kwargs)


@register_model
def efficientnet_es(pretrained=False, **kwargs):
    return _gen_efficientnet_edge("efficientnet_es", 1.0, 1.0, **kwargs)


@register_model
def efficientnet_em(pretrained=False, **kwargs):
    return _gen_efficientnet_edge("efficientnet_em", 1.0, 1.1, **kwargs)


@register_model
def efficientnet_el(pretrained=False, **kwargs):
    return _gen_efficientnet_edge("efficientnet_el", 1.2, 1.4, **kwargs)


@register_model
def efficientnet_cc_b0_4e(pretrained=False, **kwargs):
    return _gen_efficientnet_condconv("efficientnet_cc_b0_4e", 1.0, 1.0, 1,
                                      **kwargs)


@register_model
def efficientnet_cc_b0_8e(pretrained=False, **kwargs):
    return _gen_efficientnet_condconv("efficientnet_cc_b0_8e", 1.0, 1.0, 2,
                                      **kwargs)


@register_model
def efficientnet_cc_b1_8e(pretrained=False, **kwargs):
    return _gen_efficientnet_condconv("efficientnet_cc_b1_8e", 1.0, 1.1, 2,
                                      **kwargs)


@register_model
def mixnet_s(pretrained=False, **kwargs):
    return _gen_mixnet_s("mixnet_s", 1.0, **kwargs)


@register_model
def mixnet_m(pretrained=False, **kwargs):
    return _gen_mixnet_m("mixnet_m", 1.0, **kwargs)


@register_model
def mixnet_l(pretrained=False, **kwargs):
    return _gen_mixnet_m("mixnet_l", 1.3, **kwargs)


@register_model
def mixnet_xl(pretrained=False, **kwargs):
    return _gen_mixnet_m("mixnet_xl", 1.6, 1.2, **kwargs)


@register_model
def mnasnet_050(pretrained=False, **kwargs):
    return _gen_mnasnet_b1("mnasnet_050", 0.5, **kwargs)


@register_model
def mnasnet_075(pretrained=False, **kwargs):
    return _gen_mnasnet_b1("mnasnet_075", 0.75, **kwargs)


@register_model
def mnasnet_100(pretrained=False, **kwargs):
    return _gen_mnasnet_b1("mnasnet_100", 1.0, **kwargs)


@register_model
def mnasnet_b1(pretrained=False, **kwargs):
    return _gen_mnasnet_b1("mnasnet_b1", 1.0, **kwargs)


@register_model
def mnasnet_140(pretrained=False, **kwargs):
    return _gen_mnasnet_b1("mnasnet_140", 1.4, **kwargs)


@register_model
def semnasnet_050(pretrained=False, **kwargs):
    return _gen_mnasnet_a1("semnasnet_050", 0.5, **kwargs)


@register_model
def semnasnet_075(pretrained=False, **kwargs):
    return _gen_mnasnet_a1("semnasnet_075", 0.75, **kwargs)


@register_model
def semnasnet_100(pretrained=False, **kwargs):
    return _gen_mnasnet_a1("semnasnet_100", 1.0, **kwargs)


@register_model
def mnasnet_a1(pretrained=False, **kwargs):
    return _gen_mnasnet_a1("mnasnet_a1", 1.0, **kwargs)


@register_model
def semnasnet_140(pretrained=False, **kwargs):
    return _gen_mnasnet_a1("semnasnet_140", 1.4, **kwargs)


@register_model
def mnasnet_small(pretrained=False, **kwargs):
    return _gen_mnasnet_small("mnasnet_small", 1.0, **kwargs)


@register_model
def fbnetc_100(pretrained=False, **kwargs):
    return _gen_fbnetc("fbnetc_100", 1.0, **kwargs)


@register_model
def spnasnet_100(pretrained=False, **kwargs):
    return _gen_spnasnet("spnasnet_100", 1.0, **kwargs)
