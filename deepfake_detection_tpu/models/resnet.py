"""ResNet / ResNeXt / SE-ResNeXt / ECA-ResNet family (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/resnet.py`` (~1,030 LoC, 40+
entrypoints): the generic ``ResNet`` (:280) covering every stem variant
(7×7 / deep / deep_tiered / deep_tiered_narrow, 'Bag of Tricks' b/c/d/e/s/t),
conv-vs-avgpool downsampling (:249-276), cardinality/base-width (ResNeXt),
block attention (SE / ECA via ``create_attn``), output-stride dilation,
drop-block/drop-path, and zero-init of each block's last BN scale.

TPU notes: NHWC everywhere; the 7×7 stem conv and 3×3 bottleneck convs map
straight onto the MXU; BN+ReLU epilogues fuse into the convs under XLA.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops.activations import get_act_fn
from ..ops.attention import create_attn
from ..ops.conv import Conv2d
from ..ops.drop import DropBlock2d, DropPath
from ..ops.norm import BatchNorm2d
from ..ops.pool import (SelectAdaptivePool2d, avg_pool2d_same,
                        max_pool2d_torch)
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["ResNet", "BasicBlock", "Bottleneck"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bilinear",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="conv1", classifier="fc")
    cfg.update(kwargs)
    return cfg


class _Downsample(nn.Module):
    """Projection shortcut: 1×1/3×3 conv (:249-260) or avg-pool+1×1 conv
    (:263-276, the 'd' variants)."""
    out_chs: int
    kernel_size: int = 1
    stride: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    avg: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        if self.avg:
            avg_stride = self.stride if self.dilation == 1 else 1
            if not (self.stride == 1 and self.dilation == 1):
                x = avg_pool2d_same(x, (2, 2), (avg_stride, avg_stride),
                                    count_include_pad=False)
            x = Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv")(x)
        else:
            ks = 1 if self.stride == 1 and self.dilation == 1 \
                else self.kernel_size
            fd = (self.first_dilation or self.dilation) if ks > 1 else 1
            x = Conv2d(self.out_chs, ks, stride=self.stride, dilation=fd,
                       dtype=self.dtype, name="conv")(x)
        return BatchNorm2d(**(self.bn or {}), dtype=self.dtype,
                           name="bn")(x, training=training)


class BasicBlock(nn.Module):
    """3×3 + 3×3 residual block (:118-175), expansion 1."""
    planes: int
    stride: int = 1
    has_downsample: bool = False
    cardinality: int = 1
    base_width: int = 64
    reduce_first: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    act: str = "relu"
    attn_layer: Optional[str] = None
    avg_down: bool = False
    down_kernel_size: int = 1
    drop_block_rate: float = 0.0
    drop_block_gamma: float = 1.0
    drop_path_rate: float = 0.0
    zero_init_last_bn: bool = True
    bn: dict = None
    dtype: Any = None
    expansion = 1

    @nn.compact
    def __call__(self, x, training: bool = False):
        assert self.cardinality == 1 and self.base_width == 64
        act = get_act_fn(self.act)
        bn = dict(self.bn or {}, dtype=self.dtype)
        first_planes = self.planes // self.reduce_first
        outplanes = self.planes * self.expansion
        fd = self.first_dilation or self.dilation
        residual = x
        y = Conv2d(first_planes, 3, stride=self.stride, dilation=fd,
                   dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        if self.drop_block_rate:
            y = DropBlock2d(self.drop_block_rate, 7, self.drop_block_gamma, name="db1")(
                y, training=training)
        y = act(y)
        y = Conv2d(outplanes, 3, dilation=self.dilation, dtype=self.dtype,
                   name="conv2")(y)
        y = BatchNorm2d(**bn, name="bn2", scale_init=nn.initializers.zeros
                        if self.zero_init_last_bn else None)(
            y, training=training)
        if self.drop_block_rate:
            y = DropBlock2d(self.drop_block_rate, 7, self.drop_block_gamma, name="db2")(
                y, training=training)
        attn = create_attn(self.attn_layer, dtype=self.dtype, name="se")
        if attn is not None:
            y = attn(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path")(
                y, training=training)
        if self.has_downsample:
            residual = _Downsample(
                outplanes, self.down_kernel_size, self.stride, self.dilation,
                self.first_dilation, avg=self.avg_down, bn=self.bn,
                dtype=self.dtype, name="downsample")(x, training=training)
        return act(y + residual)


class Bottleneck(nn.Module):
    """1×1 → 3×3(groups) → 1×1 residual block (:178-246), expansion 4."""
    planes: int
    stride: int = 1
    has_downsample: bool = False
    cardinality: int = 1
    base_width: int = 64
    reduce_first: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    act: str = "relu"
    attn_layer: Optional[str] = None
    avg_down: bool = False
    down_kernel_size: int = 1
    drop_block_rate: float = 0.0
    drop_block_gamma: float = 1.0
    drop_path_rate: float = 0.0
    zero_init_last_bn: bool = True
    bn: dict = None
    dtype: Any = None
    expansion = 4

    @nn.compact
    def __call__(self, x, training: bool = False):
        act = get_act_fn(self.act)
        bn = dict(self.bn or {}, dtype=self.dtype)
        width = int(math.floor(self.planes * (self.base_width / 64))
                    * self.cardinality)
        first_planes = width // self.reduce_first
        outplanes = self.planes * self.expansion
        fd = self.first_dilation or self.dilation
        residual = x
        y = Conv2d(first_planes, 1, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        if self.drop_block_rate:
            y = DropBlock2d(self.drop_block_rate, 7, self.drop_block_gamma, name="db1")(
                y, training=training)
        y = act(y)
        y = Conv2d(width, 3, stride=self.stride, dilation=fd,
                   groups=self.cardinality, dtype=self.dtype, name="conv2")(y)
        y = BatchNorm2d(**bn, name="bn2")(y, training=training)
        if self.drop_block_rate:
            y = DropBlock2d(self.drop_block_rate, 7, self.drop_block_gamma, name="db2")(
                y, training=training)
        y = act(y)
        y = Conv2d(outplanes, 1, dtype=self.dtype, name="conv3")(y)
        y = BatchNorm2d(**bn, name="bn3", scale_init=nn.initializers.zeros
                        if self.zero_init_last_bn else None)(
            y, training=training)
        if self.drop_block_rate:
            y = DropBlock2d(self.drop_block_rate, 7, self.drop_block_gamma, name="db3")(
                y, training=training)
        attn = create_attn(self.attn_layer, dtype=self.dtype, name="se")
        if attn is not None:
            y = attn(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path")(
                y, training=training)
        if self.has_downsample:
            residual = _Downsample(
                outplanes, self.down_kernel_size, self.stride, self.dilation,
                self.first_dilation, avg=self.avg_down, bn=self.bn,
                dtype=self.dtype, name="downsample")(x, training=training)
        return act(y + residual)


# Block registry: res2net.py / sknet.py extend this with their block types so
# the one generic ResNet drives every derived family (the reference passes
# block *classes* into ResNet, resnet.py:280; string keys keep the flax
# module hashable/static).
_BLOCKS = {"basic": BasicBlock, "bottleneck": Bottleneck}


def register_block(name: str, cls) -> None:
    """Register an extra residual block type for :class:`ResNet`."""
    _BLOCKS[name] = cls


class ResNet(nn.Module):
    """Generic ResNet (reference :280-470); see module docstring."""
    block: str = "bottleneck"
    layers: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    in_chans: int = 3
    cardinality: int = 1
    base_width: int = 64
    stem_width: int = 64
    stem_type: str = ""
    block_reduce_first: int = 1
    down_kernel_size: int = 1
    avg_down: bool = False
    output_stride: int = 32
    act: str = "relu"
    attn_layer: Optional[str] = None
    drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    drop_block_rate: float = 0.0
    global_pool: str = "avg"
    zero_init_last_bn: bool = True
    block_args: Any = None        # extra per-block kwargs (reference :280)
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        act = get_act_fn(self.act)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        deep_stem = "deep" in self.stem_type
        inplanes = self.stem_width * 2 if deep_stem else 64
        # stem (:364-384)
        if deep_stem:
            c1 = c2 = self.stem_width
            if "tiered" in self.stem_type:
                c1 = 3 * (self.stem_width // 4)
                c2 = self.stem_width if "narrow" in self.stem_type \
                    else 6 * (self.stem_width // 4)
            x = Conv2d(c1, 3, stride=2, dtype=self.dtype, name="conv1_0")(x)
            x = BatchNorm2d(**bn, dtype=self.dtype, name="stem_bn0")(
                x, training=training)
            x = act(x)
            x = Conv2d(c2, 3, dtype=self.dtype, name="conv1_1")(x)
            x = BatchNorm2d(**bn, dtype=self.dtype, name="stem_bn1")(
                x, training=training)
            x = act(x)
            x = Conv2d(inplanes, 3, dtype=self.dtype, name="conv1_2")(x)
        else:
            x = Conv2d(inplanes, 7, stride=2, dtype=self.dtype,
                       name="conv1")(x)
        x = BatchNorm2d(**bn, dtype=self.dtype, name="bn1")(
            x, training=training)
        x = act(x)
        x = max_pool2d_torch(x, (3, 3), (2, 2), padding=1)

        # stages (:387-404)
        block_cls = _BLOCKS[self.block]
        channels = [64, 128, 256, 512]
        strides = [1, 2, 2, 2]
        dilations = [1, 1, 1, 1]
        if self.output_stride == 16:
            strides[3], dilations[3] = 1, 2
        elif self.output_stride == 8:
            strides[2:4], dilations[2:4] = [1, 1], [2, 4]
        else:
            assert self.output_stride == 32
        stage_feats = []
        in_expanded = inplanes
        prev_dilation = 1
        for si, (chs, n_blocks, stride, dilation) in enumerate(
                zip(channels, self.layers, strides, dilations)):
            # drop-block on layers 3&4 only, gamma 0.25 / 1.0 (:390-392)
            db = self.drop_block_rate if si >= 2 else 0.0
            db_gamma = 0.25 if si == 2 else 1.0
            for bi in range(n_blocks):
                s = stride if bi == 0 else 1
                need_ds = bi == 0 and (
                    s != 1 or in_expanded != chs * block_cls.expansion)
                first_dilation = prev_dilation if bi == 0 else dilation
                common = dict(
                    planes=chs, stride=s, has_downsample=need_ds,
                    cardinality=self.cardinality, base_width=self.base_width,
                    reduce_first=self.block_reduce_first, dilation=dilation,
                    first_dilation=first_dilation, act=self.act,
                    attn_layer=self.attn_layer, avg_down=self.avg_down,
                    down_kernel_size=self.down_kernel_size,
                    drop_block_rate=db, drop_block_gamma=db_gamma,
                    drop_path_rate=self.drop_path_rate,
                    zero_init_last_bn=self.zero_init_last_bn, bn=bn,
                    dtype=self.dtype)
                common.update(self.block_args or {})
                x = block_cls(**common, name=f"layer{si + 1}_{bi}")(
                    x, training=training)
                in_expanded = chs * block_cls.expansion
            prev_dilation = dilation
            stage_feats.append(x)
        if features_only:
            return stage_feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0.0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


def _resnet(block, layers, pretrained=False, **kwargs):
    kwargs.pop("pretrained", None)
    kwargs.setdefault("default_cfg", _cfg())
    return ResNet(block=block, layers=tuple(layers), **kwargs)


# ---------------------------------------------------------------------------
# Entrypoints (reference :472-1027)
# ---------------------------------------------------------------------------

_RESNET_DEFS = {
    # name: (block, layers, extra kwargs)
    "resnet18": ("basic", (2, 2, 2, 2), {}),
    "resnet34": ("basic", (3, 4, 6, 3), {}),
    "resnet26": ("bottleneck", (2, 2, 2, 2), {}),
    "resnet26d": ("bottleneck", (2, 2, 2, 2),
                  dict(stem_width=32, stem_type="deep", avg_down=True)),
    "resnet50": ("bottleneck", (3, 4, 6, 3), {}),
    "resnet50d": ("bottleneck", (3, 4, 6, 3),
                  dict(stem_width=32, stem_type="deep", avg_down=True)),
    "resnet101": ("bottleneck", (3, 4, 23, 3), {}),
    "resnet152": ("bottleneck", (3, 8, 36, 3), {}),
    "tv_resnet34": ("basic", (3, 4, 6, 3), {}),
    "tv_resnet50": ("bottleneck", (3, 4, 6, 3), {}),
    "wide_resnet50_2": ("bottleneck", (3, 4, 6, 3), dict(base_width=128)),
    "wide_resnet101_2": ("bottleneck", (3, 4, 23, 3), dict(base_width=128)),
    "resnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                        dict(cardinality=32, base_width=4)),
    "resnext50d_32x4d": ("bottleneck", (3, 4, 6, 3),
                         dict(cardinality=32, base_width=4, stem_width=32,
                              stem_type="deep", avg_down=True)),
    "resnext101_32x4d": ("bottleneck", (3, 4, 23, 3),
                         dict(cardinality=32, base_width=4)),
    "resnext101_32x8d": ("bottleneck", (3, 4, 23, 3),
                         dict(cardinality=32, base_width=8)),
    "resnext101_64x4d": ("bottleneck", (3, 4, 23, 3),
                         dict(cardinality=64, base_width=4)),
    "tv_resnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                           dict(cardinality=32, base_width=4)),
    "ig_resnext101_32x8d": ("bottleneck", (3, 4, 23, 3),
                            dict(cardinality=32, base_width=8)),
    "ig_resnext101_32x16d": ("bottleneck", (3, 4, 23, 3),
                             dict(cardinality=32, base_width=16)),
    "ig_resnext101_32x32d": ("bottleneck", (3, 4, 23, 3),
                             dict(cardinality=32, base_width=32)),
    "ig_resnext101_32x48d": ("bottleneck", (3, 4, 23, 3),
                             dict(cardinality=32, base_width=48)),
    "ssl_resnet18": ("basic", (2, 2, 2, 2), {}),
    "ssl_resnet50": ("bottleneck", (3, 4, 6, 3), {}),
    "ssl_resnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                            dict(cardinality=32, base_width=4)),
    "ssl_resnext101_32x4d": ("bottleneck", (3, 4, 23, 3),
                             dict(cardinality=32, base_width=4)),
    "ssl_resnext101_32x8d": ("bottleneck", (3, 4, 23, 3),
                             dict(cardinality=32, base_width=8)),
    "ssl_resnext101_32x16d": ("bottleneck", (3, 4, 23, 3),
                              dict(cardinality=32, base_width=16)),
    "swsl_resnet18": ("basic", (2, 2, 2, 2), {}),
    "swsl_resnet50": ("bottleneck", (3, 4, 6, 3), {}),
    "swsl_resnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                             dict(cardinality=32, base_width=4)),
    "swsl_resnext101_32x4d": ("bottleneck", (3, 4, 23, 3),
                              dict(cardinality=32, base_width=4)),
    "swsl_resnext101_32x8d": ("bottleneck", (3, 4, 23, 3),
                              dict(cardinality=32, base_width=8)),
    "swsl_resnext101_32x16d": ("bottleneck", (3, 4, 23, 3),
                               dict(cardinality=32, base_width=16)),
    "seresnext26d_32x4d": ("bottleneck", (2, 2, 2, 2),
                           dict(cardinality=32, base_width=4, stem_width=32,
                                stem_type="deep", avg_down=True,
                                attn_layer="se")),
    "seresnext26t_32x4d": ("bottleneck", (2, 2, 2, 2),
                           dict(cardinality=32, base_width=4, stem_width=32,
                                stem_type="deep_tiered", avg_down=True,
                                attn_layer="se")),
    "seresnext26tn_32x4d": ("bottleneck", (2, 2, 2, 2),
                            dict(cardinality=32, base_width=4, stem_width=32,
                                 stem_type="deep_tiered_narrow",
                                 avg_down=True, attn_layer="se")),
    "ecaresnext26tn_32x4d": ("bottleneck", (2, 2, 2, 2),
                             dict(cardinality=32, base_width=4, stem_width=32,
                                  stem_type="deep_tiered_narrow",
                                  avg_down=True, attn_layer="eca")),
    "ecaresnet18": ("basic", (2, 2, 2, 2), dict(attn_layer="eca")),
    "ecaresnet50": ("bottleneck", (3, 4, 6, 3), dict(attn_layer="eca")),
}


def _register_resnets():
    for name, (block, layers, extra) in _RESNET_DEFS.items():
        def fn(pretrained=False, *, _block=block, _layers=layers,
               _extra=extra, **kwargs):
            return _resnet(_block, _layers, **{**_extra, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference resnet.py entrypoint)."
        register_model(fn)


_register_resnets()
