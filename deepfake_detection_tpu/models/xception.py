"""Xception (Flax/NHWC) — the FaceForensics++ deepfake baseline backbone.

Re-design of ``/root/reference/dfd/timm/models/xception.py`` (Chollet 2017):
entry flow (conv 32 s2 VALID-padded, conv 64, blocks 128/256/728 s2), middle
flow (8 × 728 blocks of 3 separable convs), exit flow (1024 block,
separable 1536 + 2048 head).  Block semantics follow the reference exactly:
pre-activation ReLU (skipped on block1), ``grow_first``, residual via 1×1
strided conv+BN when shape changes, max-pool for striding (:66-116).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, max_pool2d_torch
from ..registry import register_model
from .efficientnet import IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD

__all__ = ["Xception"]

_XCEPTION_CFG = dict(
    num_classes=1000, input_size=(3, 299, 299), pool_size=(10, 10),
    crop_pct=0.8975, interpolation="bicubic",
    mean=IMAGENET_INCEPTION_MEAN, std=IMAGENET_INCEPTION_STD,
    first_conv="conv1", classifier="fc")


class SeparableConv2d(nn.Module):
    """Depthwise 3×3 + pointwise 1×1, no intermediate act (:52-63)."""
    out_chs: int
    kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        in_chs = x.shape[-1]
        x = Conv2d(in_chs, self.kernel_size, stride=self.stride,
                   dilation=self.dilation, groups=in_chs, dtype=self.dtype,
                   name="conv1")(x)
        return Conv2d(self.out_chs, 1, dtype=self.dtype, name="pointwise")(x)


class XceptionBlock(nn.Module):
    """Residual separable-conv stack (:66-116)."""
    out_filters: int
    reps: int
    strides: int = 1
    start_with_relu: bool = True
    grow_first: bool = True
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_filters = x.shape[-1]
        bn = dict(self.bn or {}, dtype=self.dtype)
        inp = x
        ops = []                      # (sepconv out_chs) sequence
        if self.grow_first:
            ops.append(self.out_filters)
            ops.extend([self.out_filters] * (self.reps - 1))
        else:
            ops.extend([in_filters] * (self.reps - 1))
            ops.append(self.out_filters)
        for i, out_chs in enumerate(ops):
            if i > 0 or self.start_with_relu:
                x = nn.relu(x)
            x = SeparableConv2d(out_chs, 3, dtype=self.dtype,
                                name=f"sep{i + 1}")(x)
            x = BatchNorm2d(**bn, name=f"bn{i + 1}")(x, training=training)
        if self.strides != 1:
            x = max_pool2d_torch(x, (3, 3), (self.strides,) * 2, padding=1)
        if self.out_filters != in_filters or self.strides != 1:
            skip = Conv2d(self.out_filters, 1, stride=self.strides,
                          dtype=self.dtype, name="skip")(inp)
            skip = BatchNorm2d(**bn, name="skipbn")(skip, training=training)
        else:
            skip = inp
        return x + skip


class Xception(nn.Module):
    """Reference ``Xception`` (:118-223)."""
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None
    num_features = 2048

    @nn.compact
    def __call__(self, x, training: bool = False, pool: bool = True):
        assert x.shape[-1] == self.in_chans
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        blk = dict(bn=bn, dtype=self.dtype)
        # entry flow stem: VALID padding like the torch padding=0 convs
        x = Conv2d(32, 3, stride=2, padding=0, dtype=self.dtype,
                   name="conv1")(x)
        x = BatchNorm2d(**bn, dtype=self.dtype, name="bn1")(
            x, training=training)
        x = nn.relu(x)
        x = Conv2d(64, 3, padding=0, dtype=self.dtype, name="conv2")(x)
        x = BatchNorm2d(**bn, dtype=self.dtype, name="bn2")(
            x, training=training)
        x = nn.relu(x)

        x = XceptionBlock(128, 2, 2, start_with_relu=False, **blk,
                          name="block1")(x, training=training)
        x = XceptionBlock(256, 2, 2, **blk, name="block2")(x, training=training)
        x = XceptionBlock(728, 2, 2, **blk, name="block3")(x, training=training)
        for i in range(4, 12):
            x = XceptionBlock(728, 3, 1, **blk, name=f"block{i}")(
                x, training=training)
        x = XceptionBlock(1024, 2, 2, grow_first=False, **blk,
                          name="block12")(x, training=training)

        x = SeparableConv2d(1536, 3, dtype=self.dtype, name="conv3")(x)
        x = BatchNorm2d(**bn, dtype=self.dtype, name="bn3")(
            x, training=training)
        x = nn.relu(x)
        x = SeparableConv2d(2048, 3, dtype=self.dtype, name="conv4")(x)
        x = BatchNorm2d(**bn, dtype=self.dtype, name="bn4")(
            x, training=training)
        x = nn.relu(x)
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


@register_model
def xception(pretrained=False, num_classes=1000, in_chans=3, **kwargs):
    """Reference xception.py:226-237."""
    kwargs.pop("pretrained", None)
    return Xception(num_classes=num_classes, in_chans=in_chans,
                    default_cfg=dict(_XCEPTION_CFG), **kwargs)
