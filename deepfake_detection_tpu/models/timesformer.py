"""TimeSformer: divided space-time attention over frame clips (TPU-native).

The reference flattens its 4-frame clips into a 12-channel image and feeds a
2-D CNN (reference params.py:31, dataset.py:496-512 — "temporal" handled as
channel concat).  This model treats time as a real axis instead: per-frame
patch embedding, then alternating temporal attention (each spatial patch
attends across frames) and spatial attention (patches attend within their
frame) — the "divided space-time" scheme of TimeSformer (Bertasius et al.
2021; PAPERS.md), which is O(F²·N + N²·F) instead of joint attention's
O((N·F)²).

Input stays the pipeline's channel-concat layout ``(B, H, W, 3·F)`` so every
existing dataset/loader/augmentation path (4-frame clips → 12 channels)
feeds it unchanged; the model splits frames back out internally.

TPU notes:
* both attentions run as batched GEMMs on the MXU — temporal attention
  reshapes to (B·N, F, heads, d) (F is tiny: one fused matmul), spatial to
  (B·F, N, heads, d);
* spatial attention is pluggable like ViT's (``attn_impl`` ∈ full | flash |
  ring | ring_flash | ulysses), so long-token regimes (larger inputs /
  finer patches) ride the Pallas flash kernels or the sequence-parallel
  ring over a mesh axis;
* everything is static-shaped; frames derive from ``in_chans // 3`` at
  construction time.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax

from ..ops.drop import DropPath
from ..registry import register_model
from .vit import _Attention

__all__ = ["TimeSformer"]


def _cfg(**kwargs):
    cfg = dict(num_classes=2, input_size=(12, 224, 224), pool_size=None,
               crop_pct=0.9, interpolation="bicubic",
               mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
               first_conv="patch_embed", classifier="head")
    cfg.update(kwargs)
    return cfg


class _DividedBlock(nn.Module):
    """Pre-LN block: temporal attention → spatial attention → MLP."""
    num_heads: int
    mlp_ratio: float = 4.0
    drop_path_rate: float = 0.0
    attn_impl: str = "full"
    sp_mesh: Any = None
    seq_axis: str = "data"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        # x: (B, F, N, C)
        B, F, N, C = x.shape

        def droppath(name, y):
            if self.drop_path_rate:
                return DropPath(self.drop_path_rate, name=name)(
                    y, training=training)
            return y

        # temporal: each spatial location attends across its F frames
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm_t")(x)
        y = y.transpose(0, 2, 1, 3).reshape(B * N, F, C)
        # F is tiny (4): always the dense kernel — one fused batched GEMM
        y = _Attention(self.num_heads, attn_impl="full", dtype=self.dtype,
                       name="attn_t")(y)
        y = y.reshape(B, N, F, C).transpose(0, 2, 1, 3)
        x = x + droppath("dp_t", y)

        # spatial: patches attend within their own frame
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm_s")(x)
        y = _Attention(self.num_heads, attn_impl=self.attn_impl,
                       sp_mesh=self.sp_mesh, seq_axis=self.seq_axis,
                       dtype=self.dtype,
                       name="attn_s")(y.reshape(B * F, N, C))
        y = y.reshape(B, F, N, C)
        x = x + droppath("dp_s", y)

        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm_mlp")(x)
        y = nn.Dense(int(C * self.mlp_ratio), dtype=self.dtype,
                     name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(C, dtype=self.dtype, name="mlp_fc2")(y)
        return x + droppath("dp_mlp", y)


class TimeSformer(nn.Module):
    """Divided space-time transformer over channel-concat clips.

    ``in_chans`` must be ``3 · frames`` (the pipeline's clip layout); mean
    pooling over all frame-patch tokens feeds the classifier head.
    """
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    num_classes: int = 2
    in_chans: int = 12
    drop_path_rate: float = 0.0
    attn_impl: str = "full"
    sp_mesh: Any = None
    seq_axis: str = "data"
    # remat at block boundaries: none | full | dots (same policy surface as
    # EfficientNet / ViT)
    remat_policy: str = "none"
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False,
                 features_only: bool = False):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        assert self.in_chans % 3 == 0, \
            f"in_chans must be 3·frames, got {self.in_chans}"
        frames = self.in_chans // 3
        B, H, W, _ = x.shape
        p = self.patch_size
        assert H % p == 0 and W % p == 0, (x.shape, p)

        # split frames out of the channel axis: (B, H, W, 3F) -> (B·F, H, W, 3)
        x = x.reshape(B, H, W, frames, 3).transpose(0, 3, 1, 2, 4)
        x = x.reshape(B * frames, H, W, 3)
        # shared per-frame patch embed
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        n = (H // p) * (W // p)
        x = x.reshape(B, frames, n, self.embed_dim)

        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, 1, n, self.embed_dim))
        tim = self.param("time_embed", nn.initializers.normal(stddev=0.02),
                         (1, frames, 1, self.embed_dim))
        x = x + pos.astype(x.dtype) + tim.astype(x.dtype)

        from .helpers import maybe_remat
        block_cls = maybe_remat(_DividedBlock, self.remat_policy)
        feats = []
        for i in range(self.depth):
            dpr = self.drop_path_rate * i / max(self.depth - 1, 1)
            x = block_cls(self.num_heads, self.mlp_ratio, dpr,
                          self.attn_impl, self.sp_mesh, self.seq_axis,
                          dtype=self.dtype,
                          name=f"blocks_{i}")(x, training)
            feats.append(x)
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm")(x)
        if features_only:
            feats[-1] = x
            return feats
        feat = x.mean(axis=(1, 2))                      # frames and patches
        if self.num_classes <= 0:
            return feat
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="head")(feat)


# name: (patch, dim, depth, heads)
_TSF_DEFS = {
    "timesformer_tiny_patch16_224": (16, 192, 12, 3),
    "timesformer_base_patch16_224": (16, 768, 12, 12),
    # flagship 600² clips: 600 = 24·25 → patch 25, 576 tokens/frame
    "timesformer_base_patch25_600": (25, 768, 12, 12),
}


def _register():
    for name, (p, dim, depth, heads) in _TSF_DEFS.items():
        size = int(name.rsplit("_", 1)[-1])

        def fn(pretrained=False, *, _p=p, _dim=dim, _depth=depth,
               _heads=heads, _size=size, **kwargs):
            kwargs.pop("pretrained", None)
            # default_cfg channels must track the constructed in_chans
            # (create_model always passes one, default 3 ⇒ single frame)
            in_chans = kwargs.get("in_chans", 12)
            kwargs.setdefault("default_cfg",
                              _cfg(input_size=(in_chans, _size, _size)))
            return TimeSformer(patch_size=_p, embed_dim=_dim, depth=_depth,
                               num_heads=_heads, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = (f"{name}: divided space-time attention over "
                      f"{name.split('_')[1]}-scale ViT dims.")
        register_model(fn)


_register()
