"""Model checkpoint helpers.

Re-design of ``/root/reference/dfd/timm/models/helpers.py``: EMA-stream
selection (:13), ``module.``-prefix handling (:19 — a DDP artifact with no JAX
analog, kept only in the torch converter), non-strict shape-mismatch dropping
(:39-43), resume with optimizer/epoch state (:47-73), and pretrained load with
in_chans / classifier surgery (:76-109).

Format: a single msgpack file holding ``{"variables": ..., "meta": {...}}``
(flax.serialization); the training-loop checkpointer (orbax, top-K/best/
recovery) lives in ``train/checkpoint.py``.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

import flax
import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization
from flax.core import freeze, unfreeze

_logger = logging.getLogger(__name__)

__all__ = ["maybe_remat",
           "save_model_checkpoint", "load_state_dict", "load_checkpoint",
           "resume_checkpoint", "load_pretrained", "filter_shape_mismatch",
           "adapt_input_params"]


#: fused-qkv column layout of this codebase (models/vit.py): (H, 3, D)-major.
#: Stamped into checkpoint meta so pre-layout-change checkpoints — whose
#: param shapes are IDENTICAL but whose columns are (3, H, D)-major — are
#: rejected at load instead of silently producing wrong logits.
QKV_LAYOUT = "head_major"


def has_fused_qkv(tree: Any) -> bool:
    """True if a params (sub)tree contains a fused-qkv Dense module."""
    if not isinstance(tree, dict):
        return False
    return any(k == "qkv" and isinstance(v, dict) or has_fused_qkv(v)
               for k, v in tree.items())


def check_qkv_layout(variables: Dict[str, Any], meta: Dict[str, Any],
                     path: str) -> None:
    """Reject transformer checkpoints that predate the head-major layout."""
    if has_fused_qkv(variables.get("params", {})) \
            and meta.get("qkv_layout") != QKV_LAYOUT:
        raise ValueError(
            f"{path}: ViT/TimeSformer checkpoint lacks the "
            f"qkv_layout={QKV_LAYOUT!r} marker, i.e. it predates the "
            f"head-major fused-qkv layout (models/vit.py). Its qkv columns "
            f"are (3, H, D)-major and would load shape-compatibly but "
            f"produce silently-wrong logits. Re-train, or re-convert the "
            f"source torch checkpoint with tools/convert_torch_checkpoint.py.")


def stamp_qkv_layout(meta: Optional[Dict[str, Any]],
                     tree: Dict[str, Any]) -> Dict[str, Any]:
    """Return ``meta`` (copied) with the head-major marker stamped when
    ``tree`` carries fused-qkv params — the single invariant every save
    path must apply so :func:`check_qkv_layout` can verify on load."""
    meta = dict(meta or {})
    if has_fused_qkv(tree.get("params", {})):
        meta.setdefault("qkv_layout", QKV_LAYOUT)
    return meta


def save_model_checkpoint(path: str, variables: Dict[str, Any],
                          meta: Optional[Dict[str, Any]] = None) -> None:
    meta = stamp_qkv_layout(meta, variables)
    variables = unfreeze(variables) if isinstance(
        variables, flax.core.FrozenDict) else variables
    # np-convert only the arrays; meta stays plain python — np.asarray on a
    # str makes a '<U*' scalar that msgpack_restore cannot round-trip
    payload = {"variables": jax.tree.map(np.asarray, variables),
               "meta": meta}
    blob = serialization.msgpack_serialize(payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def load_state_dict(checkpoint_path: str, use_ema: bool = False) -> Dict[str, Any]:
    """Read a checkpoint file; prefer the EMA stream when asked and present
    (helpers.py:13-28)."""
    if not checkpoint_path or not os.path.isfile(checkpoint_path):
        raise FileNotFoundError(f"No checkpoint at {checkpoint_path!r}")
    with open(checkpoint_path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    meta = payload.get("meta", {})
    if "state" in payload and "variables" not in payload:
        # trainer checkpoint (train/checkpoint.py): TrainState state-dict
        # {step, params, batch_stats, opt_state, ema}
        st = payload["state"]
        ema = st.get("ema") or None
        if use_ema and ema:
            _logger.info("Loaded EMA stream from %s", checkpoint_path)
            out = {"params": ema["params"],
                   "batch_stats": ema.get("batch_stats", {})}
        else:
            out = {"params": st["params"],
                   "batch_stats": st.get("batch_stats", {})}
    elif use_ema and "variables_ema" in payload:
        _logger.info("Loaded state_dict_ema from %s", checkpoint_path)
        out = payload["variables_ema"]
    elif use_ema and meta.get("has_ema"):
        _logger.info("Loaded EMA stream from %s", checkpoint_path)
        out = payload.get("variables_ema", payload["variables"])
    else:
        out = payload["variables"]
    check_qkv_layout(out, meta, checkpoint_path)
    return out


def _unflatten(flat: Dict[tuple, Any]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for k, v in flat.items():
        node = tree
        for part in k[:-1]:
            node = node.setdefault(part, {})
        node[k[-1]] = v
    return tree


def _flatten(tree, prefix=()):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = v
    return out


def filter_shape_mismatch(init_vars: Dict[str, Any],
                          loaded_vars: Dict[str, Any]) -> Tuple[Dict[str, Any], int]:
    """Non-strict load: keep the freshly-initialized value wherever the loaded
    tensor's shape disagrees or the key is missing (helpers.py:39-43)."""
    init_flat = _flatten(unfreeze(init_vars) if hasattr(init_vars, "items") else init_vars)
    loaded_flat = _flatten(loaded_vars)
    dropped = 0
    merged = {}
    for k, v in init_flat.items():
        lv = loaded_flat.get(k)
        if lv is not None and tuple(np.shape(lv)) == tuple(np.shape(v)):
            merged[k] = jnp.asarray(lv)
        else:
            if lv is not None:
                _logger.warning("shape mismatch at %s: ckpt %s vs model %s — dropped",
                                "/".join(k), np.shape(lv), np.shape(v))
                dropped += 1
            merged[k] = v
    return _unflatten(merged), dropped


def expand_split_bn(loaded: Dict[str, Any],
                    init_vars: Dict[str, Any]) -> Dict[str, Any]:
    """Adapt a plain-BN checkpoint to a split-BN model tree.

    The reference loads weights FIRST and converts to split BN after
    (convert_splitbn_model deep-copies the pretrained BN into every aux,
    split_batchnorm.py:41-69); a flax tree is fixed at construction, so
    the checkpoint adapts instead: wherever the init tree has
    ``<name>/{main,aux<i>}/bn/<leaf>`` and the checkpoint has
    ``<name>/bn/<leaf>``, the pretrained value fans out to main AND every
    aux.  Non-BN keys pass through untouched.
    """
    init_flat = _flatten(unfreeze(init_vars)
                         if hasattr(init_vars, "items") else init_vars)
    loaded_flat = _flatten(loaded)
    out = dict(loaded_flat)
    for k in init_flat:
        for i, part in enumerate(k):
            if part == "main" or (part.startswith("aux")
                                  and part[3:].isdigit()):
                if k in loaded_flat:
                    break
                # plain-BN checkpoint: <name>/bn/...; split-BN checkpoint
                # with fewer splits: its main seeds the extra aux BNs
                for src in (k[:i] + k[i + 1:],
                            k[:i] + ("main",) + k[i + 1:]):
                    if src in loaded_flat:
                        out[k] = loaded_flat[src]
                        break
                break
    return _unflatten(out)


def load_checkpoint(init_variables: Dict[str, Any], checkpoint_path: str,
                    use_ema: bool = False, strict: bool = True) -> Dict[str, Any]:
    """Load weights into an initialized variable tree (helpers.py:31-44)."""
    loaded = load_state_dict(checkpoint_path, use_ema)
    if strict:
        restored = serialization.from_state_dict(init_variables, loaded) \
            if not isinstance(loaded, dict) else loaded
        # validate structure matches
        init_flat = _flatten(unfreeze(init_variables)
                             if hasattr(init_variables, "items") else init_variables)
        loaded_flat = _flatten(restored)
        missing = set(init_flat) - set(loaded_flat)
        if missing:
            raise KeyError(f"strict load: missing keys {sorted(missing)[:5]} ...")
        merged, dropped = filter_shape_mismatch(init_variables, restored)
        if dropped:
            raise ValueError(f"strict load: {dropped} shape mismatches")
        return merged
    merged, _ = filter_shape_mismatch(init_variables, loaded)
    return merged


def resume_checkpoint(init_variables: Dict[str, Any],
                      checkpoint_path: str) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """Full resume (helpers.py:47-73): returns (variables, meta, start_epoch).

    ``meta`` carries optimizer state / epoch / metric written by the training
    checkpointer; start_epoch = saved epoch + 1 (helpers.py:64).
    """
    with open(checkpoint_path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    meta = payload.get("meta", {})
    check_qkv_layout(payload["variables"], meta, checkpoint_path)
    variables, _ = filter_shape_mismatch(init_variables, payload["variables"])
    start_epoch = int(meta.get("epoch", -1)) + 1
    _logger.info("Resumed from %s (epoch %d)", checkpoint_path, start_epoch - 1)
    return variables, meta, start_epoch


def adapt_input_params(params: Dict[str, Any], in_chans: int,
                       first_conv: str = "conv_stem") -> Dict[str, Any]:
    """Input-channel surgery for pretrained weights (helpers.py:83-103):
    3→1 chans = sum RGB; 3→N = tile + renormalize.  Kernels are HWIO."""
    params = unfreeze(params) if hasattr(params, "items") else dict(params)

    def visit(node):
        for k, v in node.items():
            if isinstance(v, dict):
                if k == first_conv and "conv" in v and "kernel" in v["conv"]:
                    kern = np.asarray(v["conv"]["kernel"])
                    kh, kw, ci, co = kern.shape
                    if ci == in_chans:
                        continue
                    if in_chans == 1:
                        new = kern.sum(axis=2, keepdims=True)
                    else:
                        reps = int(np.ceil(in_chans / ci))
                        new = np.tile(kern, (1, 1, reps, 1))[:, :, :in_chans]
                        new *= ci / in_chans
                    v["conv"]["kernel"] = jnp.asarray(new)
                else:
                    visit(v)
    visit(params)
    return params


def load_pretrained(init_variables, checkpoint_path: str, num_classes: int,
                    in_chans: int = 3, first_conv: str = "conv_stem",
                    classifier: str = "classifier", strict: bool = True):
    """Pretrained load with input/classifier surgery (helpers.py:76-109).

    The reference pulls from model-zoo URLs; this framework is zero-egress so
    pretrained weights come from a local path.
    """
    loaded = load_state_dict(checkpoint_path)
    if "params" in loaded:
        loaded["params"] = adapt_input_params(loaded["params"], in_chans,
                                              first_conv)
        cls = loaded["params"].get(classifier)
        if cls is not None and "kernel" in cls:
            if np.shape(cls["kernel"])[-1] != num_classes:
                _logger.info("classifier size mismatch — re-initializing head")
                loaded["params"].pop(classifier)
                strict = False
    merged, _ = filter_shape_mismatch(init_variables, loaded)
    return merged


def maybe_remat(block_cls, policy: str):
    """Wrap a block Module class for rematerialization (shared policy
    surface of EfficientNet/ViT/TimeSformer; TrainConfig.checkpoint_policy).

    'none' — save all activations; 'full' — recompute the whole block in
    the backward pass; 'dots' — save only matmul/conv outputs.  Blocks must
    take ``training`` as their second positional argument (static).
    """
    import flax.linen as nn
    assert policy in ("none", "full", "dots"), \
        f"remat policy must be none|full|dots, got {policy!r}"
    if policy == "none":
        return block_cls
    jpolicy = None if policy == "full" \
        else jax.checkpoint_policies.checkpoint_dots
    return nn.remat(block_cls, policy=jpolicy, static_argnums=(2,))
