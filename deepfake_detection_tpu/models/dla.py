"""DLA — Deep Layer Aggregation (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/dla.py`` (467 LoC): the three
block flavours ``DlaBasic`` (:53-79), ``DlaBottleneck`` (:82-120),
``DlaBottle2neck`` (:123-184), the aggregation ``DlaRoot`` (:187-203), the
recursive ``DlaTree`` (:206-252), the :class:`DLA` assembly (:255-330), and
all 12 entrypoints (:333-467).

TPU notes: the tree recursion is plain Python over static levels — XLA sees
one flat graph; root concats are NHWC channel concats.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, avg_pool2d_torch
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["DLA"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bilinear",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="base_layer_conv", classifier="fc")
    cfg.update(kwargs)
    return cfg


class _DlaBasic(nn.Module):
    """Reference DlaBasic (:53-79)."""
    out_chs: int
    stride: int = 1
    dilation: int = 1
    cardinality: int = 1
    base_width: int = 64
    scale: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, residual=None, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        if residual is None:
            residual = x
        y = Conv2d(self.out_chs, 3, stride=self.stride,
                   dilation=self.dilation, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        y = nn.relu(y)
        y = Conv2d(self.out_chs, 3, dilation=self.dilation, dtype=self.dtype,
                   name="conv2")(y)
        y = BatchNorm2d(**bn, name="bn2")(y, training=training)
        return nn.relu(y + residual)


class _DlaBottleneck(nn.Module):
    """Reference DlaBottleneck (:82-120), expansion 2."""
    out_chs: int
    stride: int = 1
    dilation: int = 1
    cardinality: int = 1
    base_width: int = 64
    scale: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, residual=None, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        if residual is None:
            residual = x
        mid = int(math.floor(self.out_chs * (self.base_width / 64))
                  * self.cardinality) // 2
        y = Conv2d(mid, 1, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        y = nn.relu(y)
        y = Conv2d(mid, 3, stride=self.stride, dilation=self.dilation,
                   groups=self.cardinality, dtype=self.dtype, name="conv2")(y)
        y = BatchNorm2d(**bn, name="bn2")(y, training=training)
        y = nn.relu(y)
        y = Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv3")(y)
        y = BatchNorm2d(**bn, name="bn3")(y, training=training)
        return nn.relu(y + residual)


class _DlaBottle2neck(nn.Module):
    """Reference DlaBottle2neck (:123-184): Res2Net hierarchy, expansion 2."""
    out_chs: int
    stride: int = 1
    dilation: int = 1
    cardinality: int = 8
    base_width: int = 4
    scale: int = 4
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, residual=None, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        if residual is None:
            residual = x
        is_first = self.stride > 1
        mid = int(math.floor(self.out_chs * (self.base_width / 64))
                  * self.cardinality) // 2
        num_scales = max(1, self.scale - 1)
        y = Conv2d(mid * self.scale, 1, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        y = nn.relu(y)
        spx = jnp.split(y, self.scale, axis=-1)
        spo = []
        sp = None
        for i in range(num_scales):
            sp = spx[i] if i == 0 or is_first else sp + spx[i]
            sp = Conv2d(mid, 3, stride=self.stride, dilation=self.dilation,
                        groups=self.cardinality, dtype=self.dtype,
                        name=f"convs_{i}")(sp)
            sp = BatchNorm2d(**bn, name=f"bns_{i}")(sp, training=training)
            spo.append(nn.relu(sp))
        if self.scale > 1:
            spo.append(avg_pool2d_torch(
                spx[-1], (3, 3), (self.stride, self.stride),
                padding=1) if is_first else spx[-1])
        y = jnp.concatenate(spo, axis=-1)
        y = Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv3")(y)
        y = BatchNorm2d(**bn, name="bn3")(y, training=training)
        return nn.relu(y + residual)


_DLA_BLOCKS = {"basic": _DlaBasic, "bottleneck": _DlaBottleneck,
               "bottle2neck": _DlaBottle2neck}


class _DlaRoot(nn.Module):
    """Aggregation node (reference DlaRoot, :187-203)."""
    out_chs: int
    residual: bool
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, children, training: bool = False):
        x = Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv")(
            jnp.concatenate(children, axis=-1))
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        if self.residual:
            x = x + children[0]
        return nn.relu(x)


class _DlaTree(nn.Module):
    """Recursive aggregation tree (reference DlaTree, :206-252)."""
    levels: int
    block: str
    out_chs: int
    stride: int = 1
    dilation: int = 1
    cardinality: int = 1
    base_width: int = 64
    scale: int = 4
    level_root: bool = False
    root_residual: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, residual=None, children=None,
                 training: bool = False):
        children = [] if children is None else list(children)
        cargs = dict(dilation=self.dilation, cardinality=self.cardinality,
                     base_width=self.base_width, scale=self.scale,
                     bn=self.bn, dtype=self.dtype)
        targs = dict(block=self.block, root_residual=self.root_residual,
                     **cargs)
        bottom = nn.max_pool(x, (self.stride, self.stride),
                             strides=(self.stride, self.stride)) \
            if self.stride > 1 else x
        if x.shape[-1] != self.out_chs:
            residual = Conv2d(self.out_chs, 1, dtype=self.dtype,
                              name="project_conv")(bottom)
            residual = BatchNorm2d(
                **dict(self.bn or {}, dtype=self.dtype),
                name="project_bn")(residual, training=training)
        else:
            residual = bottom
        if self.level_root:
            children.append(bottom)
        block_cls = _DLA_BLOCKS[self.block]
        if self.levels == 1:
            x1 = block_cls(self.out_chs, self.stride, **cargs,
                           name="tree1")(x, residual, training=training)
            x2 = block_cls(self.out_chs, 1, **cargs,
                           name="tree2")(x1, training=training)
            return _DlaRoot(self.out_chs, self.root_residual, bn=self.bn,
                            dtype=self.dtype, name="root")(
                [x2, x1] + children, training=training)
        x1 = _DlaTree(self.levels - 1, stride=self.stride, out_chs=self.out_chs,
                      **targs, name="tree1")(x, training=training)
        children.append(x1)
        return _DlaTree(self.levels - 1, out_chs=self.out_chs, **targs,
                        name="tree2")(x1, children=children,
                                      training=training)


class DLA(nn.Module):
    """Generic DLA (reference dla.py:255-330)."""
    levels: Sequence[int] = (1, 1, 1, 2, 2, 1)
    channels: Sequence[int] = (16, 32, 64, 128, 256, 512)
    block: str = "bottle2neck"
    cardinality: int = 1
    base_width: int = 64
    scale: int = 4
    residual_root: bool = False
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        bnd = dict(bn, dtype=self.dtype)
        # base layer: 7×7 stride 1 (:265-268)
        x = Conv2d(self.channels[0], 7, dtype=self.dtype,
                   name="base_layer_conv")(x)
        x = BatchNorm2d(**bnd, name="base_layer_bn")(x, training=training)
        x = nn.relu(x)
        feats = []
        # level0/level1: plain conv levels (:269-270, :289-298)
        for li, (chs, convs, stride) in enumerate(
                [(self.channels[0], self.levels[0], 1),
                 (self.channels[1], self.levels[1], 2)]):
            for ci in range(convs):
                x = Conv2d(chs, 3, stride=stride if ci == 0 else 1,
                           dtype=self.dtype, name=f"level{li}_{ci}_conv")(x)
                x = BatchNorm2d(**bnd, name=f"level{li}_{ci}_bn")(
                    x, training=training)
                x = nn.relu(x)
            feats.append(x)
        # level2..5: trees (:272-275)
        for li in range(2, 6):
            x = _DlaTree(
                self.levels[li], self.block, self.channels[li], stride=2,
                cardinality=self.cardinality, base_width=self.base_width,
                scale=self.scale, level_root=li > 2,
                root_residual=self.residual_root, bn=bn, dtype=self.dtype,
                name=f"level{li}")(x, training=training)
            feats.append(x)
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, flatten=False,
                                 name="global_pool")(x)
        if self.drop_rate > 0.0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x[:, 0, 0, :]
        # fc is a 1×1 conv (:279-280)
        x = Conv2d(self.num_classes, 1, use_bias=True, dtype=self.dtype,
                   name="fc")(x)
        return x[:, 0, 0, :]


# name: DLA kwargs (reference :333-467)
_DLA_DEFS = {
    "dla34": dict(levels=(1, 1, 1, 2, 2, 1),
                  channels=(16, 32, 64, 128, 256, 512), block="basic"),
    "dla46_c": dict(levels=(1, 1, 1, 2, 2, 1),
                    channels=(16, 32, 64, 64, 128, 256), block="bottleneck"),
    "dla46x_c": dict(levels=(1, 1, 1, 2, 2, 1),
                     channels=(16, 32, 64, 64, 128, 256), block="bottleneck",
                     cardinality=32, base_width=4),
    "dla60x_c": dict(levels=(1, 1, 1, 2, 3, 1),
                     channels=(16, 32, 64, 64, 128, 256), block="bottleneck",
                     cardinality=32, base_width=4),
    "dla60": dict(levels=(1, 1, 1, 2, 3, 1),
                  channels=(16, 32, 128, 256, 512, 1024),
                  block="bottleneck"),
    "dla60x": dict(levels=(1, 1, 1, 2, 3, 1),
                   channels=(16, 32, 128, 256, 512, 1024),
                   block="bottleneck", cardinality=32, base_width=4),
    "dla102": dict(levels=(1, 1, 1, 3, 4, 1),
                   channels=(16, 32, 128, 256, 512, 1024),
                   block="bottleneck", residual_root=True),
    "dla102x": dict(levels=(1, 1, 1, 3, 4, 1),
                    channels=(16, 32, 128, 256, 512, 1024),
                    block="bottleneck", cardinality=32, base_width=4,
                    residual_root=True),
    "dla102x2": dict(levels=(1, 1, 1, 3, 4, 1),
                     channels=(16, 32, 128, 256, 512, 1024),
                     block="bottleneck", cardinality=64, base_width=4,
                     residual_root=True),
    "dla169": dict(levels=(1, 1, 2, 3, 5, 1),
                   channels=(16, 32, 128, 256, 512, 1024),
                   block="bottleneck", residual_root=True),
    "dla60_res2net": dict(levels=(1, 1, 1, 2, 3, 1),
                          channels=(16, 32, 128, 256, 512, 1024),
                          block="bottle2neck", cardinality=1, base_width=28),
    "dla60_res2next": dict(levels=(1, 1, 1, 2, 3, 1),
                           channels=(16, 32, 128, 256, 512, 1024),
                           block="bottle2neck", cardinality=8, base_width=4),
}


def _register():
    for name, defs in _DLA_DEFS.items():
        def fn(pretrained=False, *, _defs=defs, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return DLA(**{**_defs, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference dla.py entrypoint)."
        register_model(fn)


_register()
