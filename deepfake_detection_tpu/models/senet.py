"""Legacy SENet / SE-ResNet / SE-ResNeXt family (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/senet.py`` (511 LoC, Cadene
lineage): the standalone :class:`SENet` with its four block flavours —
``SEBottleneck`` (SENet154: 1×1 to 2×planes then grouped 3×3 to 4×planes,
:117-137), ``SEResNetBottleneck`` (Caffe-style stride on the 1×1, :140-162),
``SEResNeXtBottleneck`` (width = planes×base_width/64×groups, :165-186),
``SEResNetBlock`` (basic, :189-218) — and the 9 entrypoints (:399-511).

Distinct from the ResNet-with-SE variants (resnet.py / gluon_resnet.py): the
residual add here is ``se(out) + residual`` with no BN zero-init, the stem is
either 3×3×3 (SENet154) or 7×7, and layer1's downsample always uses a 1×1.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, max_pool2d_torch
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["SENet"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bilinear",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="layer0.conv1", classifier="last_linear")
    cfg.update(kwargs)
    return cfg


class _SEModule(nn.Module):
    """Squeeze-excite with biased 1×1 convs (reference senet.py:67-87)."""
    channels: int
    reduction: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = Conv2d(self.channels // self.reduction, 1, use_bias=True,
                   dtype=self.dtype, name="fc1")(s)
        s = nn.relu(s)
        s = Conv2d(self.channels, 1, use_bias=True, dtype=self.dtype,
                   name="fc2")(s)
        return x * nn.sigmoid(s)


class _SENetBlock(nn.Module):
    """One residual block; ``kind`` selects the conv plan (see module doc)."""
    kind: str                 # 'se' | 'se_resnet' | 'se_resnext' | 'basic'
    planes: int
    groups: int
    reduction: int
    stride: int = 1
    has_downsample: bool = False
    down_kernel_size: int = 1
    base_width: int = 4       # SEResNeXt only
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        residual = x
        if self.kind == "basic":
            out_chs = self.planes
            y = Conv2d(self.planes, 3, stride=self.stride, dtype=self.dtype,
                       name="conv1")(x)
            y = BatchNorm2d(**bn, name="bn1")(y, training=training)
            y = nn.relu(y)
            y = Conv2d(self.planes, 3, groups=self.groups, dtype=self.dtype,
                       name="conv2")(y)
            y = BatchNorm2d(**bn, name="bn2")(y, training=training)
            y = nn.relu(y)
        else:
            out_chs = self.planes * 4
            if self.kind == "se":              # SENet154 (:117-137)
                c1, s1 = self.planes * 2, 1
                c2, s2, g = self.planes * 4, self.stride, self.groups
            elif self.kind == "se_resnet":     # Caffe stride-on-1×1 (:140-162)
                c1, s1 = self.planes, self.stride
                c2, s2, g = self.planes, 1, self.groups
            else:                              # se_resnext (:165-186)
                width = math.floor(self.planes * (self.base_width / 64)) \
                    * self.groups
                c1, s1 = width, 1
                c2, s2, g = width, self.stride, self.groups
            y = Conv2d(c1, 1, stride=s1, dtype=self.dtype, name="conv1")(x)
            y = BatchNorm2d(**bn, name="bn1")(y, training=training)
            y = nn.relu(y)
            y = Conv2d(c2, 3, stride=s2, groups=g, dtype=self.dtype,
                       name="conv2")(y)
            y = BatchNorm2d(**bn, name="bn2")(y, training=training)
            y = nn.relu(y)
            y = Conv2d(out_chs, 1, dtype=self.dtype, name="conv3")(y)
            y = BatchNorm2d(**bn, name="bn3")(y, training=training)
        if self.has_downsample:
            residual = Conv2d(out_chs, self.down_kernel_size,
                              stride=self.stride, dtype=self.dtype,
                              name="downsample_conv")(x)
            residual = BatchNorm2d(**bn, name="downsample_bn")(
                residual, training=training)
        y = _SEModule(out_chs, self.reduction, dtype=self.dtype,
                      name="se_module")(y) + residual
        return nn.relu(y)


_EXPANSION = {"se": 4, "se_resnet": 4, "se_resnext": 4, "basic": 1}


class SENet(nn.Module):
    """Generic SENet (reference senet.py:229-397)."""
    block: str = "se_resnet"
    layers: Sequence[int] = (3, 4, 6, 3)
    groups: int = 1
    reduction: int = 16
    num_classes: int = 1000
    in_chans: int = 3
    inplanes: int = 128
    input_3x3: bool = True
    down_kernel_size: int = 3
    drop_rate: float = 0.2
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        bnd = dict(bn, dtype=self.dtype)
        # layer0 (:278-300): 3× 3×3 convs (SENet154) or one 7×7
        if self.input_3x3:
            x = Conv2d(64, 3, stride=2, dtype=self.dtype, name="conv1")(x)
            x = BatchNorm2d(**bnd, name="bn1")(x, training=training)
            x = nn.relu(x)
            x = Conv2d(64, 3, dtype=self.dtype, name="conv2")(x)
            x = BatchNorm2d(**bnd, name="bn2")(x, training=training)
            x = nn.relu(x)
            x = Conv2d(self.inplanes, 3, dtype=self.dtype, name="conv3")(x)
            x = BatchNorm2d(**bnd, name="bn3")(x, training=training)
            x = nn.relu(x)
        else:
            x = Conv2d(self.inplanes, 7, stride=2, dtype=self.dtype,
                       name="conv1")(x)
            x = BatchNorm2d(**bnd, name="bn1")(x, training=training)
            x = nn.relu(x)
        # ceil_mode max-pool (:299) — pad-at-end windowing, torch-exact
        x = max_pool2d_torch(x, (3, 3), (2, 2), ceil_mode=True)

        exp = _EXPANSION[self.block]
        in_expanded = self.inplanes
        stage_feats = []
        for si, (planes, n_blocks) in enumerate(
                zip((64, 128, 256, 512), self.layers)):
            stride = 1 if si == 0 else 2
            # layer1 always downsamples with a 1×1 (:304-312)
            dks = 1 if si == 0 else self.down_kernel_size
            for bi in range(n_blocks):
                s = stride if bi == 0 else 1
                need_ds = bi == 0 and (s != 1 or in_expanded != planes * exp)
                x = _SENetBlock(
                    kind=self.block, planes=planes, groups=self.groups,
                    reduction=self.reduction, stride=s, has_downsample=need_ds,
                    down_kernel_size=dks, bn=bn, dtype=self.dtype,
                    name=f"layer{si + 1}_{bi}")(x, training=training)
                in_expanded = planes * exp
            stage_feats.append(x)
        if features_only:
            return stage_feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="avg_pool")(x)
        if self.drop_rate > 0.0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="last_linear")(x)


# name: (block, layers, groups, extra kwargs); all non-154 nets use the 7×7
# stem, inplanes 64, 1×1 downsamples, and no dropout (reference :399-511)
_SMALL = dict(inplanes=64, input_3x3=False, down_kernel_size=1, drop_rate=0.0)
_SENET_DEFS = {
    "seresnet18": ("basic", (2, 2, 2, 2), 1, _SMALL),
    "seresnet34": ("basic", (3, 4, 6, 3), 1, _SMALL),
    "seresnet50": ("se_resnet", (3, 4, 6, 3), 1, _SMALL),
    "seresnet101": ("se_resnet", (3, 4, 23, 3), 1, _SMALL),
    "seresnet152": ("se_resnet", (3, 8, 36, 3), 1, _SMALL),
    "senet154": ("se", (3, 8, 36, 3), 64, {}),
    "seresnext26_32x4d": ("se_resnext", (2, 2, 2, 2), 32, _SMALL),
    "seresnext50_32x4d": ("se_resnext", (3, 4, 6, 3), 32, _SMALL),
    "seresnext101_32x4d": ("se_resnext", (3, 4, 23, 3), 32, _SMALL),
}


def _register():
    for name, (block, layers, groups, extra) in _SENET_DEFS.items():
        def fn(pretrained=False, *, _block=block, _layers=layers,
               _groups=groups, _extra=extra, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return SENet(block=_block, layers=tuple(_layers), groups=_groups,
                         **{**_extra, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference senet.py entrypoint)."
        register_model(fn)


_register()
