"""EfficientNet building blocks (Flax/NHWC).

TPU-native re-design of ``/root/reference/dfd/timm/models/efficientnet_blocks.py``:
``ConvBnAct`` (:113), ``DepthwiseSeparableConv`` (:136), ``InvertedResidual``
(MBConv, :260), ``CondConvResidual`` (:431), ``EdgeResidual`` (:484),
``SqueezeExcite`` (:93), channel rounding helpers (:55-69).

Every block is a single fused region under XLA: pw-expand → BN → Swish →
dw → BN → Swish → SE → pw-linear → BN → drop_path+residual compiles to a
handful of MXU convs with elementwise epilogues fused in — no reason for the
reference's module-per-op granularity to survive at runtime.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.activations import get_act_fn
from ..ops.conv import CondConv2d, Conv2d, MixedConv2d, create_conv2d
from ..ops.drop import DropPath
from ..ops.norm import BatchNorm2d, GroupNorm, Identity


def make_divisible(v, divisor: int = 8, min_value: Optional[int] = None) -> int:
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def round_channels(channels, multiplier: float = 1.0, divisor: int = 8,
                   channel_min: Optional[int] = None) -> int:
    """Scale + round channel count (efficientnet_blocks.py:64-69)."""
    if not multiplier:
        return channels
    return make_divisible(channels * multiplier, divisor, channel_min)


def _norm(norm_layer: str, momentum, eps, axis_name, dtype, name):
    if norm_layer.startswith("split"):
        # AdvProp split BN: 'split<k>' (reference convert_splitbn_model,
        # split_batchnorm.py:41-69 — here a norm_layer option, since flax
        # modules cannot be surgically rewritten post-construction)
        from ..ops.norm import SplitBatchNorm2d
        return SplitBatchNorm2d(num_splits=int(norm_layer[5:] or 2),
                                momentum=momentum, eps=eps,
                                axis_name=axis_name, dtype=dtype, name=name)
    if norm_layer == "none":
        return Identity(name=name)
    if norm_layer == "gn":
        return GroupNorm(eps=eps, dtype=dtype, name=name)
    return BatchNorm2d(momentum=momentum, eps=eps, axis_name=axis_name,
                       dtype=dtype, name=name)


class SqueezeExcite(nn.Module):
    """EfficientNet-style SE (efficientnet_blocks.py:93-110): reduction is
    computed from ``reduced_base_chs`` (the block *input* chs), not the
    expanded chs."""
    se_ratio: float = 0.25
    reduced_base_chs: Optional[int] = None
    act: Any = "relu"
    gate_fn: Any = "sigmoid"
    divisor: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        base = self.reduced_base_chs or chs
        reduced_chs = make_divisible(base * self.se_ratio, self.divisor)
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = Conv2d(reduced_chs, 1, use_bias=True, dtype=self.dtype,
                   name="conv_reduce")(s)
        s = get_act_fn(self.act)(s)
        s = Conv2d(chs, 1, use_bias=True, dtype=self.dtype,
                   name="conv_expand")(s)
        return x * get_act_fn(self.gate_fn)(s)


class ConvBnAct(nn.Module):
    """conv → norm → act (efficientnet_blocks.py:113-133 / layers/conv_bn_act.py:10)."""
    out_chs: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = create_conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          dtype=self.dtype, name="conv")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        return get_act_fn(self.act)(x)


class DepthwiseSeparableConv(nn.Module):
    """dw conv → SE → pw conv; used where the MBConv expansion is 1
    (efficientnet_blocks.py:136-194)."""
    out_chs: int
    dw_kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    pw_kernel_size: int = 1
    pw_act: bool = False
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        has_residual = (self.stride == 1 and in_chs == self.out_chs
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        x = create_conv2d(in_chs, self.dw_kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          depthwise=True, dtype=self.dtype, name="conv_dw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            sek = dict(self.se_kwargs or {})
            sek.pop("reduce_mid", None)   # dw block: mid == in chs
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=sek.pop("act", self.act),
                              gate_fn=sek.pop("gate_fn", self.se_gate_fn),
                              divisor=sek.pop("divisor", 1),
                              dtype=self.dtype, name="se")(x)
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        if self.pw_act:
            x = act(x)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class InvertedResidual(nn.Module):
    """MBConv (efficientnet_blocks.py:260-348)."""
    out_chs: int
    dw_kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    exp_kernel_size: int = 1
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    def _mid_chs(self, in_chs: int) -> int:
        return make_divisible(in_chs * self.exp_ratio)

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        mid_chs = self._mid_chs(in_chs)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        # point-wise expansion
        x = create_conv2d(mid_chs, self.exp_kernel_size, padding=self.pad_type,
                          dtype=self.dtype, name="conv_pw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        # depth-wise
        x = create_conv2d(mid_chs, self.dw_kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          depthwise=True, dtype=self.dtype, name="conv_dw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            sek = dict(self.se_kwargs or {})
            base = mid_chs if sek.pop("reduce_mid", False) else in_chs
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=base,
                              act=sek.pop("act", self.act),
                              gate_fn=sek.pop("gate_fn", self.se_gate_fn),
                              divisor=sek.pop("divisor", 1),
                              dtype=self.dtype, name="se")(x)
        # point-wise linear projection
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pwl")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn3")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class CondConvResidual(nn.Module):
    """MBConv with conditionally-parameterized convs (efficientnet_blocks.py:431-481):
    routing = sigmoid(Linear(global_avg_pool(x))) shared by all three convs."""
    out_chs: int
    num_experts: int = 4
    dw_kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    exp_kernel_size: int = 1
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        mid_chs = make_divisible(in_chs * self.exp_ratio)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        pooled = jnp.mean(x, axis=(1, 2))
        routing = jax.nn.sigmoid(
            nn.Dense(self.num_experts, dtype=self.dtype,
                     name="routing_fn")(pooled))
        x = CondConv2d(mid_chs, self.exp_kernel_size,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_pw")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        x = CondConv2d(mid_chs, self.dw_kernel_size, stride=self.stride,
                       dilation=self.dilation, groups=mid_chs,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_dw")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=self.act, gate_fn=self.se_gate_fn,
                              dtype=self.dtype, name="se")(x)
        x = CondConv2d(self.out_chs, self.pw_kernel_size,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_pwl")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn3")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class EdgeResidual(nn.Module):
    """EdgeTPU FusedMBConv: full kxk expansion conv instead of pw+dw
    (efficientnet_blocks.py:484-549)."""
    out_chs: int
    exp_kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    fake_in_chs: int = 0
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        base = self.fake_in_chs if self.fake_in_chs > 0 else in_chs
        mid_chs = make_divisible(base * self.exp_ratio)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        x = create_conv2d(mid_chs, self.exp_kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          dtype=self.dtype, name="conv_exp")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=self.act, gate_fn=self.se_gate_fn,
                              dtype=self.dtype, name="se")(x)
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pwl")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x
