"""EfficientNet building blocks (Flax/NHWC).

TPU-native re-design of ``/root/reference/dfd/timm/models/efficientnet_blocks.py``:
``ConvBnAct`` (:113), ``DepthwiseSeparableConv`` (:136), ``InvertedResidual``
(MBConv, :260), ``CondConvResidual`` (:431), ``EdgeResidual`` (:484),
``SqueezeExcite`` (:93), channel rounding helpers (:55-69).

Every block is a single fused region under XLA: pw-expand → BN → Swish →
dw → BN → Swish → SE → pw-linear → BN → drop_path+residual compiles to a
handful of MXU convs with elementwise epilogues fused in — no reason for the
reference's module-per-op granularity to survive at runtime.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.activations import get_act_fn
from ..ops.conv import (CondConv2d, Conv2d, MixedConv2d,
                        conv_kernel_init_goog, create_conv2d,
                        space_to_depth_stem_kernel)
from ..ops.drop import DropPath
from ..ops.norm import BatchNorm2d, GroupNorm, Identity


def make_divisible(v, divisor: int = 8, min_value: Optional[int] = None) -> int:
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def round_channels(channels, multiplier: float = 1.0, divisor: int = 8,
                   channel_min: Optional[int] = None) -> int:
    """Scale + round channel count (efficientnet_blocks.py:64-69)."""
    if not multiplier:
        return channels
    return make_divisible(channels * multiplier, divisor, channel_min)


def _norm(norm_layer: str, momentum, eps, axis_name, dtype, name):
    if norm_layer.startswith("split"):
        # AdvProp split BN: 'split<k>' (reference convert_splitbn_model,
        # split_batchnorm.py:41-69 — here a norm_layer option, since flax
        # modules cannot be surgically rewritten post-construction)
        from ..ops.norm import SplitBatchNorm2d
        return SplitBatchNorm2d(num_splits=int(norm_layer[5:] or 2),
                                momentum=momentum, eps=eps,
                                axis_name=axis_name, dtype=dtype, name=name)
    if norm_layer == "none":
        return Identity(name=name)
    if norm_layer == "gn":
        return GroupNorm(eps=eps, dtype=dtype, name=name)
    return BatchNorm2d(momentum=momentum, eps=eps, axis_name=axis_name,
                       dtype=dtype, name=name)


# ---------------------------------------------------------------------------
# Fused depthwise path (ops/depthwise_pallas.py) + space-to-depth stem.
#
# Both are pure EXECUTION rewrites: the parameter tree (names, shapes, inits,
# dtypes) is identical to the default path's, so one checkpoint serves both
# and the flags can flip between runs.  That is achieved by tiny modules that
# declare the same nested params the stock Conv2d / BatchNorm2d modules
# would, while the compute happens outside them.
# ---------------------------------------------------------------------------

class _Kernel(nn.Module):
    """Declares ``kernel`` exactly like ``nn.Conv`` (goog init, f32)."""
    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self):
        return self.param("kernel", conv_kernel_init_goog, self.shape)


class _DwConvParams(nn.Module):
    """Param mirror of ``Conv2d(name='conv_dw')``: path conv_dw/conv/kernel
    with the HWIO depthwise shape ``(kh, kw, 1, C)``."""
    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self):
        return _Kernel(self.shape, name="conv")()


class _BNInner(nn.Module):
    """Param mirror of ``nn.BatchNorm``: scale/bias params + mean/var
    batch_stats, same names, shapes, inits and dtypes."""
    features: int

    @nn.compact
    def __call__(self):
        f = (self.features,)
        scale = self.param("scale", nn.initializers.ones, f, jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, f, jnp.float32)
        mean = self.variable("batch_stats", "mean",
                             lambda s: jnp.zeros(s, jnp.float32), f)
        var = self.variable("batch_stats", "var",
                            lambda s: jnp.ones(s, jnp.float32), f)
        return scale, bias, mean, var


class _BNParams(nn.Module):
    """Param mirror of ``BatchNorm2d(name=<bn_name>)``: path <bn_name>/bn/*."""
    features: int

    @nn.compact
    def __call__(self):
        return _BNInner(self.features, name="bn")()


def fused_dw_eligible(dw_kernel_size, dilation: int, stride,
                      norm_layer: str) -> bool:
    """Whether a block's dw stage can route through the Pallas fused op:
    single square kernel (no MixedConv arms), no dilation, stride 1/2, plain
    BN (split/group/none norms keep the default path)."""
    return (isinstance(dw_kernel_size, int) and int(dilation) == 1
            and int(stride) in (1, 2) and norm_layer == "bn")


def _fused_dw_bn_act(block: nn.Module, x, training: bool, *, chs: int,
                     kernel_size: int, stride: int, pad_type, act,
                     bn_name: str, momentum: float, eps: float,
                     axis_name, dtype):
    """dw-conv → BN → act through the fused Pallas op, called from inside a
    block's ``@nn.compact`` __call__ (children splice in at block level).

    Eval folds the running stats into the kernel's per-channel affine
    epilogue — the whole stage is one VMEM-resident pass.  Training needs
    the batch statistics of the conv output before it can normalize, so the
    Pallas pass produces the conv output and the stats/normalize/act
    epilogue runs as one fused XLA elementwise pass, mirroring
    ``flax.linen.BatchNorm`` semantics exactly (f32 stats via E[x²]−E[x]²,
    clamped at 0; flax-convention momentum; optional ``axis_name`` pmean for
    cross-replica sync BN).  Gradients flow through the op's custom VJP.
    """
    from ..ops.depthwise_pallas import FUSED_DW_ACTS, fused_depthwise
    k = int(kernel_size)
    kernel = _DwConvParams((k, k, 1, chs), name="conv_dw")()
    scale, bias, ra_mean, ra_var = _BNParams(chs, name=bn_name)()
    act_name = "silu" if act in ("silu", "swish") else act
    kern_act = act_name if act_name in FUSED_DW_ACTS else "none"
    act_fn = get_act_fn(act)
    out_dtype = dtype if dtype is not None else \
        jnp.promote_types(x.dtype, jnp.float32)
    if dtype is not None:
        x = x.astype(dtype)

    if not training:
        inv = jax.lax.rsqrt(ra_var.value + eps)
        eff_scale = scale.astype(jnp.float32) * inv
        eff_bias = bias.astype(jnp.float32) - ra_mean.value * eff_scale
        y = fused_depthwise(x, kernel, eff_scale, eff_bias, stride=stride,
                            padding=pad_type, act=kern_act)
        y = y.astype(out_dtype)
        return y if kern_act == act_name else act_fn(y)

    z = fused_depthwise(x, kernel, None, None, stride=stride,
                        padding=pad_type, act="none")
    zf = z.astype(jnp.promote_types(z.dtype, jnp.float32))
    from ..ops.norm import (_active_local_stats, grouped_local_stats,
                            grouped_running_update)
    scope = _active_local_stats()
    if axis_name is None and scope is not None and scope.groups > 1:
        # unified GSPMD local-BN (ISSUE 12): per-group statistics via the
        # SAME ops/norm.py core as _LocalStatsBatchNorm — each mesh slot
        # normalizes with its own shard's stats, running stats take the
        # group mean (== the shard_map era's per-device update + pmean)
        zg, mu_g, var_g = grouped_local_stats(zf, scope.groups,
                                              scope.sharding)
        if not block.is_initializing():
            m = 1.0 - momentum      # flax convention (BatchNorm2d:70)
            ra_mean.value = grouped_running_update(ra_mean.value, mu_g, m)
            ra_var.value = grouped_running_update(ra_var.value, var_g, m)
        mul = jax.lax.rsqrt(var_g + eps)[:, None, None, None] \
            * scale.astype(jnp.float32)
        y = ((zg - mu_g[:, None, None, None]) * mul
             + bias.astype(jnp.float32))
        if scope.sharding is not None:
            y = jax.lax.with_sharding_constraint(y, scope.sharding)
        return act_fn(y.reshape(zf.shape).astype(out_dtype))
    mu = jnp.mean(zf, axis=(0, 1, 2))
    mu2 = jnp.mean(zf * zf, axis=(0, 1, 2))
    if axis_name is not None:
        mu, mu2 = jax.lax.pmean((mu, mu2), axis_name)
    var = jnp.maximum(0.0, mu2 - mu * mu)
    if not block.is_initializing():
        m = 1.0 - momentum          # flax convention (BatchNorm2d:70)
        ra_mean.value = m * ra_mean.value + (1.0 - m) * mu
        ra_var.value = m * ra_var.value + (1.0 - m) * var
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    y = ((zf - mu[None, None, None]) * mul[None, None, None]
         + bias.astype(jnp.float32)[None, None, None])
    return act_fn(y.astype(out_dtype))


class SqueezeExcite(nn.Module):
    """EfficientNet-style SE (efficientnet_blocks.py:93-110): reduction is
    computed from ``reduced_base_chs`` (the block *input* chs), not the
    expanded chs."""
    se_ratio: float = 0.25
    reduced_base_chs: Optional[int] = None
    act: Any = "relu"
    gate_fn: Any = "sigmoid"
    divisor: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        base = self.reduced_base_chs or chs
        reduced_chs = make_divisible(base * self.se_ratio, self.divisor)
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = Conv2d(reduced_chs, 1, use_bias=True, dtype=self.dtype,
                   name="conv_reduce")(s)
        s = get_act_fn(self.act)(s)
        s = Conv2d(chs, 1, use_bias=True, dtype=self.dtype,
                   name="conv_expand")(s)
        return x * get_act_fn(self.gate_fn)(s)


class ConvBnAct(nn.Module):
    """conv → norm → act (efficientnet_blocks.py:113-133 / layers/conv_bn_act.py:10)."""
    out_chs: int
    kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = create_conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          dtype=self.dtype, name="conv")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        return get_act_fn(self.act)(x)


class _S2dStemConv(nn.Module):
    """Param mirror of ``Conv2d(name='conv')`` computing the space-to-depth
    stem: the parameter KEEPS the original ``(3, 3, C, stem)`` stride-2
    shape (checkpoints stay bit-compatible, converted torch weights load
    unchanged) and is re-scattered on the fly into the ``(2, 2, 4C, stem)``
    stride-1 kernel over the pixel-shuffled input.  The reshape is traced
    into the jit and is a tiny gather next to the conv itself."""
    out_chs: int
    pad_type: str = ""
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        in_chans = x.shape[-1] // 4
        kernel = _Kernel((3, 3, in_chans, self.out_chs), name="conv")()
        k2, pad = space_to_depth_stem_kernel(kernel, self.pad_type)
        if self.dtype is not None:
            x, k2 = x.astype(self.dtype), k2.astype(self.dtype)
        return jax.lax.conv_general_dilated(
            x, k2, window_strides=(1, 1), padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ConvBnActS2d(nn.Module):
    """Drop-in stem replacement for ``ConvBnAct(stem, 3, stride=2)`` over
    space-to-depth input ``(B, H/2, W/2, 4C)``: a stride-1 2×2 conv whose
    contraction depth (4C·4 taps) tiles the MXU where the original
    12-channel 600² stem ran the systolic array at ~1/3 occupancy.  Same
    parameter tree as ConvBnAct (conv/conv/kernel + bn1)."""
    out_chs: int
    pad_type: str = ""
    act: Any = "relu"
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = _S2dStemConv(self.out_chs, self.pad_type, dtype=self.dtype,
                         name="conv")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        return get_act_fn(self.act)(x)


class DepthwiseSeparableConv(nn.Module):
    """dw conv → SE → pw conv; used where the MBConv expansion is 1
    (efficientnet_blocks.py:136-194)."""
    out_chs: int
    dw_kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    pw_kernel_size: int = 1
    pw_act: bool = False
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    # 'off' | 'pallas' — route dw → BN → act through the fused VMEM-resident
    # kernel (ops/depthwise_pallas.py); parameter tree is identical either way
    fused_depthwise: str = "off"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        has_residual = (self.stride == 1 and in_chs == self.out_chs
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        if self.fused_depthwise == "pallas" and fused_dw_eligible(
                self.dw_kernel_size, self.dilation, self.stride,
                self.norm_layer):
            x = _fused_dw_bn_act(
                self, x, training, chs=in_chs,
                kernel_size=self.dw_kernel_size, stride=self.stride,
                pad_type=self.pad_type, act=self.act, bn_name="bn1",
                momentum=self.bn_momentum, eps=self.bn_eps,
                axis_name=self.bn_axis_name, dtype=self.dtype)
        else:
            x = create_conv2d(in_chs, self.dw_kernel_size,
                              stride=self.stride, dilation=self.dilation,
                              padding=self.pad_type, depthwise=True,
                              dtype=self.dtype, name="conv_dw")(x)
            x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                      self.bn_axis_name, self.dtype,
                      "bn1")(x, training=training)
            x = act(x)
        if self.se_ratio > 0.0:
            sek = dict(self.se_kwargs or {})
            sek.pop("reduce_mid", None)   # dw block: mid == in chs
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=sek.pop("act", self.act),
                              gate_fn=sek.pop("gate_fn", self.se_gate_fn),
                              divisor=sek.pop("divisor", 1),
                              dtype=self.dtype, name="se")(x)
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        if self.pw_act:
            x = act(x)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class InvertedResidual(nn.Module):
    """MBConv (efficientnet_blocks.py:260-348)."""
    out_chs: int
    dw_kernel_size: Union[int, Sequence[int]] = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    exp_kernel_size: int = 1
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    # 'off' | 'pallas' — route dw → BN → act through the fused VMEM-resident
    # kernel (ops/depthwise_pallas.py); parameter tree is identical either way
    fused_depthwise: str = "off"
    dtype: Any = None

    def _mid_chs(self, in_chs: int) -> int:
        return make_divisible(in_chs * self.exp_ratio)

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        mid_chs = self._mid_chs(in_chs)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        # point-wise expansion
        x = create_conv2d(mid_chs, self.exp_kernel_size, padding=self.pad_type,
                          dtype=self.dtype, name="conv_pw")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        # depth-wise
        if self.fused_depthwise == "pallas" and fused_dw_eligible(
                self.dw_kernel_size, self.dilation, self.stride,
                self.norm_layer):
            x = _fused_dw_bn_act(
                self, x, training, chs=mid_chs,
                kernel_size=self.dw_kernel_size, stride=self.stride,
                pad_type=self.pad_type, act=self.act, bn_name="bn2",
                momentum=self.bn_momentum, eps=self.bn_eps,
                axis_name=self.bn_axis_name, dtype=self.dtype)
        else:
            x = create_conv2d(mid_chs, self.dw_kernel_size,
                              stride=self.stride, dilation=self.dilation,
                              padding=self.pad_type, depthwise=True,
                              dtype=self.dtype, name="conv_dw")(x)
            x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                      self.bn_axis_name, self.dtype,
                      "bn2")(x, training=training)
            x = act(x)
        if self.se_ratio > 0.0:
            sek = dict(self.se_kwargs or {})
            base = mid_chs if sek.pop("reduce_mid", False) else in_chs
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=base,
                              act=sek.pop("act", self.act),
                              gate_fn=sek.pop("gate_fn", self.se_gate_fn),
                              divisor=sek.pop("divisor", 1),
                              dtype=self.dtype, name="se")(x)
        # point-wise linear projection
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pwl")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn3")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class CondConvResidual(nn.Module):
    """MBConv with conditionally-parameterized convs (efficientnet_blocks.py:431-481):
    routing = sigmoid(Linear(global_avg_pool(x))) shared by all three convs."""
    out_chs: int
    num_experts: int = 4
    dw_kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    exp_kernel_size: int = 1
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        mid_chs = make_divisible(in_chs * self.exp_ratio)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        pooled = jnp.mean(x, axis=(1, 2))
        routing = jax.nn.sigmoid(
            nn.Dense(self.num_experts, dtype=self.dtype,
                     name="routing_fn")(pooled))
        x = CondConv2d(mid_chs, self.exp_kernel_size,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_pw")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        x = CondConv2d(mid_chs, self.dw_kernel_size, stride=self.stride,
                       dilation=self.dilation, groups=mid_chs,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_dw")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=self.act, gate_fn=self.se_gate_fn,
                              dtype=self.dtype, name="se")(x)
        x = CondConv2d(self.out_chs, self.pw_kernel_size,
                       num_experts=self.num_experts, padding=self.pad_type,
                       dtype=self.dtype, name="conv_pwl")(x, routing)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn3")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x


class EdgeResidual(nn.Module):
    """EdgeTPU FusedMBConv: full kxk expansion conv instead of pw+dw
    (efficientnet_blocks.py:484-549)."""
    out_chs: int
    exp_kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    pad_type: str = ""
    act: Any = "relu"
    noskip: bool = False
    exp_ratio: float = 1.0
    fake_in_chs: int = 0
    pw_kernel_size: int = 1
    se_ratio: float = 0.0
    se_gate_fn: Any = "sigmoid"
    se_kwargs: Any = None    # {'act','gate_fn','reduce_mid','divisor'} overrides
    drop_path_rate: float = 0.0
    norm_layer: str = "bn"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        base = self.fake_in_chs if self.fake_in_chs > 0 else in_chs
        mid_chs = make_divisible(base * self.exp_ratio)
        has_residual = (in_chs == self.out_chs and self.stride == 1
                        and not self.noskip)
        act = get_act_fn(self.act)
        shortcut = x
        x = create_conv2d(mid_chs, self.exp_kernel_size, stride=self.stride,
                          dilation=self.dilation, padding=self.pad_type,
                          dtype=self.dtype, name="conv_exp")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn1")(x, training=training)
        x = act(x)
        if self.se_ratio > 0.0:
            x = SqueezeExcite(self.se_ratio, reduced_base_chs=in_chs,
                              act=self.act, gate_fn=self.se_gate_fn,
                              dtype=self.dtype, name="se")(x)
        x = create_conv2d(self.out_chs, self.pw_kernel_size,
                          padding=self.pad_type, dtype=self.dtype,
                          name="conv_pwl")(x)
        x = _norm(self.norm_layer, self.bn_momentum, self.bn_eps,
                  self.bn_axis_name, self.dtype, "bn2")(x, training=training)
        if has_residual:
            x = DropPath(self.drop_path_rate, name="drop_path")(x, training=training)
            x = x + shortcut
        return x
