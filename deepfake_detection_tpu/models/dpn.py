"""DPN — Dual-Path Networks (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/dpn.py`` (323 LoC): the
``DualPathBlock`` (:90-154) carrying a residual stream and a dense
(concat-growing) stream in parallel, pre-activation ``BnActConv2d`` (:62-70),
the :class:`DPN` assembly (:157-246), and the 6 entrypoints (:249-323).

TPU notes: the dual streams are an explicit ``(resid, dense)`` pair —
functional JAX makes the reference's tuple-threading natural; the channel
slices/concats are NHWC layout no-ops under XLA.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, max_pool2d_torch
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["DPN"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bicubic",
               mean=(124 / 255, 117 / 255, 104 / 255),
               std=(1 / (0.0167 * 255),) * 3,
               first_conv="conv1_conv", classifier="classifier")
    cfg.update(kwargs)
    return cfg


class _BnActConv(nn.Module):
    """Pre-activation conv (reference BnActConv2d, dpn.py:62-70)."""
    out_chs: int
    kernel_size: int = 1
    stride: int = 1
    groups: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        x = nn.relu(x)
        return Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                      groups=self.groups, dtype=self.dtype, name="conv")(x)


class _DualPathBlock(nn.Module):
    """Reference DualPathBlock (dpn.py:90-154)."""
    num_1x1_a: int
    num_3x3_b: int
    num_1x1_c: int
    inc: int
    groups: int
    block_type: str = "normal"     # 'proj' | 'down' | 'normal'
    b: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, resid, dense, training: bool = False):
        k = dict(bn=self.bn, dtype=self.dtype)
        x_in = jnp.concatenate([resid, dense], axis=-1)
        stride = 2 if self.block_type == "down" else 1
        if self.block_type in ("proj", "down"):
            x_s = _BnActConv(self.num_1x1_c + 2 * self.inc, 1, stride, **k,
                             name=f"c1x1_w_s{stride}")(x_in,
                                                       training=training)
            x_s1 = x_s[..., :self.num_1x1_c]
            x_s2 = x_s[..., self.num_1x1_c:]
        else:
            x_s1, x_s2 = resid, dense
        y = _BnActConv(self.num_1x1_a, 1, 1, **k, name="c1x1_a")(
            x_in, training=training)
        y = _BnActConv(self.num_3x3_b, 3, stride, groups=self.groups,
                       **dict(k), name="c3x3_b")(y, training=training)
        if self.b:
            # 'b' variants: BN-act then two separate 1×1 heads (:122-125)
            y = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                            name="c1x1_c_bn")(y, training=training)
            y = nn.relu(y)
            out1 = Conv2d(self.num_1x1_c, 1, dtype=self.dtype,
                          name="c1x1_c1")(y)
            out2 = Conv2d(self.inc, 1, dtype=self.dtype, name="c1x1_c2")(y)
        else:
            y = _BnActConv(self.num_1x1_c + self.inc, 1, 1, **k,
                           name="c1x1_c")(y, training=training)
            out1 = y[..., :self.num_1x1_c]
            out2 = y[..., self.num_1x1_c:]
        return x_s1 + out1, jnp.concatenate([x_s2, out2], axis=-1)


class DPN(nn.Module):
    """Generic DPN (reference dpn.py:157-246)."""
    small: bool = False
    num_init_features: int = 64
    k_r: int = 96
    groups: int = 32
    b: bool = False
    k_sec: Sequence[int] = (3, 4, 20, 3)
    inc_sec: Sequence[int] = (16, 32, 24, 128)
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3              # reference hardcodes eps=0.001
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        # input block (:72-88): 3×3 stem for 'small', 7×7 otherwise
        x = Conv2d(self.num_init_features, 3 if self.small else 7, stride=2,
                   dtype=self.dtype, name="conv1_conv")(x)
        x = BatchNorm2d(**dict(bn, dtype=self.dtype), name="conv1_bn")(
            x, training=training)
        x = nn.relu(x)
        x = max_pool2d_torch(x, (3, 3), (2, 2), padding=1)

        bw_factor = 1 if self.small else 4
        resid, dense = x, x[..., :0]       # dense stream starts empty
        stage_feats = []
        for si, (n_blocks, inc) in enumerate(zip(self.k_sec, self.inc_sec)):
            bw = (64 << si) * bw_factor
            r = (self.k_r * bw) // (64 * bw_factor)
            for bi in range(n_blocks):
                btype = ("proj" if si == 0 else "down") if bi == 0 \
                    else "normal"
                resid, dense = _DualPathBlock(
                    r, r, bw, inc, self.groups, btype, self.b, bn=bn,
                    dtype=self.dtype,
                    name=f"conv{si + 2}_{bi + 1}")(resid, dense,
                                                   training=training)
            stage_feats.append(jnp.concatenate([resid, dense], axis=-1))
        # conv5_bn_ac (:215): final BN-act over the concatenated streams
        x = jnp.concatenate([resid, dense], axis=-1)
        x = BatchNorm2d(**dict(bn, dtype=self.dtype), name="conv5_bn_ac")(
            x, training=training)
        x = nn.elu(x)            # fc_act = ELU (reference :160)
        if features_only:
            stage_feats[-1] = x
            return stage_feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, flatten=False,
                                 name="global_pool")(x)
        if self.drop_rate > 0.0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x[:, 0, 0, :]
        # classifier is a 1×1 conv (reference :223-225)
        x = Conv2d(self.num_classes, 1, use_bias=True, dtype=self.dtype,
                   name="classifier")(x)
        return x[:, 0, 0, :]


# name: DPN kwargs (reference :249-323)
_DPN_DEFS = {
    "dpn68": dict(small=True, num_init_features=10, k_r=128, groups=32,
                  k_sec=(3, 4, 12, 3), inc_sec=(16, 32, 32, 64)),
    "dpn68b": dict(small=True, num_init_features=10, k_r=128, groups=32,
                   b=True, k_sec=(3, 4, 12, 3), inc_sec=(16, 32, 32, 64)),
    "dpn92": dict(num_init_features=64, k_r=96, groups=32,
                  k_sec=(3, 4, 20, 3), inc_sec=(16, 32, 24, 128)),
    "dpn98": dict(num_init_features=96, k_r=160, groups=40,
                  k_sec=(3, 6, 20, 3), inc_sec=(16, 32, 32, 128)),
    "dpn131": dict(num_init_features=128, k_r=160, groups=40,
                   k_sec=(4, 8, 28, 3), inc_sec=(16, 32, 32, 128)),
    "dpn107": dict(num_init_features=128, k_r=200, groups=50,
                   k_sec=(4, 8, 20, 3), inc_sec=(20, 64, 64, 128)),
}


def _register():
    for name, defs in _DPN_DEFS.items():
        def fn(pretrained=False, *, _defs=defs, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return DPN(**{**_defs, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference dpn.py entrypoint)."
        register_model(fn)


_register()
