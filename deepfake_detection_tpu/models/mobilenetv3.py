"""MobileNetV3 family (Flax/NHWC), built on the EfficientNet generator.

Re-design of ``/root/reference/dfd/timm/models/mobilenetv3.py`` (11
entrypoints): large/small × width, the ``minimal`` ReLU-only variants, the
``rw`` reference-impl variant, and the ``tf_`` weight-compat configs.  The
MobileNetV3 head (pool → 1×1 conv_head → act → classifier, :65+) and the
paper's SE semantics (ReLU squeeze act, hard-sigmoid gate, reduction computed
from the *expanded* channels with divisor 8, :357) ride the shared
``EfficientNet`` module via ``head_type='mobilenetv3'`` / ``se_kwargs``.
"""

from __future__ import annotations

from ..registry import register_model
from .efficientnet import (IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD,
                           _cfg, _make, default_cfgs)

_LARGE_ARCH = [
    ["ds_r1_k3_s1_e1_c16_nre"],
    ["ir_r1_k3_s2_e4_c24_nre", "ir_r1_k3_s1_e3_c24_nre"],
    ["ir_r3_k5_s2_e3_c40_se0.25_nre"],
    ["ir_r1_k3_s2_e6_c80", "ir_r1_k3_s1_e2.5_c80", "ir_r2_k3_s1_e2.3_c80"],
    ["ir_r2_k3_s1_e6_c112_se0.25"],
    ["ir_r3_k5_s2_e6_c160_se0.25"],
    ["cn_r1_k1_s1_c960"],
]

_LARGE_MINIMAL_ARCH = [
    ["ds_r1_k3_s1_e1_c16"],
    ["ir_r1_k3_s2_e4_c24", "ir_r1_k3_s1_e3_c24"],
    ["ir_r3_k3_s2_e3_c40"],
    ["ir_r1_k3_s2_e6_c80", "ir_r1_k3_s1_e2.5_c80", "ir_r2_k3_s1_e2.3_c80"],
    ["ir_r2_k3_s1_e6_c112"],
    ["ir_r3_k3_s2_e6_c160"],
    ["cn_r1_k1_s1_c960"],
]

_SMALL_ARCH = [
    ["ds_r1_k3_s2_e1_c16_se0.25_nre"],
    ["ir_r1_k3_s2_e4.5_c24_nre", "ir_r1_k3_s1_e3.67_c24_nre"],
    ["ir_r1_k5_s2_e4_c40_se0.25", "ir_r2_k5_s1_e6_c40_se0.25"],
    ["ir_r2_k5_s1_e3_c48_se0.25"],
    ["ir_r3_k5_s2_e6_c96_se0.25"],
    ["cn_r1_k1_s1_c576"],
]

_SMALL_MINIMAL_ARCH = [
    ["ds_r1_k3_s2_e1_c16"],
    ["ir_r1_k3_s2_e4.5_c24", "ir_r1_k3_s1_e3.67_c24"],
    ["ir_r1_k3_s2_e4_c40", "ir_r2_k3_s1_e6_c40"],
    ["ir_r2_k3_s1_e3_c48"],
    ["ir_r3_k3_s2_e6_c96"],
    ["cn_r1_k1_s1_c576"],
]

_RW_ARCH = [
    ["ds_r1_k3_s1_e1_c16_nre_noskip"],
    ["ir_r1_k3_s2_e4_c24_nre", "ir_r1_k3_s1_e3_c24_nre"],
    ["ir_r3_k5_s2_e3_c40_se0.25_nre"],
    ["ir_r1_k3_s2_e6_c80", "ir_r1_k3_s1_e2.5_c80", "ir_r2_k3_s1_e2.3_c80"],
    ["ir_r2_k3_s1_e6_c112_se0.25"],
    ["ir_r3_k5_s2_e6_c160_se0.25"],
    ["cn_r1_k1_s1_c960"],
]

for _name in ("mobilenetv3_large_075", "mobilenetv3_large_100",
              "mobilenetv3_small_075", "mobilenetv3_small_100",
              "mobilenetv3_rw"):
    default_cfgs.setdefault(_name, _cfg(interpolation="bilinear"))
for _name in ("tf_mobilenetv3_large_075", "tf_mobilenetv3_large_100",
              "tf_mobilenetv3_large_minimal_100", "tf_mobilenetv3_small_075",
              "tf_mobilenetv3_small_100", "tf_mobilenetv3_small_minimal_100"):
    default_cfgs.setdefault(_name, _cfg(
        interpolation="bilinear", mean=IMAGENET_INCEPTION_MEAN,
        std=IMAGENET_INCEPTION_STD))


def _gen_mobilenet_v3(variant, channel_multiplier=1.0, **kwargs):
    """Reference _gen_mobilenet_v3 (:268-361)."""
    small = "small" in variant
    minimal = "minimal" in variant
    num_features = 1024 if small else 1280
    if minimal:
        act = "relu"
        arch = _SMALL_MINIMAL_ARCH if small else _LARGE_MINIMAL_ARCH
    else:
        act = "hard_swish"
        arch = _SMALL_ARCH if small else _LARGE_ARCH
    se_kwargs = dict(act="relu", gate_fn="hard_sigmoid", reduce_mid=True,
                     divisor=8)
    return _make(arch, channel_multiplier, stem_size=16,
                 num_features=num_features, act=act, head_type="mobilenetv3",
                 se_kwargs=se_kwargs, variant=variant, **kwargs)


def _gen_mobilenet_v3_rw(variant, channel_multiplier=1.0, **kwargs):
    """Reference _gen_mobilenet_v3_rw (:230-266): head_bias=False, SE divisor
    1 and squeeze act following the block act."""
    se_kwargs = dict(gate_fn="hard_sigmoid", reduce_mid=True, divisor=1)
    return _make(_RW_ARCH, channel_multiplier, stem_size=16,
                 num_features=1280, act="hard_swish",
                 head_type="mobilenetv3", head_bias=False,
                 se_kwargs=se_kwargs, variant=variant, **kwargs)


def _register():
    names = ["mobilenetv3_large_075", "mobilenetv3_large_100",
             "mobilenetv3_small_075", "mobilenetv3_small_100",
             "tf_mobilenetv3_large_075", "tf_mobilenetv3_large_100",
             "tf_mobilenetv3_large_minimal_100", "tf_mobilenetv3_small_075",
             "tf_mobilenetv3_small_100", "tf_mobilenetv3_small_minimal_100"]
    for name in names:
        mult = 0.75 if "_075" in name else 1.0

        def fn(pretrained=False, *, _name=name, _mult=mult, **kwargs):
            if _name.startswith("tf_"):
                kwargs.setdefault("bn_tf", True)
                kwargs.setdefault("pad_type", "same")   # TF SAME padding
            return _gen_mobilenet_v3(_name, _mult, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference mobilenetv3.py entrypoint)."
        register_model(fn)


_register()


@register_model
def mobilenetv3_rw(pretrained=False, **kwargs):
    return _gen_mobilenet_v3_rw("mobilenetv3_rw", 1.0, **kwargs)
