"""Vision Transformer (Flax/NHWC, TPU-native).

The reference has no transformer backbone (SURVEY.md §5: the temporal dim is
channel-concat); ViT-B/16 and ViT-L/16 are the BASELINE.json stretch configs
("stress the XLA attention path") and the customer for the sequence-parallel
machinery in ``parallel/ring_attention.py``.

TPU notes:
* Attention is pluggable: ``attn_impl='full'`` is single-device dense
  attention; ``'ring'``/``'ulysses'`` shard the token axis over a mesh axis
  via shard_map (``sp_mesh`` + ``seq_axis``), so a 12-block ViT-L forward at
  long sequence runs with O(L/n) activation memory per chip and K/V blocks
  riding ICI neighbor-to-neighbor.
* All matmuls are (B·L, D)×(D, ·) GEMMs on the MXU; LayerNorm and GELU fuse
  into the surrounding dots under XLA.
* Architectural layout (pre-LN, learned pos-embed, optional class token)
  follows the ViT paper / timm conventions, EXCEPT the fused-qkv output
  layout: the 3C columns are HEAD-MAJOR (H, 3, D), not timm's (3, H, D),
  so tensor-parallel sharding of the qkv kernel propagates through the
  reshape (see parallel/tp.py).  A torch ViT checkpoint import must
  permute the qkv kernel/bias columns accordingly
  (tools/convert_torch_checkpoint.py's ViT path does this); loading
  timm-layout columns unpermuted yields silently-wrong logits.
* Checkpoint-parity numerics: LayerNorm ε=1e-5 and exact (erf) GELU match
  torch/timm — both fuse identically under XLA, so parity costs nothing.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.drop import DropPath
from ..ops.flash_attention import flash_attention
from ..parallel.ring_attention import full_attention, ring_self_attention
from ..registry import register_model

__all__ = ["VisionTransformer", "prepare_vit_pipeline",
           "vit_pipeline_forward"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=None,
               crop_pct=0.9, interpolation="bicubic",
               mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5),
               first_conv="patch_embed", classifier="head")
    cfg.update(kwargs)
    return cfg


class _Attention(nn.Module):
    """Multi-head self-attention with a pluggable kernel."""
    num_heads: int
    qkv_bias: bool = True
    attn_impl: str = "full"  # 'full'|'flash'|'ring'|'ring_flash'|'ulysses'
    sp_mesh: Any = None           # jax.sharding.Mesh for ring/ulysses
    seq_axis: str = "data"
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        B, L, C = x.shape
        H = self.num_heads
        qkv = nn.Dense(3 * C, use_bias=self.qkv_bias, dtype=self.dtype,
                       name="qkv")(x)
        # head-major fused-qkv layout (H, 3, D), not timm's (3, H, D): under
        # tensor parallelism the qkv kernel's 3C output dim is sharded over
        # the 'model' axis (parallel/tp.py), and only an H-major split lets
        # GSPMD propagate that sharding through this reshape (H % tp == 0;
        # a leading factor 3 would force an all-gather + reshard here)
        qkv = qkv.reshape(B, L, H, 3, C // H)
        q, k, v = (qkv[:, :, :, i] for i in range(3))  # (B, L, H, D)
        if self.attn_impl == "flash":
            # fused Pallas kernel: scores stay in VMEM, O(L) HBM traffic
            out = flash_attention(q, k, v)
        elif self.attn_impl == "full" or self.sp_mesh is None:
            out = full_attention(q, k, v)
        else:
            out = ring_self_attention(q, k, v, self.sp_mesh,
                                      seq_axis=self.seq_axis,
                                      impl=self.attn_impl)
        out = out.reshape(B, L, C)
        return nn.Dense(C, dtype=self.dtype, name="proj")(out)


class _Block(nn.Module):
    """Pre-LN transformer block."""
    num_heads: int
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    attn_impl: str = "full"
    sp_mesh: Any = None
    seq_axis: str = "data"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        C = x.shape[-1]
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(x)
        y = _Attention(self.num_heads, self.qkv_bias, self.attn_impl,
                       self.sp_mesh, self.seq_axis, dtype=self.dtype,
                       name="attn")(y)
        if self.drop_rate:
            y = nn.Dropout(self.drop_rate, deterministic=not training)(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path1")(
                y, training=training)
        x = x + y
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm2")(x)
        y = nn.Dense(int(C * self.mlp_ratio), dtype=self.dtype,
                     name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        if self.drop_rate:
            y = nn.Dropout(self.drop_rate, deterministic=not training)(y)
        y = nn.Dense(C, dtype=self.dtype, name="mlp_fc2")(y)
        if self.drop_rate:
            y = nn.Dropout(self.drop_rate, deterministic=not training)(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path2")(
                y, training=training)
        return x + y


class VisionTransformer(nn.Module):
    """ViT classifier; token or mean pooling, optional sequence parallelism.

    With ``class_token=False`` + ``global_pool='avg'`` the token count is
    exactly (H/p)·(W/p), which keeps the sequence axis divisible by the mesh
    for ring/ulysses sharding.
    """
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    class_token: bool = True
    global_pool: str = "token"     # 'token' | 'avg'
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    attn_impl: str = "full"
    sp_mesh: Any = None
    seq_axis: str = "data"
    # remat at block boundaries (same policy surface as EfficientNet's
    # TrainConfig.checkpoint_policy): none | full | dots
    remat_policy: str = "none"
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        B, H, W, _ = x.shape
        p = self.patch_size
        assert H % p == 0 and W % p == 0, (x.shape, p)
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(B, -1, self.embed_dim)           # (B, N, C)
        n_tokens = x.shape[1] + (1 if self.class_token else 0)
        if self.class_token:
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, self.embed_dim))
            x = jnp.concatenate([jnp.broadcast_to(
                cls, (B, 1, self.embed_dim)).astype(x.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, n_tokens, self.embed_dim))
        x = x + pos.astype(x.dtype)
        if self.drop_rate:
            x = nn.Dropout(self.drop_rate, deterministic=not training)(x)
        from .helpers import maybe_remat
        block_cls = maybe_remat(_Block, self.remat_policy)
        feats = []
        for i in range(self.depth):
            # stochastic depth scales linearly over blocks (timm convention)
            dpr = self.drop_path_rate * i / max(self.depth - 1, 1)
            x = block_cls(self.num_heads, self.mlp_ratio, self.qkv_bias,
                          self.drop_rate, dpr, self.attn_impl, self.sp_mesh,
                          self.seq_axis, dtype=self.dtype,
                          name=f"blocks_{i}")(x, training)
            feats.append(x)
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm")(x)
        if features_only:
            feats[-1] = x
            return feats
        if not pool:
            return x
        if self.global_pool == "avg":
            start = 1 if self.class_token else 0
            feat = x[:, start:].mean(axis=1)
        else:
            assert self.class_token, "token pooling needs a class token"
            feat = x[:, 0]
        if self.num_classes <= 0:
            return feat
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(feat)


def prepare_vit_pipeline(model: "VisionTransformer", variables, mesh,
                         axis: str = "stage"):
    """One-time prep for :func:`vit_pipeline_forward`: stack the per-block
    param trees and shard them over ``axis`` (each stage holds depth/S
    blocks).  Do this once, not per step — it copies the whole tower."""
    from ..parallel.pp import pipeline_sharding, stack_block_params
    s = mesh.shape[axis]
    assert model.depth % s == 0, \
        f"depth {model.depth} not divisible by {s} pipeline stages"
    stacked = stack_block_params(
        [variables["params"][f"blocks_{i}"] for i in range(model.depth)])
    return jax.device_put(stacked, pipeline_sharding(stacked, mesh, axis))


def vit_pipeline_forward(model: "VisionTransformer", variables, x,
                         mesh, num_microbatches: int = 4,
                         axis: str = "stage", stacked=None):
    """Inference forward with the block tower pipelined over ``axis``.

    Patch embed / positional embed / final norm / head run replicated on
    every stage (tiny); the depth-D block tower runs as a GPipe schedule
    (parallel/pp.py).  Output matches ``model.apply(variables, x,
    training=False)`` — the parity test in tests/test_pp.py pins the two
    paths together; KEEP THIS IN SYNC with VisionTransformer.__call__
    (which cannot be factored into setup()-style shared methods because
    pos_embed's shape depends on the input size).

    Per-stage attention runs ``model.attn_impl`` when it is 'full' or
    'flash'; sequence-parallel impls (ring/ulysses) shard over their own
    mesh axis and do not compose with this helper.  Pass ``stacked`` from
    :func:`prepare_vit_pipeline` to avoid re-stacking the tower per call.
    """
    assert model.attn_impl in ("full", "flash"), \
        f"pipeline forward supports full/flash attention, " \
        f"got {model.attn_impl!r}"
    from ..parallel.pp import gpipe_transformer_tower
    p = variables["params"]
    B = x.shape[0]
    if stacked is None:
        stacked = prepare_vit_pipeline(model, variables, mesh, axis)
    # --- embed (replicated) ---------------------------------------------
    pe = nn.Conv(model.embed_dim, (model.patch_size,) * 2,
                 strides=(model.patch_size,) * 2, padding="VALID",
                 dtype=model.dtype)
    h = pe.apply({"params": p["patch_embed"]}, x)
    h = h.reshape(B, -1, model.embed_dim)
    if model.class_token:
        cls = jnp.broadcast_to(p["cls_token"],
                               (B, 1, model.embed_dim)).astype(h.dtype)
        h = jnp.concatenate([cls, h], axis=1)
    h = h + p["pos_embed"].astype(h.dtype)

    # --- pipelined tower -------------------------------------------------
    block = _Block(model.num_heads, model.mlp_ratio, model.qkv_bias,
                   attn_impl=model.attn_impl, dtype=model.dtype)

    def block_apply(bp, hh):
        return block.apply({"params": bp}, hh, False)

    h = gpipe_transformer_tower(mesh, block_apply, stacked, h,
                                num_microbatches, axis=axis)

    # --- head (replicated) -----------------------------------------------
    h = nn.LayerNorm(epsilon=1e-5, dtype=model.dtype).apply({"params": p["norm"]}, h)
    if model.global_pool == "avg":
        start = 1 if model.class_token else 0
        feat = h[:, start:].mean(axis=1)
    else:
        assert model.class_token, "token pooling needs a class token"
        feat = h[:, 0]
    if model.num_classes <= 0:
        return feat
    return nn.Dense(model.num_classes, dtype=model.dtype).apply(
        {"params": p["head"]}, feat)


# name: (patch, dim, depth, heads)
_VIT_DEFS = {
    "vit_tiny_patch16_224": (16, 192, 12, 3),
    "vit_small_patch16_224": (16, 384, 12, 6),
    "vit_base_patch16_224": (16, 768, 12, 12),
    "vit_base_patch16_384": (16, 768, 12, 12),
    "vit_base_patch32_224": (32, 768, 12, 12),
    "vit_large_patch16_224": (16, 1024, 24, 16),
    "vit_large_patch16_384": (16, 1024, 24, 16),
}


def _register():
    for name, (p, dim, depth, heads) in _VIT_DEFS.items():
        size = 384 if name.endswith("_384") else 224

        def fn(pretrained=False, *, _p=p, _dim=dim, _depth=depth,
               _heads=heads, _size=size, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg",
                              _cfg(input_size=(3, _size, _size)))
            return VisionTransformer(patch_size=_p, embed_dim=_dim,
                                     depth=_depth, num_heads=_heads, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (BASELINE.json stretch config)."
        register_model(fn)


_register()
