"""Res2Net / Res2NeXt (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/res2net.py`` (236 LoC): the
``Bottle2neck`` multi-scale residual block (:50-125) plugged into the generic
:class:`~.resnet.ResNet`, and the 7 entrypoints (:128-236).

TPU notes: the hierarchical split-conv chain is a static Python loop over
``scale`` branches — XLA sees ``scale`` small convs per block and fuses the
adds; channel split/concat are free layout ops in NHWC.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.activations import get_act_fn
from ..ops.attention import create_attn
from ..ops.conv import Conv2d
from ..ops.drop import DropPath
from ..ops.norm import BatchNorm2d
from ..ops.pool import avg_pool2d_torch
from ..registry import register_model
from .resnet import _Downsample, _cfg, register_block, ResNet

__all__ = ["Bottle2neck"]


class Bottle2neck(nn.Module):
    """Res2Net bottleneck (reference res2net.py:50-125): 1×1 expand to
    ``width*scale``, hierarchical 3×3 convs over ``scale-1`` channel groups
    (each fed the previous group's output plus its own split), 1×1 project."""
    planes: int
    stride: int = 1
    has_downsample: bool = False
    cardinality: int = 1
    base_width: int = 26
    scale: int = 4
    reduce_first: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    act: str = "relu"
    attn_layer: Optional[str] = None
    avg_down: bool = False
    down_kernel_size: int = 1
    drop_block_rate: float = 0.0      # unused by reference Bottle2neck (**_)
    drop_block_gamma: float = 1.0
    drop_path_rate: float = 0.0
    zero_init_last_bn: bool = True
    bn: dict = None
    dtype: Any = None
    expansion = 4

    @nn.compact
    def __call__(self, x, training: bool = False):
        act = get_act_fn(self.act)
        bn = dict(self.bn or {}, dtype=self.dtype)
        width = int(math.floor(
            self.planes * (self.base_width / 64.0))) * self.cardinality
        outplanes = self.planes * self.expansion
        num_scales = max(1, self.scale - 1)
        is_first = self.stride > 1 or self.has_downsample
        fd = self.first_dilation or self.dilation

        residual = x
        y = Conv2d(width * self.scale, 1, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        y = act(y)

        spx = jnp.split(y, self.scale, axis=-1)
        spo = []
        sp = None
        for i in range(num_scales):
            sp = spx[i] if i == 0 or is_first else sp + spx[i]
            sp = Conv2d(width, 3, stride=self.stride, dilation=fd,
                        groups=self.cardinality, dtype=self.dtype,
                        name=f"convs_{i}")(sp)
            sp = BatchNorm2d(**bn, name=f"bns_{i}")(sp, training=training)
            sp = act(sp)
            spo.append(sp)
        if self.scale > 1:
            # last split passes through (pooled when the block downsamples;
            # count_include_pad=True matches the reference's AvgPool2d)
            spo.append(avg_pool2d_torch(
                spx[-1], (3, 3), (self.stride, self.stride),
                padding=1) if is_first else spx[-1])
        y = jnp.concatenate(spo, axis=-1)

        y = Conv2d(outplanes, 1, dtype=self.dtype, name="conv3")(y)
        y = BatchNorm2d(**bn, name="bn3",
                        scale_init=nn.initializers.zeros
                        if self.zero_init_last_bn else None)(
            y, training=training)
        attn = create_attn(self.attn_layer, dtype=self.dtype, name="se")
        if attn is not None:
            y = attn(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path")(
                y, training=training)
        if self.has_downsample:
            residual = _Downsample(
                outplanes, self.down_kernel_size, self.stride, self.dilation,
                self.first_dilation, avg=self.avg_down, bn=self.bn,
                dtype=self.dtype, name="downsample")(x, training=training)
        return act(y + residual)


register_block("bottle2neck", Bottle2neck)


# name: (layers, base_width, extra ResNet kwargs, block_args)
_RES2NET_DEFS = {
    "res2net50_26w_4s": ((3, 4, 6, 3), 26, {}, dict(scale=4)),
    "res2net101_26w_4s": ((3, 4, 23, 3), 26, {}, dict(scale=4)),
    "res2net50_26w_6s": ((3, 4, 6, 3), 26, {}, dict(scale=6)),
    "res2net50_26w_8s": ((3, 4, 6, 3), 26, {}, dict(scale=8)),
    "res2net50_48w_2s": ((3, 4, 6, 3), 48, {}, dict(scale=2)),
    "res2net50_14w_8s": ((3, 4, 6, 3), 14, {}, dict(scale=8)),
    "res2next50": ((3, 4, 6, 3), 4, dict(cardinality=8), dict(scale=4)),
}


def _register():
    for name, (layers, bw, extra, block_args) in _RES2NET_DEFS.items():
        def fn(pretrained=False, *, _layers=layers, _bw=bw, _extra=extra,
               _ba=block_args, **kwargs):
            kwargs.pop("pretrained", None)
            ba = {**_ba, **kwargs.pop("block_args", {})}
            kwargs.setdefault("default_cfg", _cfg())
            return ResNet(block="bottle2neck", layers=tuple(_layers),
                          base_width=_bw, block_args=ba,
                          **{**_extra, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference res2net.py entrypoint)."
        register_model(fn)


_register()
