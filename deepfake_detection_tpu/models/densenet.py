"""DenseNet-BC family (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/densenet.py`` (214 LoC):
``_DenseLayer`` pre-activation BN→ReLU→1×1→BN→ReLU→3×3 with channel concat
(:37-54), ``_Transition`` halving (:65-72), :class:`DenseNet` (:75-160), and
the 4 entrypoints (:168-214).

TPU note: the growing concat chain is memory-unfriendly; XLA keeps each
block's concat buffer alive only within the fused region, and NHWC concat on
the channel axis is layout-free.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, max_pool2d_torch
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["DenseNet"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bicubic",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="features.conv0", classifier="classifier")
    cfg.update(kwargs)
    return cfg


class DenseNet(nn.Module):
    """Densenet-BC (reference densenet.py:75-160)."""
    growth_rate: int = 32
    block_config: Sequence[int] = (6, 12, 24, 16)
    num_init_features: int = 64
    bn_size: int = 4
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name, dtype=self.dtype)
        # stem (:97-103)
        x = Conv2d(self.num_init_features, 7, stride=2, dtype=self.dtype,
                   name="conv0")(x)
        x = BatchNorm2d(**bn, name="norm0")(x, training=training)
        x = nn.relu(x)
        x = max_pool2d_torch(x, (3, 3), (2, 2), padding=1)

        stage_feats = []
        for bi, num_layers in enumerate(self.block_config):
            # dense block (:57-62): each layer sees everything before it
            for li in range(num_layers):
                y = BatchNorm2d(**bn, name=f"block{bi}_l{li}_norm1")(
                    x, training=training)
                y = nn.relu(y)
                y = Conv2d(self.bn_size * self.growth_rate, 1,
                           dtype=self.dtype, name=f"block{bi}_l{li}_conv1")(y)
                y = BatchNorm2d(**bn, name=f"block{bi}_l{li}_norm2")(
                    y, training=training)
                y = nn.relu(y)
                y = Conv2d(self.growth_rate, 3, dtype=self.dtype,
                           name=f"block{bi}_l{li}_conv2")(y)
                if self.drop_rate > 0:
                    y = nn.Dropout(rate=self.drop_rate,
                                   deterministic=not training)(y)
                x = jnp.concatenate([x, y], axis=-1)
            stage_feats.append(x)
            if bi != len(self.block_config) - 1:
                # transition (:65-72): BN→ReLU→1×1 half→avgpool 2
                x = BatchNorm2d(**bn, name=f"transition{bi}_norm")(
                    x, training=training)
                x = nn.relu(x)
                x = Conv2d(x.shape[-1] // 2, 1, dtype=self.dtype,
                           name=f"transition{bi}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = BatchNorm2d(**bn, name="norm5")(x, training=training)
        x = nn.relu(x)
        if features_only:
            stage_feats[-1] = x
            return stage_feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="classifier")(x)


# name: (growth_rate, block_config, num_init_features)  (reference :168-214)
_DENSENET_DEFS = {
    "densenet121": (32, (6, 12, 24, 16), 64),
    "densenet169": (32, (6, 12, 32, 32), 64),
    "densenet201": (32, (6, 12, 48, 32), 64),
    "densenet161": (48, (6, 12, 36, 24), 96),
}


def _register():
    for name, (gr, blocks, init_f) in _DENSENET_DEFS.items():
        def fn(pretrained=False, *, _gr=gr, _blocks=blocks, _init=init_f,
               **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return DenseNet(growth_rate=_gr, block_config=tuple(_blocks),
                            num_init_features=_init, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference densenet.py entrypoint)."
        register_model(fn)


_register()
