"""Model factory.

Re-design of ``/root/reference/dfd/timm/models/factory.py`` (252 LoC):
``create_model`` (:8) plus the three deepfake variants that differ only in
defaults (num_classes=2) and checkpoint-loading strictness —
``create_deepfake_model`` (:67), ``_v3`` (:127), ``_v4`` (:190).

Flax split: the factory returns the *architecture* (a flax Module); parameters
live in a separate pytree created by :func:`init_model` (or loaded via
``checkpoint_path``).  ``create_model_and_params`` bundles both for
runner-level convenience.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..registry import is_model, is_model_in_modules, model_entrypoint

__all__ = ["create_model", "create_deepfake_model", "create_deepfake_model_v3",
           "create_deepfake_model_v4", "init_model", "create_model_and_params"]

# modules whose generators understand TF-BN kwargs (factory.py:33-38)
_BN_KWARG_MODULES = ("efficientnet", "mobilenetv3")
# modules that consume the remat policy (TrainConfig.checkpoint_policy)
_REMAT_MODULES = _BN_KWARG_MODULES + ("vit", "timesformer")
# modules with a pluggable attention kernel (TrainConfig.attn_impl)
_ATTN_MODULES = ("vit", "timesformer")

_DROP_BLOCK_MODULES = ("resnet", "res2net", "sknet", "gluon_resnet")
_ATTN_IMPLS = ("full", "flash", "ring", "ring_flash", "ulysses")


def create_model(model_name: str, pretrained: bool = False,
                 num_classes: int = 1000, in_chans: int = 3,
                 checkpoint_path: str = "", **kwargs):
    """Build a registered model (factory.py:8-64).

    Filters bn_tf/bn_momentum/bn_eps for non-EfficientNet families and maps the
    legacy ``drop_connect_rate`` onto ``drop_path_rate`` (factory.py:46-50).
    """
    model_args = dict(pretrained=pretrained, num_classes=num_classes,
                      in_chans=in_chans)
    if not is_model_in_modules(model_name, _BN_KWARG_MODULES):
        for k in ("bn_tf", "bn_momentum", "bn_eps"):
            kwargs.pop(k, None)
    if not is_model_in_modules(model_name, _REMAT_MODULES):
        v = kwargs.pop("remat_policy", None)
        if v not in (None, "none"):
            import logging
            logging.getLogger(__name__).warning(
                "remat_policy=%r is only consumed by the %s families; "
                "ignored for %s", v, _REMAT_MODULES, model_name)
    if not is_model_in_modules(model_name, _BN_KWARG_MODULES):
        # the step-time optimization layer rewrites MBConv dw stages and the
        # 3x3-s2 stem — EfficientNet-family-only by construction
        fd = kwargs.pop("fused_depthwise", None)
        s2d = kwargs.pop("stem_s2d", None)
        if fd not in (None, "off") or s2d:
            raise ValueError(
                f"--fused-depthwise/--stem-s2d rewrite the EfficientNet-"
                f"family hot path ({_BN_KWARG_MODULES}); {model_name} has no "
                "depthwise/s2d-stem equivalent — silently training the stock "
                "path would invalidate the perf comparison")
    if (ai := kwargs.get("attn_impl")) is not None:
        if ai not in _ATTN_IMPLS:
            # a typo must not silently fall back to dense attention
            raise ValueError(f"attn_impl={ai!r}: expected one of "
                             f"{_ATTN_IMPLS}")
        if not is_model_in_modules(model_name, _ATTN_MODULES):
            kwargs.pop("attn_impl")
            import logging
            logging.getLogger(__name__).warning(
                "attn_impl=%r is only consumed by the %s families; "
                "ignored for %s", ai, _ATTN_MODULES, model_name)
    if str(kwargs.get("norm_layer", "")).startswith("split") and \
            not is_model_in_modules(model_name, _BN_KWARG_MODULES):
        # the user explicitly asked for AdvProp split-BN semantics —
        # silently training without them would be worse than failing
        raise ValueError(
            f"norm_layer={kwargs['norm_layer']!r} (--split-bn) is only "
            f"supported by the {_BN_KWARG_MODULES} families, not "
            f"{model_name} (the reference's post-hoc convert_splitbn_model "
            "has no flax equivalent)")
    if not is_model_in_modules(model_name, _DROP_BLOCK_MODULES):
        v = kwargs.pop("drop_block_rate", None)
        if v:
            import logging
            logging.getLogger(__name__).warning(
                "drop_block_rate=%r is only consumed by the %s families; "
                "ignored for %s (matches the reference factory's pop of "
                "unsupported drop_block_rate)", v, _DROP_BLOCK_MODULES,
                model_name)
    dcr = kwargs.pop("drop_connect_rate", None)
    if dcr is not None and "drop_path_rate" not in kwargs:
        kwargs["drop_path_rate"] = dcr
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    if not is_model(model_name):
        raise KeyError(f"Unknown model {model_name!r}")
    model = model_entrypoint(model_name)(**model_args, **kwargs)
    if checkpoint_path:
        # parameters are loaded separately in the flax world; keep the arg for
        # interface parity and surface it via attribute-free convention
        from .helpers import load_checkpoint  # late import, avoids cycle
        model = model  # architecture unchanged; load happens in init path
    return model


def create_deepfake_model(model_name: str = "efficientnet_b7_deepfake",
                          pretrained: bool = False, num_classes: int = 2,
                          in_chans: int = 3, **kwargs):
    """Deepfake default wrapper (factory.py:67-124): num_classes=2."""
    return create_model(model_name, pretrained=pretrained,
                        num_classes=num_classes, in_chans=in_chans, **kwargs)


def create_deepfake_model_v3(model_name: str = "efficientnet_deepfake_v3",
                             pretrained: bool = False, num_classes: int = 2,
                             in_chans: int = 12, **kwargs):
    """v3 wrapper (factory.py:127-187) — asserts its model name (:150)."""
    assert model_name == "efficientnet_deepfake_v3", \
        f"create_deepfake_model_v3 only builds efficientnet_deepfake_v3, got {model_name!r}"
    return create_model(model_name, pretrained=pretrained,
                        num_classes=num_classes, in_chans=in_chans, **kwargs)


def create_deepfake_model_v4(model_name: str = "efficientnet_deepfake_v4",
                             pretrained: bool = False, num_classes: int = 2,
                             in_chans: int = 12, **kwargs):
    """v4 wrapper (factory.py:190-252) — asserts its model name (:213)."""
    assert model_name == "efficientnet_deepfake_v4", \
        f"create_deepfake_model_v4 only builds efficientnet_deepfake_v4, got {model_name!r}"
    return create_model(model_name, pretrained=pretrained,
                        num_classes=num_classes, in_chans=in_chans, **kwargs)


def init_model(model, rng: jax.Array, input_shape: Tuple[int, ...],
               training: bool = False, dtype=jnp.float32) -> Dict[str, Any]:
    """Initialize variables ({'params', 'batch_stats', ...}) for a model.

    ``input_shape`` is NHWC, e.g. ``(1, 600, 600, 12)``.

    The init runs under ``jax.jit``: eager Flax init dispatches every
    constituent op separately, which is pathological on high-dispatch-latency
    backends (the axon TPU relay: ~0.5-1 s per dispatch x hundreds of ops in
    an EfficientNet made bare ``model.init`` take >10 min); one compiled
    program is a single dispatch, and the compile is shared through the
    persistent compilation cache.
    """
    dummy = jnp.zeros(input_shape, dtype)
    p_rng, d_rng = jax.random.split(rng)

    def _init(p_rng, d_rng, dummy):
        return model.init({"params": p_rng, "dropout": d_rng}, dummy,
                          training=training)

    return jax.jit(_init)(p_rng, d_rng, dummy)


def create_model_and_params(model_name: str, rng: Optional[jax.Array] = None,
                            input_shape: Optional[Tuple[int, ...]] = None,
                            checkpoint_path: str = "", **kwargs):
    """Convenience: build + init (+ optional checkpoint load)."""
    model = create_model(model_name, **kwargs)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if input_shape is None:
        cfg = getattr(model, "default_cfg", None) or {}
        c, h, w = cfg.get("input_size", (3, 224, 224))
        input_shape = (1, h, w, c)
    variables = init_model(model, rng, input_shape)
    if checkpoint_path:
        from .helpers import load_checkpoint
        variables = load_checkpoint(variables, checkpoint_path)
    return model, variables
