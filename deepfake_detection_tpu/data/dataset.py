"""Datasets.

Re-design of ``/root/reference/dfd/timm/data/dataset.py``.  The active class is
:class:`DeepFakeClipDataset` — parity with ``DeepFakeDataset_v3`` (:378-528):

* per-root ``real_list.txt`` / ``fake_list.txt`` with ``name:num_frames``
  lines (``get_all_images_list_v3`` :362-373);
* loads ``frames_per_clip`` (4) frames ``<root>/{fake,real}/<name>/<i>.jpg``,
  front-padding short clips by repeating ``0.jpg`` (:496-512);
* labels: 0 = fake, 1 = real; fakes come first in index space (:477-483);
* seeded train/val split (:424-438) and label-balance fake bucketing with a
  rotating per-bucket cursor (:460-491);
* optional ``noise_fake`` fake-label flipping (:520-521).

Determinism fixes over the reference (SURVEY.md §7 "hard parts" #3):

* The reference's val split is ``set``-difference — *nondeterministic order*
  (:437-438).  Here the split is a seeded permutation; val is the complement
  in deterministic order, so every host/process sees the same split.
* The reference's bucket rotation mutates ``self.fakeIndexes`` inside
  ``__getitem__`` — per-dataloader-worker state, so the clip actually chosen
  depends on worker layout.  Here the cursor is pure index arithmetic:
  ``cursor = (epoch + visit) % len(bucket)`` driven by :meth:`set_epoch`,
  reproducing the rotation semantics (each epoch advances every bucket by its
  per-epoch visit count) statelessly across any host/worker layout.
* ``noise_fake`` flipping uses the per-sample RNG, not global ``random``.

All datasets return ``(np.uint8 array (H, W, C) NHWC, int label)`` once a
transform is set, and accept the per-sample ``numpy.random.Generator`` derived
from ``(seed, epoch, index)``.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from . import native

__all__ = ["AugMixDataset", "ConcatDataset", "DatasetTar",
           "DeepFakeClipDataset", "FolderDataset",
           "SyntheticDataset", "clip_frame_paths", "read_clip_list",
           "split_clips"]

_IMG_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp")


def _load_images(paths: List[str]) -> List[Image.Image]:
    """Decode a clip's frames — C++ pool when available, PIL otherwise.

    The native path decodes all of the clip's JPEG frames concurrently
    outside the GIL (data/native.py); non-JPEG paths go straight to PIL
    (no wasted native read), and any JPEG the native decoder rejects
    (corrupt, exotic colorspace) falls back to PIL individually, so behavior
    is identical either way.
    """
    pool = native.default_pool()
    if pool is not None:
        # dedup: front-padded clips repeat 0.jpg — decode it once
        jpeg_paths = list(dict.fromkeys(
            p for p in paths if p.lower().endswith((".jpg", ".jpeg"))))
        decoded = dict(zip(jpeg_paths, pool.decode_files(jpeg_paths)))
        out = []
        for p in paths:
            a = decoded.get(p)
            out.append(Image.fromarray(a) if a is not None
                       else Image.open(p).convert("RGB"))
        return out
    return [Image.open(p).convert("RGB") for p in paths]


def read_clip_list(list_file: str, root_index: int = 0
                   ) -> List[Tuple[str, int, int]]:
    """Parse one ``name:num_frames`` list file (reference :362-373).

    Returns ``[(clip_name, num_frames, root_index), ...]``; missing files
    yield an empty list (the reference silently skips them too).
    """
    if not os.path.isfile(list_file):
        return []
    out = []
    with open(list_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            name, num = line.split(":")
            out.append((name, int(num), root_index))
    return out


def clip_frame_paths(roots: Sequence[str], kind: str,
                     clip: Tuple[str, int, int],
                     frames_per_clip: int) -> List[str]:
    """Frame paths for one clip, front-padded with frame 0 (reference
    :496-512).  Clips longer than ``frames_per_clip`` use the first
    ``frames_per_clip`` frames (the reference would emit a ragged channel
    count and crash downstream; clamping is the sane reading).  Module-level
    so the dataset cache packer (tools/pack_dataset.py) resolves the exact
    frames the runtime decode path would."""
    name, num, root_index = clip
    num = int(num)
    base = os.path.join(roots[int(root_index)], kind, name)
    k = frames_per_clip
    if num >= k:
        idxs: List[int] = list(range(k))
    else:
        idxs = [0] * (k - num) + list(range(num))
    return [os.path.join(base, f"{i}.jpg") for i in idxs]


def split_clips(clips: Sequence[Tuple], train_ratio: float, seed: int,
                is_training: bool) -> List[Tuple]:
    """Deterministic seeded train/val split.

    Train = seeded sample of ``int(len * ratio)`` clips (reference :429-433);
    val = the complement **in deterministic original order** (fixes the
    reference's set-difference nondeterminism, :437-438).
    """
    n = len(clips)
    n_train = int(n * train_ratio)
    if n_train < 1:
        return list(clips)  # reference keeps the full list if sample < 1
    perm = np.random.default_rng(seed).permutation(n)
    train_idx = set(perm[:n_train].tolist())
    if is_training:
        return [clips[i] for i in sorted(train_idx)]
    return [clips[i] for i in range(n) if i not in train_idx]


def _array_split_buckets(items: List[Any], n_buckets: int) -> List[List[Any]]:
    """``np.array_split`` semantics on a plain list (reference :460-476)."""
    n_buckets = max(1, n_buckets)
    splits = np.array_split(np.arange(len(items)), n_buckets)
    return [[items[i] for i in idx] for idx in splits]


class DeepFakeClipDataset:
    """4-frame clip dataset in the v3 list-file format."""

    def __init__(self, roots, frames_per_clip: int = 4,
                 transform: Optional[Callable] = None,
                 train_split: bool = False, train_ratio: float = 0.0,
                 is_training: bool = False, label_balance: bool = False,
                 noise_fake: bool = False, split_seed: int = 0,
                 frac: float = 1.0, n: Optional[int] = None):
        if isinstance(roots, str):
            roots = [r for r in roots.split(":") if r]
        self.roots = list(roots)
        self.frames_per_clip = frames_per_clip
        self.transform = transform
        self.noise_fake = noise_fake
        self.epoch = 0

        real: List[Tuple[str, int, int]] = []
        fake: List[Tuple[str, int, int]] = []
        for ri in range(self._num_roots()):
            r, f = self._read_root_lists(ri)
            real += r
            fake += f

        if train_split:
            real = split_clips(real, train_ratio, split_seed, is_training)
            fake = split_clips(fake, train_ratio, split_seed, is_training)
        else:
            # fraction / fixed-count subsetting (reference :441-457)
            rng = np.random.default_rng(split_seed)
            if 0 < frac < 1:
                if int(len(real) * frac) >= 1:
                    real = [real[i] for i in sorted(
                        rng.choice(len(real), int(len(real) * frac),
                                   replace=False))]
                if int(len(fake) * frac) >= 1:
                    fake = [fake[i] for i in sorted(
                        rng.choice(len(fake), int(len(fake) * frac),
                                   replace=False))]
            elif n:
                if len(real) > n:
                    real = [real[i] for i in sorted(
                        rng.choice(len(real), n, replace=False))]
                if len(fake) > n:
                    fake = [fake[i] for i in sorted(
                        rng.choice(len(fake), n, replace=False))]

        self.real_clips = real
        # bucket the fakes (reference :460-491): without label_balance every
        # fake is its own bucket; with it, fakes collapse into len(real)
        # buckets so index space is 50/50 balanced.
        if fake:
            if label_balance and real and len(real) < len(fake):
                self.fake_buckets = _array_split_buckets(fake, len(real))
            else:
                self.fake_buckets = _array_split_buckets(fake, len(fake))
        else:
            self.fake_buckets = []

    # ------------------------------------------------------------------
    # hooks subclasses override to swap the clip SOURCE (the packed-cache
    # dataset replaces both with index-file/mmap lookups, data/packed.py)
    def _num_roots(self) -> int:
        return len(self.roots)

    def _read_root_lists(self, root_index: int
                         ) -> Tuple[List[Tuple[str, int, int]],
                                    List[Tuple[str, int, int]]]:
        """(real, fake) clip lists for one root, in list-file order (the
        seeded split/bucketing downstream is order-sensitive)."""
        root = self.roots[root_index]
        return (read_clip_list(os.path.join(root, "real_list.txt"),
                               root_index),
                read_clip_list(os.path.join(root, "fake_list.txt"),
                               root_index))

    def _load_clip(self, kind: str, clip: Tuple[str, int, int]):
        """Decode one clip's frames (front-padded to ``frames_per_clip``)."""
        return _load_images(self._clip_paths(kind, clip))

    # ------------------------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        """Advance the stateless bucket-rotation cursor."""
        self.epoch = epoch

    def set_transform(self, transform: Callable) -> None:
        self.transform = transform

    def __len__(self) -> int:
        return len(self.fake_buckets) + len(self.real_clips)

    # ------------------------------------------------------------------
    def _clip_paths(self, kind: str, clip: Tuple[str, int, int]) -> List[str]:
        return clip_frame_paths(self.roots, kind, clip, self.frames_per_clip)

    def sample_clip(self, index: int, epoch: Optional[int] = None
                    ) -> Tuple[str, Tuple[str, int, int], int]:
        """(kind, clip tuple, label) for one index — pure function of
        (index, epoch): fake buckets rotate their cursor with the epoch,
        reals are direct."""
        epoch = self.epoch if epoch is None else epoch
        if index < len(self.fake_buckets):
            bucket = self.fake_buckets[index]
            return "fake", bucket[epoch % len(bucket)], 0
        return "real", self.real_clips[index - len(self.fake_buckets)], 1

    def sample_paths(self, index: int, epoch: Optional[int] = None
                     ) -> Tuple[List[str], int]:
        """(frame paths, label) for one index — pure function of
        (index, epoch)."""
        kind, clip, target = self.sample_clip(index, epoch)
        return self._clip_paths(kind, clip), target

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        rng = rng if rng is not None else np.random.default_rng(
            np.random.SeedSequence([self.epoch, index]))
        kind, clip, target = self.sample_clip(index)
        imgs = self._load_clip(kind, clip)
        if self.transform is not None:
            imgs = self.transform(imgs, rng)
        if target == 0 and self.noise_fake:
            target = 0 if rng.random() < 0.5 else 1  # reference :520-521
        return imgs, target


class FolderDataset:
    """ImageNet-style ``root/class_x/*.jpg`` folder dataset (reference
    ``Dataset`` :77-124), single-frame."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 class_to_idx: Optional[dict] = None):
        self.root = root
        self.transform = transform
        samples: List[Tuple[str, int]] = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = class_to_idx or {c: i for i, c in enumerate(classes)}
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_IMG_EXTENSIONS):
                    samples.append((os.path.join(cdir, fn),
                                    self.class_to_idx[c]))
        if not samples:
            raise RuntimeError(f"no images found under {root!r}")
        self.samples = samples
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_transform(self, transform: Callable) -> None:
        self.transform = transform

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        rng = rng if rng is not None else np.random.default_rng(
            np.random.SeedSequence([self.epoch, index]))
        path, target = self.samples[index]
        img = _load_images([path])[0]
        if self.transform is not None:
            img = self.transform(img, rng)
        return img, target


class DatasetTar:
    """Image dataset inside a single tar file (reference ``DatasetTar``,
    dataset.py:602-630): class = parent directory name inside the archive,
    classes sorted by natural key.

    TPU-era changes: the tar handle is per-*thread* (``threading.local``) —
    the HostLoader parallelizes with threads, not forked workers, and one
    shared handle would interleave concurrent ``extractfile`` reads;
    ``__getitem__`` takes the explicit per-sample rng like every dataset
    here."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 class_to_idx: Optional[dict] = None):
        import tarfile
        import threading

        from ..utils import natural_key
        assert os.path.isfile(root), root
        self.root = root
        self.transform = transform
        with tarfile.open(root) as tf:
            infos = [ti for ti in tf.getmembers() if ti.isfile()
                     and ti.name.lower().endswith(_IMG_EXTENSIONS)]
        labels = [os.path.basename(os.path.dirname(ti.name)) for ti in infos]
        if class_to_idx is None:
            class_to_idx = {c: i for i, c in enumerate(
                sorted(set(labels), key=natural_key))}
        self.class_to_idx = class_to_idx
        pairs = sorted(zip(infos, labels), key=lambda p: natural_key(
            p[0].name))
        self.samples = [(ti, class_to_idx[lb]) for ti, lb in pairs]
        self._local = threading.local()
        self.epoch = 0

    def _tar(self):
        import tarfile
        tf = getattr(self._local, "tf", None)
        if tf is None:
            tf = self._local.tf = tarfile.open(self.root)
        return tf

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_transform(self, transform: Callable) -> None:
        self.transform = transform

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        rng = rng if rng is not None else np.random.default_rng(
            np.random.SeedSequence([self.epoch, index]))
        tarinfo, target = self.samples[index]
        iob = self._tar().extractfile(tarinfo)
        data = iob.read()
        arr = native.decode_jpeg_bytes(data) if tarinfo.name.lower(
            ).endswith((".jpg", ".jpeg")) else None
        if arr is not None:
            img: Any = Image.fromarray(arr)
        else:
            import io
            img = Image.open(io.BytesIO(data)).convert("RGB")
        if self.transform is not None:
            img = self.transform(img, rng)
        return img, target


class ConcatDataset:
    """Concatenation of datasets (reference ``ConcatDataset``,
    dataset.py:229-265): bisect over cumulative sizes; ``set_epoch`` /
    ``set_transform`` fan out to every child."""

    def __init__(self, datasets: Sequence[Any]):
        assert datasets, "datasets should not be an empty iterable"
        self.datasets = list(datasets)
        self.cumulative_sizes = list(np.cumsum(
            [len(d) for d in self.datasets]))

    def set_epoch(self, epoch: int) -> None:
        for d in self.datasets:
            if hasattr(d, "set_epoch"):
                d.set_epoch(epoch)

    def set_transform(self, transform: Callable) -> None:
        for d in self.datasets:
            if hasattr(d, "set_transform"):
                d.set_transform(transform)

    def __len__(self) -> int:
        return int(self.cumulative_sizes[-1])

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        import bisect
        if index < 0:
            if -index > len(self):
                raise ValueError("index out of range")
            index = len(self) + index
        di = bisect.bisect_right(self.cumulative_sizes, index)
        local = index if di == 0 else \
            index - int(self.cumulative_sizes[di - 1])
        return self.datasets[di].__getitem__(local, rng=rng)


class SyntheticDataset:
    """Deterministic random-image dataset for smoke tests and benchmarking
    (no reference analog; replaces 'point the trainer at real data' for CI)."""

    def __init__(self, length: int = 64, image_shape=(600, 600, 12),
                 num_classes: int = 2, seed: int = 0,
                 transform: Optional[Callable] = None):
        self.length = length
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.seed = seed
        self.transform = transform  # accepted for interface parity; unused
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_transform(self, transform: Callable) -> None:
        self.transform = transform

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        g = np.random.default_rng(np.random.SeedSequence([self.seed, index]))
        img = g.integers(0, 256, self.image_shape, dtype=np.uint8)
        target = int(g.integers(0, self.num_classes))
        return img, target


class AugMixDataset:
    """Clean + augmented multi-view wrapper (reference dataset.py:633-670).

    Wraps any dataset producing post-transform ``(H, W, 3*img_num)`` uint8
    clips and emits ``num_splits`` stacked views per sample: the clean base
    output first, then ``num_splits-1`` AugMix-augmented copies (each frame
    slice augmented independently in the uint8 domain — equivalent to the
    reference's augment-before-normalize split, since normalization here
    happens on device and applies to every split identically).  The JSD loss
    (losses.py:jsd_cross_entropy) consumes the split-major batch the collate
    builds from these.
    """

    def __init__(self, dataset, num_splits: int = 2,
                 aug_config: str = "augmix-m3-w3"):
        from .auto_augment import augment_and_mix_transform
        assert num_splits >= 2, num_splits
        self.dataset = dataset
        self.num_splits = num_splits
        self.augment = augment_and_mix_transform(aug_config)

    def set_transform(self, transform: Callable) -> None:
        self.dataset.set_transform(transform)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.dataset)

    def _augment_clip(self, clip: np.ndarray,
                      rng: np.random.Generator) -> np.ndarray:
        frames = []
        for f in range(clip.shape[-1] // 3):
            img = Image.fromarray(clip[..., 3 * f:3 * f + 3])
            frames.append(np.asarray(self.augment(img, rng), dtype=np.uint8))
        return np.concatenate(frames, axis=-1)

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        epoch = getattr(self.dataset, "epoch", 0)
        rng = rng if rng is not None else np.random.default_rng(
            np.random.SeedSequence([epoch, index]))
        clip, target = self.dataset.__getitem__(index, rng=rng)
        clip = np.asarray(clip, dtype=np.uint8)
        views = [clip]
        for _ in range(self.num_splits - 1):
            views.append(self._augment_clip(clip, rng))
        return np.stack(views), target
