"""Transform pipeline factories.

Parity with ``/root/reference/dfd/timm/data/transforms_factory.py``:

* ``transforms_deepfake_train_v3`` (:137-183) — the active 4-frame train
  pipeline: MultiRotate → MultiRandomHorizontalFlip → MultiRandomResize
  (scale 2/3–3/2) → MultiRandomCrop(600², pad_if_needed) → [MultiBlur] →
  MultiColorJitter → [MultiFlicker] → MultiToNumpy → MultiConcate.
* ``transforms_deepfake_eval_v3`` (:225-236) — random-crop only (the
  reference evaluates with a *random* crop, not center crop; kept for parity).
* ``transforms_imagenet_train`` / ``transforms_imagenet_eval`` (:239-355) —
  the single-frame ImageNet pipelines with AutoAugment/RandAugment/AugMix
  hooks.
* ``create_transform`` dispatcher (:358+).

Normalization and RandomErasing are *not* part of these pipelines: the host
emits uint8 NHWC and the device prologue (loader.DeviceLoader) normalizes —
the reference's prefetcher split, which is exactly the right split on TPU.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple, Union

from .auto_augment import (augment_and_mix_transform, auto_augment_transform,
                           rand_augment_transform)
from .constants import (DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN,
                        IMAGENET_DEFAULT_STD)
from .transforms import (CenterCrop, ColorJitter, Compose,
                         DeviceAugmentPassthrough, MultiBlur,
                         MultiCenterCrop, MultiColorJitter, MultiConcate,
                         MultiFlicker, MultiFusedGeometric,
                         MultiRandomCrop, MultiRandomHorizontalFlip,
                         MultiRandomResize, MultiRotate, MultiToNumpy,
                         RandomHorizontalFlip,
                         RandomResizedCropAndInterpolation, RandomVerticalFlip,
                         Resize, ToNumpy)

__all__ = ["transforms_deepfake_train_v3", "transforms_deepfake_eval_v3",
           "transforms_deepfake_train_passthrough",
           "transforms_imagenet_train", "transforms_imagenet_eval",
           "create_transform"]


def _blur_radius_compat(blur_radius, blur_radiu):
    """``blur_radiu`` (the reference's misspelling) stays accepted as a
    deprecated alias — YAML configs and launch scripts written against
    the old flag keep working, loudly."""
    if blur_radius is None and blur_radiu is not None:
        import warnings
        warnings.warn("blur_radiu= is deprecated; use blur_radius=",
                      DeprecationWarning, stacklevel=3)
        return blur_radiu
    return 0 if blur_radius is None else blur_radius


def transforms_deepfake_train_v3(
        img_size: Union[int, Tuple[int, int]] = 600,
        color_jitter: Any = 0.4, flicker: float = 0.0,
        rotate_range: float = 0, blur_radius: Optional[float] = None,
        blur_prob: float = 0.0, fused_geom: bool = True,
        blur_radiu: Optional[float] = None,
        **unused) -> Compose:
    """The active 4-frame train pipeline (reference :137-183).

    ``fused_geom=True`` (default) renders rotate/flip/resize/crop as ONE
    native bilinear warp per frame (same parameter distribution, one
    resample instead of three — see MultiFusedGeometric); ``False`` keeps
    the reference-exact sequential PIL chain.  ``color_jitter=None`` /
    ``flicker=0`` lets the loader apply those stages on-device instead
    (loader.py DeviceLoader prologue) — host PIL jitter at 600² costs more
    than the whole decode."""
    blur_radius = _blur_radius_compat(blur_radius, blur_radiu)
    if fused_geom:
        primary: list = [MultiFusedGeometric(
            img_size, rotate_range=rotate_range, scale=(2.0 / 3, 3.0 / 2.0))]
    else:
        primary = [
            MultiRotate(rotate_range),
            MultiRandomHorizontalFlip(),
            MultiRandomResize(scale=(2.0 / 3, 3.0 / 2.0)),
            MultiRandomCrop(img_size, pad_if_needed=True),
        ]
    if blur_prob > 0.0:
        primary.append(MultiBlur(blur_prob, blur_radius))
    secondary = []
    if color_jitter is not None:
        if isinstance(color_jitter, (list, tuple)):
            assert len(color_jitter) in (3, 4)
        else:
            color_jitter = (float(color_jitter),) * 3
        secondary.append(MultiColorJitter(*color_jitter))
    if flicker > 0.0:
        secondary.append(MultiFlicker(flicker))
    final = [MultiToNumpy(), MultiConcate()]
    return Compose(primary + secondary + final)


def transforms_deepfake_train_passthrough(
        img_size: Union[int, Tuple[int, int]] = 600,
        rotate_range: float = 0, blur_prob: float = 0.0) -> Compose:
    """The ``--augment-device on`` host pipeline: ONE passthrough stage.

    The geometric warp, blur, jitter/flicker and the mixup blend all run
    in the DeviceLoader's jitted prologue; the host only consumes the
    chain's rng draws (stream-position parity, see
    :class:`~.transforms.DeviceAugmentPassthrough`) and hands the raw
    source clip to the collate memcpy.  Same knob meanings as
    :func:`transforms_deepfake_train_v3` — the scale range is the chain's
    fixed (2/3, 3/2)."""
    return Compose([DeviceAugmentPassthrough(
        img_size, rotate_range=rotate_range, scale=(2.0 / 3, 3.0 / 2.0),
        blur_prob=blur_prob)])


def transforms_deepfake_eval_v3(img_size: Union[int, Tuple[int, int]] = 224,
                                crop: str = "random") -> Compose:
    """Eval pipeline (reference :225-236).

    ``crop='random'`` reproduces the reference quirk (eval uses a *random*
    crop — parity default); ``crop='center'`` is the opt-in deterministic
    eval (``--eval-crop center``) for run-to-run comparable AUC."""
    assert crop in ("random", "center"), crop
    crop_t = (MultiRandomCrop(img_size, pad_if_needed=True)
              if crop == "random" else MultiCenterCrop(img_size))
    return Compose([crop_t, MultiToNumpy(), MultiConcate()])


def transforms_imagenet_train(
        img_size: Union[int, Tuple[int, int]] = 224,
        scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
        hflip: float = 0.5, vflip: float = 0.0, color_jitter: Any = 0.4,
        auto_augment: Optional[str] = None,
        interpolation: str = "random",
        mean=IMAGENET_DEFAULT_MEAN) -> Compose:
    """Single-frame ImageNet train pipeline (reference :239-318)."""
    tfl: list = [RandomResizedCropAndInterpolation(
        img_size, scale=scale, ratio=ratio, interpolation=interpolation)]
    if hflip > 0.0:
        tfl.append(RandomHorizontalFlip(p=hflip))
    if vflip > 0.0:
        tfl.append(RandomVerticalFlip(p=vflip))
    if auto_augment:
        assert isinstance(auto_augment, str)
        sz = img_size if isinstance(img_size, int) else min(img_size)
        aa_params = dict(
            translate_const=int(sz * 0.45),
            img_mean=tuple(min(255, round(255 * x)) for x in mean),
        )
        if interpolation and interpolation != "random":
            aa_params["interpolation"] = interpolation
        if auto_augment.startswith("rand"):
            tfl.append(rand_augment_transform(auto_augment, aa_params))
        elif auto_augment.startswith("augmix"):
            tfl.append(augment_and_mix_transform(auto_augment, aa_params))
        else:
            tfl.append(auto_augment_transform(auto_augment, aa_params))
    elif color_jitter is not None:
        if isinstance(color_jitter, (list, tuple)):
            assert len(color_jitter) in (3, 4)
        else:
            color_jitter = (float(color_jitter),) * 3
        tfl.append(ColorJitter(*color_jitter))
    tfl.append(ToNumpy())
    return Compose(tfl)


def transforms_imagenet_eval(img_size: Union[int, Tuple[int, int]] = 224,
                             crop_pct: Optional[float] = None,
                             interpolation: str = "bilinear") -> Compose:
    """Resize-shorter-side + center crop (reference :321-355)."""
    crop_pct = crop_pct or DEFAULT_CROP_PCT
    if isinstance(img_size, (tuple, list)):
        assert len(img_size) == 2
        if img_size[-1] == img_size[-2]:
            scale_size: Any = int(math.floor(img_size[0] / crop_pct))
        else:
            scale_size = tuple(int(x / crop_pct) for x in img_size)
    else:
        scale_size = int(math.floor(img_size / crop_pct))
    return Compose([Resize(scale_size, interpolation), CenterCrop(img_size),
                    ToNumpy()])


def create_transform(input_size, is_training: bool = False,
                     tf_preprocessing: bool = False, **kwargs):
    """Dispatch to train or eval ImageNet pipeline (reference :358+);
    ``tf_preprocessing=True`` selects the TF-semantics bridge (reference
    :381-385 — here TF-free, data/tf_preprocessing.py)."""
    img_size = input_size[-2:] if isinstance(input_size, (tuple, list)) \
        else input_size
    if isinstance(img_size, (tuple, list)) and img_size[0] == img_size[1]:
        img_size = img_size[0]
    if tf_preprocessing:
        from .tf_preprocessing import TfPreprocessTransform
        return TfPreprocessTransform(
            is_training=is_training, size=img_size,
            interpolation=kwargs.get("interpolation", "bicubic"))
    if is_training:
        keys = ("scale", "ratio", "hflip", "vflip", "color_jitter",
                "auto_augment", "interpolation", "mean")
        return transforms_imagenet_train(
            img_size, **{k: v for k, v in kwargs.items() if k in keys})
    keys = ("crop_pct", "interpolation")
    return transforms_imagenet_eval(
        img_size, **{k: v for k, v in kwargs.items() if k in keys})
