"""Data pipeline: host decode/augment → uint8 NHWC → jitted device prologue.

TPU-native re-design of ``/root/reference/dfd/timm/data/`` (SURVEY.md §2.4):
deterministic index-space sampling replaces stateful datasets/samplers, NHWC
uint8 host batches replace CHW float tensors, and the CUDA-stream prefetcher
becomes a jitted normalize/cast/erase prologue with async dispatch.

The jax-dependent modules (loader, mixup, random_erasing, device_augment)
are imported LAZILY (PEP 562): shm-ring loader workers unpickle datasets by
module path, which executes this package ``__init__`` — an eager jax import
would cost every spawned decode worker seconds of startup and hundreds of
MB of RSS for code it never runs (N workers × jax ≫ the slabs themselves).
"""

from .config import resolve_data_config
from .constants import (DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN,
                        IMAGENET_DEFAULT_STD, IMAGENET_INCEPTION_MEAN,
                        IMAGENET_INCEPTION_STD)
from .dataset import (ConcatDataset, DatasetTar, DeepFakeClipDataset,
                      FolderDataset, SyntheticDataset,
                      read_clip_list, split_clips)
from .packed import (PackedCacheStale, PackedDataset, PackedShardCorrupt,
                     verify_pack, write_pack)
from .samplers import (OrderedShardedSampler, ShardedTrainSampler,
                       epoch_batches)
from .shm_ring import ShmRing, ShmRingLoader
from .transforms_factory import (create_transform, transforms_deepfake_eval_v3,
                                 transforms_deepfake_train_passthrough,
                                 transforms_deepfake_train_v3,
                                 transforms_imagenet_eval,
                                 transforms_imagenet_train)

# lazily-resolved (jax-importing) attributes: name -> submodule
_LAZY = {
    "DeviceLoader": "loader", "HostLoader": "loader",
    "create_deepfake_loader_v3": "loader", "create_loader": "loader",
    "fast_collate": "loader",
    "FastCollateMixup": "mixup", "mixup_batch": "mixup",
    "RandomErasing": "random_erasing", "random_erasing": "random_erasing",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        value = getattr(mod, name)
        globals()[name] = value        # cache: __getattr__ runs once per name
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
