"""Data pipeline: host decode/augment → uint8 NHWC → jitted device prologue.

TPU-native re-design of ``/root/reference/dfd/timm/data/`` (SURVEY.md §2.4):
deterministic index-space sampling replaces stateful datasets/samplers, NHWC
uint8 host batches replace CHW float tensors, and the CUDA-stream prefetcher
becomes a jitted normalize/cast/erase prologue with async dispatch.
"""

from .config import resolve_data_config
from .constants import (DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN,
                        IMAGENET_DEFAULT_STD, IMAGENET_INCEPTION_MEAN,
                        IMAGENET_INCEPTION_STD)
from .dataset import (ConcatDataset, DatasetTar, DeepFakeClipDataset,
                      FolderDataset, SyntheticDataset,
                      read_clip_list, split_clips)
from .loader import (DeviceLoader, HostLoader, create_deepfake_loader_v3,
                     create_loader, fast_collate)
from .mixup import FastCollateMixup, mixup_batch
from .random_erasing import RandomErasing, random_erasing
from .samplers import OrderedShardedSampler, ShardedTrainSampler
from .transforms_factory import (create_transform, transforms_deepfake_eval_v3,
                                 transforms_deepfake_train_v3,
                                 transforms_imagenet_eval,
                                 transforms_imagenet_train)
