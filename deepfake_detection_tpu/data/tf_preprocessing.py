"""TF-semantics preprocessing bridge, without TensorFlow.

Re-design of ``/root/reference/dfd/timm/data/tf_preprocessing.py`` (the
MnasNet/EfficientNet TF eval pipeline the reference exposes behind
``--tf-preprocessing``).  The reference builds a TF1 graph + Session per
transform and feeds raw JPEG bytes; here the same *math* runs on decoded
arrays in pure numpy — half-pixel-center separable resampling with the
Keys a=-0.5 bicubic (exactly ``tf.image.resize``'s default semantics,
antialias off), so TF resize behavior comes without a TF dependency and
without per-sample device dispatch from loader threads.

Exposed as a library surface: ``create_transform(..,
tf_preprocessing=True)`` (mirroring the reference's loader kwarg,
loader.py:381-385) — the active deepfake clip path never uses it, same
as the reference.

Parity notes (reference :108-127, :86-105, :135-175):

* eval: center crop of ``size/(size+CROP_PADDING) · min(H, W)`` (the
  "crop padding" formula), offsets ``((dim - crop) + 1) // 2``, then
  bicubic/bilinear resize to ``size``²;
* train: TF's ``sample_distorted_bounding_box`` over the whole image
  (aspect 3/4–4/3, area 8–100%, 10 attempts, center-crop fallback), then
  resize and a coin-flip horizontal mirror;
* output is uint8 HWC in [0, 255] — NHWC is this package's wire format
  (the reference emits CHW for torch, :225-228).

The per-sample RNG is the explicit ``numpy.random.Generator`` every
transform here receives; TF's graph-level randomness is not reproducible
across worker layouts, this is.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple, Union

import numpy as np

__all__ = ["TfPreprocessTransform", "CROP_PADDING"]

CROP_PADDING = 32          # reference :25


def _axis_weights(in_size: int, out_size: int, method: str
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-output-position tap indices and weights, TF2 semantics:
    half-pixel centers, no antialias widening, Keys bicubic a=-0.5."""
    scale = in_size / out_size
    center = (np.arange(out_size) + 0.5) * scale - 0.5
    base = np.floor(center).astype(int)
    if method == "bilinear":
        idx = np.stack([base, base + 1], 1)
        frac = center - base
        w = np.stack([1 - frac, frac], 1)
    else:                                    # bicubic, Keys a = -0.5
        idx = np.stack([base - 1, base, base + 1, base + 2], 1)
        t = np.abs(center[:, None] - idx)
        a = -0.5
        w = np.where(
            t <= 1, (a + 2) * t ** 3 - (a + 3) * t ** 2 + 1,
            np.where(t < 2,
                     a * t ** 3 - 5 * a * t ** 2 + 8 * a * t - 4 * a, 0.0))
    # boundary: taps outside the image are dropped and the remaining
    # weights renormalized (tf.image.resize / jax.image.resize semantics,
    # NOT edge-clamping — the two differ by several gray levels at borders)
    inside = (idx >= 0) & (idx < in_size)
    w = np.where(inside, w, 0.0)
    w = w / w.sum(axis=1, keepdims=True)
    return np.clip(idx, 0, in_size - 1), w.astype(np.float32)


def _resize(img: np.ndarray, size: int, interpolation: str) -> np.ndarray:
    """Separable numpy resample — pure host work: a per-sample
    ``jax.image.resize`` would recompile for every fresh random crop shape
    AND dispatch to the training TPU from loader threads."""
    method = "bicubic" if interpolation == "bicubic" else "bilinear"
    x = img.astype(np.float32)
    idx, w = _axis_weights(x.shape[0], size, method)
    x = (x[idx] * w[..., None, None]).sum(axis=1)        # rows
    idx, w = _axis_weights(x.shape[1], size, method)
    x = (x[:, idx] * w[None, ..., None]).sum(axis=2)     # cols
    return x


def _center_crop(img: np.ndarray, size: int,
                 interpolation: str) -> np.ndarray:
    """Reference ``_decode_and_center_crop`` (:108-127)."""
    h, w = img.shape[:2]
    crop = int((size / (size + CROP_PADDING)) * min(h, w))
    top = ((h - crop) + 1) // 2
    left = ((w - crop) + 1) // 2
    return _resize(img[top:top + crop, left:left + crop], size,
                   interpolation)


def _sample_distorted_box(h: int, w: int, rng: np.random.Generator,
                          area_range=(0.08, 1.0),
                          aspect_ratio_range=(3. / 4, 4. / 3),
                          min_object_covered: float = 0.1,
                          max_attempts: int = 10
                          ) -> Optional[Tuple[int, int, int, int]]:
    """TF ``sample_distorted_bounding_box`` over the whole-image bbox:
    aspect ratio UNIFORM in range (not torchvision's log-uniform), crop
    dims from the sampled area, a crop rejected unless it covers
    ``min_object_covered`` of the bbox (= the whole image here), uniform
    offsets; None after ``max_attempts`` failures (reference :86-105 then
    falls back to the center crop)."""
    area = h * w
    for _ in range(max_attempts):
        target_area = rng.uniform(*area_range) * area
        aspect = rng.uniform(*aspect_ratio_range)
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if not (0 < cw <= w and 0 < ch <= h):
            continue
        if ch * cw < min_object_covered * area:
            continue            # TF rejects crops covering <10% of the bbox
        top = int(rng.integers(0, h - ch + 1))
        left = int(rng.integers(0, w - cw + 1))
        return top, left, ch, cw
    return None


class TfPreprocessTransform:
    """Drop-in for the reference class (:199-228), PIL/ndarray → uint8 HWC."""

    def __init__(self, is_training: bool = False,
                 size: Union[int, Tuple[int, int]] = 224,
                 interpolation: str = "bicubic"):
        self.is_training = is_training
        self.size = size[0] if isinstance(size, (tuple, list)) else size
        self.interpolation = interpolation

    def __call__(self, img: Any,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        # the Compose chain always threads the per-sample (seed, epoch,
        # index) Generator; the no-rng fallback is for ad-hoc eval use and
        # must be deterministic, not wall-clock-entropy (dfdlint DFD003 —
        # an OS-seeded draw here would silently break resume parity if a
        # caller ever forgot to pass rng on the training path)
        rng = rng if rng is not None else np.random.default_rng(0)
        arr = np.asarray(img, dtype=np.uint8)
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, -1)
        if self.is_training:
            box = _sample_distorted_box(arr.shape[0], arr.shape[1], rng)
            if box is None:
                out = _center_crop(arr, self.size, self.interpolation)
            else:
                top, left, ch, cw = box
                out = _resize(arr[top:top + ch, left:left + cw],
                              self.size, self.interpolation)
            if rng.random() < 0.5:
                out = out[:, ::-1]
        else:
            out = _center_crop(arr, self.size, self.interpolation)
        return out.round().clip(0, 255).astype(np.uint8)
