"""Data-config resolution.

Parity with ``/root/reference/dfd/timm/data/config.py:5-101``: layered
defaulting CLI args > model ``default_cfg`` > constants for input_size /
interpolation / mean / std / crop_pct, the ``input_size_v2`` string parse
(:17-21), and the per-model-family mean/std overrides (``get_mean_by_model``
:84-101 — Inception-family models use 0.5 mean/std, DPN uses its own).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from .constants import (DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN,
                        IMAGENET_DEFAULT_STD, IMAGENET_DPN_MEAN,
                        IMAGENET_DPN_STD, IMAGENET_INCEPTION_MEAN,
                        IMAGENET_INCEPTION_STD)

_logger = logging.getLogger(__name__)

__all__ = ["resolve_data_config", "get_mean_by_model", "get_std_by_model"]


def get_mean_by_model(model_name: str):
    model_name = model_name.lower()
    if "dpn" in model_name:
        return IMAGENET_DPN_MEAN
    if "ception" in model_name or ("nasnet" in model_name
                                   and "mnasnet" not in model_name):
        return IMAGENET_INCEPTION_MEAN
    return IMAGENET_DEFAULT_MEAN


def get_std_by_model(model_name: str):
    model_name = model_name.lower()
    if "dpn" in model_name:
        return IMAGENET_DPN_STD
    if "ception" in model_name or ("nasnet" in model_name
                                   and "mnasnet" not in model_name):
        return IMAGENET_INCEPTION_STD
    return IMAGENET_DEFAULT_STD


def resolve_data_config(args: Dict[str, Any],
                        default_cfg: Optional[Dict[str, Any]] = None,
                        model=None, verbose: bool = True) -> Dict[str, Any]:
    """Merge CLI args over model cfg over defaults (reference :5-81).

    ``args`` is a plain dict (e.g. ``TrainConfig.to_dict()``).  Note the
    reference resolves ``input_size`` in (C, H, W) order; that convention is
    kept — convert to NHWC at the batch boundary.
    """
    new_config: Dict[str, Any] = {}
    default_cfg = default_cfg or {}
    if not default_cfg and model is not None and \
            getattr(model, "default_cfg", None):
        default_cfg = model.default_cfg

    in_chans = 3
    if args.get("chans") is not None:
        in_chans = args["chans"]

    input_size = (in_chans, 224, 224)
    if args.get("input_size_v2") is not None:
        v2 = args["input_size_v2"]
        if isinstance(v2, str):
            v2 = tuple(int(i) for i in v2.split(","))
        input_size = tuple(v2)
        assert len(input_size) == 3
        in_chans = input_size[0]
    elif args.get("input_size") is not None:
        assert len(args["input_size"]) == 3
        input_size = tuple(args["input_size"])
        in_chans = input_size[0]
    elif args.get("img_size") is not None:
        input_size = (in_chans, args["img_size"], args["img_size"])
    elif "input_size" in default_cfg:
        input_size = tuple(default_cfg["input_size"])
    new_config["input_size"] = input_size

    new_config["interpolation"] = "bicubic"
    if args.get("interpolation"):
        new_config["interpolation"] = args["interpolation"]
    elif default_cfg.get("interpolation"):
        new_config["interpolation"] = default_cfg["interpolation"]

    new_config["mean"] = IMAGENET_DEFAULT_MEAN
    if "model" in args:
        new_config["mean"] = get_mean_by_model(args["model"])
    if args.get("mean") is not None:
        mean = tuple(args["mean"])
        if len(mean) == 1:
            mean = mean * in_chans
        new_config["mean"] = mean
    elif "mean" in default_cfg and "model" not in args:
        new_config["mean"] = default_cfg["mean"]

    new_config["std"] = IMAGENET_DEFAULT_STD
    if "model" in args:
        new_config["std"] = get_std_by_model(args["model"])
    if args.get("std") is not None:
        std = tuple(args["std"])
        if len(std) == 1:
            std = std * in_chans
        new_config["std"] = std
    elif "std" in default_cfg and "model" not in args:
        new_config["std"] = default_cfg["std"]

    new_config["crop_pct"] = DEFAULT_CROP_PCT
    if args.get("crop_pct") is not None:
        new_config["crop_pct"] = args["crop_pct"]
    elif default_cfg.get("crop_pct"):
        new_config["crop_pct"] = default_cfg["crop_pct"]

    # packed pre-decoded cache (data/packed.py): the dir replaces the JPEG
    # decode stage; pack_image_size (0/None = accept the pack's stored
    # resolution) is the loud-mismatch assertion, never a resize knob
    new_config["pack_dir"] = args.get("data_packed") or None
    new_config["pack_image_size"] = int(args.get("pack_image_size") or 0) \
        or None

    if verbose:
        _logger.info("Data processing configuration:")
        for n, v in new_config.items():
            _logger.info("\t%s: %s", n, v)
    return new_config
