"""Process-parallel host loader: a shared-memory ring of batch slabs.

The thread-pool :class:`~deepfake_detection_tpu.data.loader.HostLoader`
parallelizes decode inside ONE process — fine while every hot stage releases
the GIL, but the Python glue between stages (PIL objects, numpy views, the
collate ``np.stack``) serializes, and its share of the clip budget caps
scaling well below the core count.  This module is the torch-DataLoader
equivalent for the TPU port: N *spawned* worker processes (no GIL sharing,
no fork-inherited thread pools) decode + transform samples and write the
resulting uint8 NHWC bytes **directly into a preallocated
``multiprocessing.shared_memory`` ring of batch slabs** at their sample's
slot offset — collate is zero-copy, the batch simply *appears* in the slab
as its last worker finishes, and the consumer hands the slab view straight
to ``jax.device_put`` (no pickle IPC of image bytes anywhere).

Determinism: a sample's content is a pure function of ``(seed, epoch,
index)`` — workers derive the identical per-sample RNG the thread loader
uses, so ``thread`` and ``shm`` backends produce bit-identical batches for
any worker count (tested in ``tests/test_shm_loader.py``).  That purity is
also what makes crash recovery trivial: re-executing a lost task rewrites
the same bytes, so recovery is idempotent by construction.

Robustness:

* **Backpressure** — at most ``ring_depth`` batches are ever in flight; the
  task queue is bounded by ``ring_depth * batch_size`` sample tasks and a
  slab slot is only re-dispatched after the consumer has moved two batches
  past it (see the reuse contract below).
* **Worker crashes** — each worker publishes its current ``(batch, slot)``
  task in a shared cell before touching the sample; the consumer polls
  ``exitcode`` while collecting, respawns dead workers, and re-dispatches
  exactly the one task a dead worker can have lost.
* **Stalls** — workers heartbeat a shared timestamp per task; a worker that
  is alive but silent past ``heartbeat_timeout`` while holding a task is
  terminated and handled like a crash.
* **Shutdown** — ``close()`` (also wired to a ``weakref.finalize``) stops
  workers, drains queues, and unlinks the shm segment; abandoned iterators
  are quiesced with a generation counter so stale tasks can never write
  into a recycled slab.

Slab-reuse contract: a yielded image batch is a **view into the ring** and
stays valid until TWO further batches have been requested from the
iterator.  ``DeviceLoader`` enforces this by blocking on the previous
batch's prologue output before pulling the batch that would recycle the
slot (jax CPU ``device_put`` zero-copies aligned host buffers, so this is
load-bearing, not just belt-and-braces).  Consumers that hold host batches
longer must copy.  Targets and valid masks are tiny and always copied.

No jax imports here: spawned workers import only numpy + the dataset's own
dependencies (PIL, the ctypes native decoder), keeping worker startup and
memory footprint small.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import queue as queue_mod
import time
import weakref
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .samplers import epoch_batches

_logger = logging.getLogger(__name__)

__all__ = ["ShmRing", "ShmRingLoader"]


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment WITHOUT registering it with the
    resource tracker: attachers registering the creator's segment makes
    the (process-tree-shared) tracker unlink it when any worker exits
    (bpo-38119), yanking the ring out from under the survivors.  Python
    3.13 grew ``track=False`` for exactly this; on older interpreters the
    registration hook is swapped out for the duration of the attach
    (single-threaded worker startup, so the swap cannot race)."""
    try:
        return shared_memory.SharedMemory(name=name, track=False)
    except TypeError:  # pre-3.13: no track kwarg
        pass
    from multiprocessing import resource_tracker
    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


class ShmRing:
    """``depth`` batch slabs in one shared-memory segment.

    Layout: ``(depth, rows, H, W, C)`` uint8 image slabs followed (64-byte
    aligned) by ``(depth, batch)`` int64 target slabs.  ``rows`` is
    ``batch * num_splits`` — AugMix multi-view samples land split-major,
    exactly the layout ``fast_collate`` produces on the thread path.
    """

    def __init__(self, depth: int, rows: int, img_shape: Sequence[int],
                 batch: int, name: Optional[str] = None,
                 create: bool = False):
        self.depth = int(depth)
        self.rows = int(rows)
        self.img_shape = tuple(int(d) for d in img_shape)
        self.batch = int(batch)
        img_bytes = self.depth * self.rows * int(np.prod(self.img_shape))
        self._tgt_off = -(-img_bytes // 64) * 64
        total = self._tgt_off + self.depth * self.batch * 8
        if create:
            self.shm = shared_memory.SharedMemory(create=True, size=total)
        else:
            self.shm = _attach_untracked(name)
        self.images = np.ndarray((self.depth, self.rows) + self.img_shape,
                                 np.uint8, buffer=self.shm.buf)
        self.targets = np.ndarray((self.depth, self.batch), np.int64,
                                  buffer=self.shm.buf, offset=self._tgt_off)

    @property
    def name(self) -> str:
        return self.shm.name

    def close(self, unlink: bool = False) -> None:
        self.images = None
        self.targets = None
        try:
            self.shm.close()
        except BufferError:
            # a consumer still holds a yielded slab view; the mapping is
            # freed when the last view dies / the process exits — unlink
            # below still removes the name so nothing leaks system-wide
            pass
        if unlink:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------

def _owner_token(gen: int, bi: int) -> int:
    """One int64 identifying which (iteration, batch) owns a ring slot."""
    return (int(gen) << 32) | (int(bi) & 0xFFFFFFFF)


def _worker_main(wid: int, dataset: Any, seed: int, shm_name: str,
                 depth: int, rows: int, img_shape: Tuple[int, ...],
                 batch: int, task_q, done_q, stop_ev, hb, cur, gen, owner,
                 native_threads: int) -> None:
    """One decode worker: pull ``(slot, j, index, epoch, bi, gen)`` sample
    tasks, write the transformed uint8 sample at its slot offset, ack on
    ``done_q``.  Errors are reported per-sample, not fatal — the consumer
    decides.  Protocol order matters for crash recovery: the current-task
    cell is set BEFORE any work and cleared only AFTER the done ack, so the
    consumer can always reconstruct what a dead worker may have lost.
    Before touching a slab the worker verifies it still OWNS the slot
    (``owner[slot]`` carries the (gen, bi) token the consumer wrote at
    dispatch): a stale task — from an abandoned iteration, or a duplicate
    from a lost-ack re-dispatch executed after its batch completed — must
    never write into a recycled slab."""
    try:
        from . import native as _native
        _native.set_default_pool_threads(native_threads)
    except Exception:  # pragma: no cover - native module is optional
        pass
    chaos = None
    if wid == 0 and os.environ.get("DFD_CHAOS"):
        # env-gated fault injection (worker 0 only, deterministic): die
        # after the Nth completed task so the consumer's crash-recovery
        # path (respawn + re-dispatch) is driven by a REAL dead process
        from ..chaos import chaos_from_env
        chaos = chaos_from_env()
        if "kill_shm_worker" not in chaos.points:
            chaos = None
    tasks_done = 0
    ring = ShmRing(depth, rows, img_shape, batch, name=shm_name)
    base = 3 * wid
    last_epoch: Optional[int] = None
    try:
        while True:
            try:
                task = task_q.get(timeout=0.5)
            except queue_mod.Empty:
                hb[wid] = time.monotonic()
                if stop_ev.is_set():
                    break
                continue
            if task is None:
                break
            if chaos is not None and chaos.fires("kill_shm_worker",
                                                 tasks_done):
                os._exit(113)       # hard death: no ack, no cleanup
            tasks_done += 1
            slot, j, index, epoch, bi, task_gen = task
            cur[base + 1] = bi
            cur[base + 2] = j
            cur[base] = 1
            hb[wid] = time.monotonic()
            token = _owner_token(task_gen, bi)
            if task_gen != gen.value or owner[slot] != token:
                cur[base] = 0
                continue
            err = None
            try:
                if epoch != last_epoch:
                    if hasattr(dataset, "set_epoch"):
                        dataset.set_epoch(epoch)
                    last_epoch = epoch
                rng = np.random.default_rng(
                    np.random.SeedSequence([seed, epoch, int(index)]))
                img, target = dataset.__getitem__(int(index), rng=rng)
                arr = np.asarray(img, dtype=np.uint8)
                if owner[slot] == token:
                    # authoritative pre-write check: the slot may have been
                    # recycled while this (stale/duplicate) task decoded
                    if arr.ndim == 4:    # (S, H, W, C) AugMix views →
                        for s in range(arr.shape[0]):   # split-major rows
                            ring.images[slot, s * batch + j] = arr[s]
                    else:
                        ring.images[slot, j] = arr
                    ring.targets[slot, j] = int(target)
            except Exception as e:      # report, keep serving; interrupts
                err = f"{type(e).__name__}: {e}"   # (Ctrl-C → SIGINT to the
                # process group) must NOT become a per-sample error that
                # beats the consumer's own KeyboardInterrupt to the punch —
                # they propagate, the worker dies, crash handling applies
            done_q.put((task_gen, bi, j, err))
            cur[base] = 0
    finally:
        ring.close()


# ---------------------------------------------------------------------------
# Consumer
# ---------------------------------------------------------------------------

def _shutdown(stop_ev, workers: List, task_q, done_q,
              ring: Optional[ShmRing]) -> None:
    """Idempotent teardown shared by close() and the weakref finalizer.
    Must not reference the loader object (finalizer callback)."""
    try:
        stop_ev.set()
    except Exception:
        pass
    for p in workers:
        try:
            task_q.put_nowait(None)
        except Exception:
            break
    deadline = time.monotonic() + 5.0
    for p in workers:
        p.join(timeout=max(0.1, deadline - time.monotonic()))
    for p in workers:
        if p.is_alive():
            p.terminate()
            p.join(timeout=2.0)
    for q in (task_q, done_q):
        try:
            q.cancel_join_thread()
            q.close()
        except Exception:
            pass
    if ring is not None:
        ring.close(unlink=True)


class ShmRingLoader:
    """Drop-in replacement for :class:`HostLoader` backed by worker
    *processes* and a shared-memory slab ring (module docstring has the
    full design).  Same contract: yields ``(images_uint8, targets)``
    numpy batches (plus a valid mask for masked eval), every batch a pure
    function of ``(seed, epoch, batch_index)``.
    """

    def __init__(self, dataset, sampler, batch_size: int, seed: int = 42,
                 num_workers: int = 4, ring_depth: int = 4,
                 collate_mixup: Optional[Any] = None,
                 valid_mask: bool = False,
                 heartbeat_timeout: float = 120.0):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = batch_size
        self.seed = seed
        self.num_workers = max(1, int(num_workers))
        self.ring_depth = max(3, int(ring_depth))
        self.collate_mixup = collate_mixup
        self.valid_mask = valid_mask
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.epoch = 0
        # mid-epoch resume: first yielded batch of the next iteration
        # (absolute indices are kept for slot tokens and per-batch RNG);
        # reset by set_epoch — see HostLoader.start_batch
        self.start_batch = 0
        self.respawn_count = 0          # lifetime total: observability/tests
        self._iter_respawns = 0         # windowed: crash-loop abort guard
        self._slow_tasks: Set[Tuple[int, int]] = set()  # kill-once ledger
        # telemetry counters (obs/telemetry.py loader_collector): lifetime
        # totals, single-writer (the consumer thread), torn-proof reads
        self.stall_sweeps = 0           # lost-ack re-dispatch sweeps fired
        self.collect_wait_s = 0.0       # consumer blocked waiting on a batch
        self.inflight_batches = 0       # dispatched, not yet yielded (ring
        # occupancy = inflight_batches / ring_depth)

        self._ctx = mp.get_context("spawn")
        self._ring: Optional[ShmRing] = None
        self._workers: List[Any] = []
        self._finalizer: Optional[weakref.finalize] = None
        self._dirty = False             # iterator abandoned mid-epoch
        self._splits = 1
        self._img_shape: Tuple[int, ...] = ()
        self._rows = 0

    # -- HostLoader interface parity ------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.start_batch = 0
        self.sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.sampler) // self.batch_size

    # -- lifecycle ------------------------------------------------------
    def _ensure_started(self) -> None:
        if self._ring is not None:
            return
        probe_index = next(iter(self.sampler), None)
        if probe_index is None:
            raise ValueError("sampler yields no indices")
        # one probe decode in the parent fixes the slab geometry; workers
        # recompute the sample, so the probe costs one clip, not parity
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, self.epoch, int(probe_index)]))
        img, _ = self.dataset.__getitem__(int(probe_index), rng=rng)
        arr = np.asarray(img, dtype=np.uint8)
        if arr.ndim == 4:
            self._splits, self._img_shape = int(arr.shape[0]), arr.shape[1:]
        elif arr.ndim == 3:
            self._splits, self._img_shape = 1, arr.shape
        else:
            raise ValueError(f"sample must be (H, W, C) or (S, H, W, C), "
                             f"got shape {arr.shape}")
        self._rows = self._splits * self.batch_size
        self._ring = ShmRing(self.ring_depth, self._rows, self._img_shape,
                             self.batch_size, create=True)
        self._task_q = self._ctx.Queue()
        self._done_q = self._ctx.Queue()
        self._stop = self._ctx.Event()
        self._hb = self._ctx.Array("d", self.num_workers, lock=False)
        self._cur = self._ctx.Array("q", 3 * self.num_workers, lock=False)
        self._gen = self._ctx.Value("q", 0, lock=False)
        self._owner = self._ctx.Array("q", self.ring_depth, lock=False)
        # each worker's in-process native decode pool gets a slice of the
        # cores — N workers x 4 default threads would oversubscribe
        self._native_threads = max(
            1, min(4, (os.cpu_count() or 1) // self.num_workers))
        self._workers = [None] * self.num_workers
        for i in range(self.num_workers):
            self._spawn(i)
        self._finalizer = weakref.finalize(
            self, _shutdown, self._stop, self._workers, self._task_q,
            self._done_q, self._ring)

    def _spawn(self, i: int) -> None:
        self._hb[i] = time.monotonic()
        self._cur[3 * i] = 0
        p = self._ctx.Process(
            target=_worker_main,
            args=(i, self.dataset, self.seed, self._ring.name,
                  self.ring_depth, self._rows, self._img_shape,
                  self.batch_size, self._task_q, self._done_q, self._stop,
                  self._hb, self._cur, self._gen, self._owner,
                  self._native_threads),
            daemon=True, name=f"dfd-shm-worker-{i}")
        # chaos worker-kill is a TRANSIENT fault: the replacement worker
        # must not inherit the spec and die again in a loop (spawn-context
        # children snapshot os.environ at start)
        chaos_env = os.environ.pop("DFD_CHAOS", None) \
            if self.respawn_count else None
        try:
            p.start()
        finally:
            if chaos_env is not None:
                os.environ["DFD_CHAOS"] = chaos_env
        self._workers[i] = p

    def close(self) -> None:
        """Stop workers, drain queues, unlink the shm segment.  Safe to
        call twice; also runs via weakref.finalize on GC/interpreter exit."""
        if self._finalizer is not None:
            self._finalizer()
        self._ring = None
        self._workers = []

    # -- iteration ------------------------------------------------------
    def _quiesce(self) -> None:
        """After an abandoned iteration: invalidate outstanding tasks (gen
        bump), drain them, and wait for in-flight writes to land so no
        stale worker can touch a slab the new epoch re-dispatches."""
        self._gen.value += 1
        while True:
            try:
                self._task_q.get_nowait()
            except (queue_mod.Empty, OSError):
                break
        deadline = time.monotonic() + 30.0
        while any(self._cur[3 * i] for i in range(self.num_workers)):
            for i, p in enumerate(self._workers):
                if p.exitcode is not None and self._cur[3 * i]:
                    self._cur[3 * i] = 0      # dead: can't clear its flag
                    self.respawn_count += 1
                    self._spawn(i)
            if time.monotonic() > deadline:
                # a straggler stuck in __getitem__ on a stale task that
                # already passed its gen check would eventually write into
                # a slab the next epoch re-dispatches — kill it rather
                # than risk a silent corrupt batch
                for i, p in enumerate(self._workers):
                    if self._cur[3 * i]:
                        _logger.warning("shm worker %d still busy after "
                                        "quiesce deadline; terminating", i)
                        p.terminate()
                        p.join(timeout=5.0)
                        self.respawn_count += 1
                        self._spawn(i)
                break
            time.sleep(0.01)
        while True:
            try:
                self._done_q.get_nowait()
            except (queue_mod.Empty, OSError):
                break
        self._dirty = False

    def _check_workers(self, done: Dict[int, Set[int]],
                       batches: List[List[int]], epoch: int,
                       gen: int) -> None:
        now = time.monotonic()
        for i in range(self.num_workers):
            p = self._workers[i]
            dead = p.exitcode is not None
            base = 3 * i
            if not dead and self._cur[base] and \
                    now - self._hb[i] > self.heartbeat_timeout:
                tkey = (int(self._cur[base + 1]), int(self._cur[base + 2]))
                if tkey in self._slow_tasks:
                    # this exact task already stalled a worker once: the
                    # sample is deterministic, so a re-kill loop would
                    # abort healthy-but-slow data (cold storage, a huge
                    # clip) — let the re-execution run to completion
                    continue
                self._slow_tasks.add(tkey)
                _logger.warning(
                    "shm worker %d silent for %.0fs on a task; killing",
                    i, now - self._hb[i])
                p.terminate()
                p.join(timeout=5.0)
                dead = True
            if not dead:
                continue
            flag, bi, j = (self._cur[base], int(self._cur[base + 1]),
                           int(self._cur[base + 2]))
            self.respawn_count += 1
            self._iter_respawns += 1
            # windowed (reset each epoch): isolated, fully-recovered
            # crashes over a long run must not accumulate into an abort —
            # only an actual crash loop within one epoch should
            if self._iter_respawns > 3 * self.num_workers:
                raise RuntimeError(
                    "shm loader: workers keep dying "
                    f"({self._iter_respawns} respawns this epoch); "
                    "giving up")
            _logger.warning("shm worker %d died (exitcode %s); respawning",
                            i, p.exitcode)
            self._spawn(i)
            # the dead worker held at most ONE task; everything else is
            # still queued or already acked.  Re-dispatch it unless its
            # ack made it out before the crash.  Deterministic samples
            # make a duplicate execution write identical bytes.
            if flag and bi in done and j < len(batches[bi]) \
                    and j not in done[bi]:
                self._task_q.put((bi % self.ring_depth, j,
                                  int(batches[bi][j]), epoch, bi, gen))

    def _collect(self, bi: int, done: Dict[int, Set[int]],
                 batches: List[List[int]], epoch: int, gen: int) -> None:
        need = len(batches[bi])
        t_enter = time.monotonic()
        last_progress = t_enter
        sweeps = 0
        while len(done.get(bi, ())) < need:
            try:
                g, dbi, j, err = self._done_q.get(timeout=0.2)
            except queue_mod.Empty:
                self._check_workers(done, batches, epoch, gen)
                # lost-ack net: a worker that died between completing a
                # sample and its ack actually reaching the pipe (the ack
                # rides the dying process's queue feeder thread) leaves
                # done[bi] short with nothing in flight.  When the batch
                # stalls, re-dispatch its unacked samples that no live
                # worker is holding — duplicates are harmless (the worker-
                # side owner check blocks any late write into a recycled
                # slab, and identical bytes land when the slot is current).
                now = time.monotonic()
                if now - last_progress > max(5.0, self.heartbeat_timeout / 8):
                    sweeps += 1
                    self.stall_sweeps += 1
                    if sweeps > 20:
                        raise RuntimeError(
                            f"shm loader: batch {bi} stalled "
                            f"({len(done.get(bi, ()))}/{need} samples after "
                            f"{sweeps} re-dispatch sweeps)")
                    busy = {(int(self._cur[3 * i + 1]),
                             int(self._cur[3 * i + 2]))
                            for i in range(self.num_workers)
                            if self._cur[3 * i]}
                    for j2 in range(need):
                        if j2 not in done.get(bi, ()) and \
                                (bi, j2) not in busy:
                            self._task_q.put(
                                (bi % self.ring_depth, j2,
                                 int(batches[bi][j2]), epoch, bi, gen))
                    last_progress = now
                continue
            last_progress = time.monotonic()
            if g != gen:
                continue
            if err is not None:
                raise RuntimeError(
                    f"shm worker failed on sample {j} of batch {dbi}: {err}")
            done.setdefault(dbi, set()).add(j)
        self.collect_wait_s += time.monotonic() - t_enter

    def __iter__(self):
        batches, vms = epoch_batches(self.sampler, self.batch_size,
                                     self.valid_mask)
        start = self.start_batch
        if not batches or start >= len(batches):
            return
        self._ensure_started()
        if self._dirty:
            self._quiesce()
        self._gen.value += 1
        gen = int(self._gen.value)
        self._dirty = True
        self._iter_respawns = 0
        self._slow_tasks.clear()
        epoch = self.epoch
        D = self.ring_depth
        nb = len(batches)
        done: Dict[int, Set[int]] = {}

        def dispatch(bi: int) -> None:
            done.setdefault(bi, set())
            slot = bi % D
            # recycling gate: a worker can still be mid-write on this slot
            # under its PREVIOUS batch (a stale duplicate from a lost-ack
            # sweep, or an ack processed before the worker cleared its
            # cell).  Waiting for those published tasks to finish makes
            # the owner re-claim mutually exclusive with in-flight writes;
            # the worker-side pre-write token check covers the residual
            # window of a claim that has not published its cell yet.
            deadline = time.monotonic() + 10.0
            while any(self._cur[3 * i]
                      and int(self._cur[3 * i + 1]) != bi
                      and int(self._cur[3 * i + 1]) % D == slot
                      for i in range(self.num_workers)):
                if time.monotonic() > deadline:
                    _logger.warning("slot %d recycle gate timed out", slot)
                    break
                time.sleep(0.002)
            # claim the slot for (gen, bi) BEFORE its tasks exist: workers
            # verify this token right before any slab write
            self._owner[slot] = _owner_token(gen, bi)
            for j, idx in enumerate(batches[bi]):
                self._task_q.put((slot, j, int(idx), epoch, bi, gen))
            self.inflight_batches = len(done)

        for bi in range(start, min(start + D, nb)):
            dispatch(bi)
        for bi in range(start, nb):
            # slot of batch bi-2 is free by contract (the caller has
            # requested two batches past it) → refill the ring
            if bi >= start + 2 and bi - 2 + D < nb:
                dispatch(bi - 2 + D)
            self._collect(bi, done, batches, epoch, gen)
            images = self._ring.images[bi % D]
            targets = self._ring.targets[bi % D].copy()
            if self._splits > 1:
                targets = np.tile(targets, self._splits)
            if self.collate_mixup is not None:
                mrng = np.random.default_rng(np.random.SeedSequence(
                    [self.seed, epoch, bi, 0x77]))
                images, targets = self.collate_mixup(images, targets, mrng)
            done.pop(bi, None)
            self.inflight_batches = len(done)
            if vms is not None:
                yield images, targets, np.asarray(vms[bi])
            else:
                yield images, targets
        self._dirty = False
