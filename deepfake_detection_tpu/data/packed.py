"""Packed pre-decoded dataset cache: mmap-backed clips, no JPEG decode.

INPUT_BENCH.md prices the flagship input pipeline at ≈23 host cores per
chip of decode+augment demand — and every epoch re-decodes every JPEG
from scratch.  This module is the steady-state half of the fix (the
one-time half is ``tools/pack_dataset.py``): a pack directory holds the
dataset's clips **decoded once** to a canonical pre-augment resolution
and written as fixed-stride ``(H, W, 3·frames)`` uint8 samples in sharded
files — exactly the channel-packed layout ``MultiConcate`` produces — plus
a JSON index carrying shape/dtype/label/clip-id per sample, a per-shard
sha256, and a staleness fingerprint (source lists + pack resolution +
interpolation).  :class:`PackedDataset` then serves clips as zero-copy
``np.frombuffer`` views over the mmapped shards (FFCV's packed-record
idea, tf.data's snapshot stage), turning a CPU-bound decode problem into
a sequential-read bandwidth problem.

Drop-in contract: ``PackedDataset`` subclasses ``DeepFakeClipDataset`` and
overrides only the clip *source* (index-file lists instead of
``real_list.txt``/``fake_list.txt``, mmap lookup instead of JPEG decode),
so the seeded train/val split, fake-bucket rotation, ``set_epoch``,
``noise_fake`` and the absolute ``(seed, epoch, index)`` RNG stream are
the inherited code paths — batches are **bit-identical** to the decode
backend whenever the source frames are at the pack resolution (the packer
skips its resample then; tests/test_packed_data.py locks this across
epochs, worker counts and both thread/shm transports).

Failure modes are loud, never silent skew:

* :class:`PackedCacheStale` — the source lists changed since the pack was
  built, or the requested resolution / frame count / root layout doesn't
  match the index.
* :class:`PackedShardCorrupt` — a shard file is truncated (size checked at
  construction AND at mmap time) or fails its checksum (``verify=True`` /
  :func:`verify_pack`), identified by shard file and sample range.

No jax imports here (PR 1's worker-import discipline): spawned shm-ring
workers unpickle a ``PackedDataset`` and reopen the mmaps lazily in their
own process, importing only numpy/PIL/this package.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import (DeepFakeClipDataset, _load_images, clip_frame_paths,
                      read_clip_list)
from .transforms import PackedFrames, pil_interp

__all__ = ["PackedDataset", "PackedShardCorrupt", "PackedCacheStale",
           "PACK_INDEX", "PACK_PARTIAL", "canonical_clip_array",
           "clip_records", "load_index", "open_shard_array",
           "pack_fingerprint", "read_source_lists", "verify_pack",
           "write_pack"]

PACK_INDEX = "index.json"
PACK_PARTIAL = "index.partial.json"
PACK_VERSION = 1

_REQUIRED_KEYS = ("version", "frames_per_clip", "sample_hw", "interpolation",
                  "roots", "lists", "fingerprint", "shards", "clips")


class PackedShardCorrupt(RuntimeError):
    """A packed shard's bytes don't match its index entry — truncated
    mmap or checksum mismatch.  The message names the shard file and the
    global sample range it holds (the ``CheckpointCorrupt`` contract of
    train/checkpoint.py, applied to data shards)."""


class PackedCacheStale(RuntimeError):
    """The pack disagrees with the source lists or the requested pack
    parameters (resolution / frames per clip / roots).  Re-run
    ``tools/pack_dataset.py`` rather than training on skewed data."""


# ---------------------------------------------------------------------------
# Shared pack arithmetic (packer + reader + validators)
# ---------------------------------------------------------------------------

def read_source_lists(roots: Sequence[str]) -> List[Dict[str, list]]:
    """Each root's ``real``/``fake`` lists parsed to the JSON shape the
    index stores: ``[{"real": [[name, num], ...], "fake": [...]}, ...]``,
    in list-file order (the seeded split downstream is order-sensitive)."""
    out = []
    for ri, root in enumerate(roots):
        out.append({kind: [[name, int(num)] for name, num, _ in
                           read_clip_list(os.path.join(
                               root, f"{kind}_list.txt"), ri)]
                    for kind in ("real", "fake")})
    return out


def pack_fingerprint(lists: List[Dict[str, list]],
                     image_size: Optional[int], interpolation: str,
                     frames_per_clip: int) -> str:
    """Staleness fingerprint: source-list content + pack resolution +
    interpolation + frame count.  Any drift in these means the packed
    bytes no longer reproduce the decode path."""
    payload = json.dumps(
        {"lists": lists, "image_size": image_size or None,
         "interpolation": interpolation, "frames_per_clip": frames_per_clip},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def canonical_clip_array(imgs, image_size: Optional[int],
                         interpolation: str = "bilinear") -> np.ndarray:
    """Decoded PIL frames → ONE ``(H, W, 3·k)`` channel-packed uint8 clip
    at the canonical pre-augment resolution.  Frames already at the target
    size are NOT resampled — the condition under which packed batches are
    bit-identical to the decode path."""
    interp = pil_interp(interpolation)
    arrs = []
    for im in imgs:
        if image_size and im.size != (image_size, image_size):
            im = im.resize((image_size, image_size), interp)
        a = np.asarray(im, dtype=np.uint8)
        if a.ndim < 3:
            a = np.expand_dims(a, axis=-1)
        arrs.append(a)
    return np.concatenate(arrs, axis=-1)


def _sample_stride(index: Dict[str, Any]) -> int:
    h, w = index["sample_hw"]
    return int(h) * int(w) * 3 * int(index["frames_per_clip"])


def _atomic_json(path: str, obj: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_index(pack_dir: str) -> Dict[str, Any]:
    """Read + structurally validate a pack index; loud on anything off."""
    path = os.path.join(pack_dir, PACK_INDEX)
    if not os.path.isfile(path):
        if os.path.isfile(os.path.join(pack_dir, PACK_PARTIAL)):
            raise PackedCacheStale(
                f"{pack_dir}: pack is incomplete (only {PACK_PARTIAL} "
                f"present) — re-run tools/pack_dataset.py to finish it")
        raise FileNotFoundError(
            f"{os.path.join(pack_dir, PACK_INDEX)}: no pack index "
            f"(build one with tools/pack_dataset.py)")
    try:
        with open(path) as f:
            index = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise PackedCacheStale(f"{path}: unreadable pack index ({e})")
    missing = [k for k in _REQUIRED_KEYS if k not in index]
    if missing or int(index.get("version", -1)) != PACK_VERSION:
        raise PackedCacheStale(
            f"{path}: pack index version/schema mismatch "
            f"(version {index.get('version')!r}, missing keys {missing}) — "
            f"re-pack with this build's tools/pack_dataset.py")
    if sum(int(s["num_samples"]) for s in index["shards"]) != \
            len(index["clips"]):
        raise PackedCacheStale(
            f"{path}: shard sample counts disagree with the clip table")
    return index


def _shard_size_problems(pack_dir: str, index: Dict[str, Any],
                         checksums: bool = False) -> List[str]:
    """The one shard audit every consumer shares (reader constructor,
    offline verify, packer resume): size per shard, optionally sha256,
    each problem naming the shard file and its global sample range."""
    problems = []
    stride = _sample_stride(index)
    start = 0
    for sh in index["shards"]:
        path = os.path.join(pack_dir, sh["file"])
        n = int(sh["num_samples"])
        want = n * stride
        rng_txt = f"samples [{start}, {start + n})"
        try:
            got = os.path.getsize(path)
        except OSError:
            problems.append(f"{path}: shard file missing ({rng_txt})")
            start += n
            continue
        if got != want:
            problems.append(f"{path}: {got} bytes, expected {want} "
                            f"({rng_txt})")
        elif checksums:
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for block in iter(lambda: f.read(1 << 20), b""):
                    h.update(block)
            if h.hexdigest() != sh["sha256"]:
                problems.append(f"{path}: checksum mismatch ({rng_txt})")
        start += n
    return problems


def verify_pack(pack_dir: str, checksums: bool = True) -> List[str]:
    """Full offline audit: index schema, shard sizes and (optionally)
    shard checksums.  Returns human-readable problem strings (empty =
    clean); used by ``tools/make_lists.py --validate --packed`` and the
    packer's ``--verify``."""
    try:
        index = load_index(pack_dir)
    except (FileNotFoundError, PackedCacheStale) as e:
        return [str(e)]
    return _shard_size_problems(pack_dir, index, checksums=checksums)


def clip_records(index: Dict[str, Any]
                 ) -> Dict[Tuple[str, int, str], Tuple[int, int]]:
    """``(kind, root_index, name) → (shard_index, slot)`` for every
    packed sample, in index order — the sample lookup every pack reader
    shares (:class:`PackedDataset` and the backfill ``PackSource``)."""
    records: Dict[Tuple[str, int, str], Tuple[int, int]] = {}
    pos = 0
    for si, sh in enumerate(index["shards"]):
        for slot in range(int(sh["num_samples"])):
            kind, ri, name = index["clips"][pos][:3]
            records[(kind, int(ri), name)] = (si, slot)
            pos += 1
    return records


def open_shard_array(pack_dir: str, index: Dict[str, Any],
                     si: int) -> np.ndarray:
    """mmap one shard as a ``(n, H, W, 3·frames)`` uint8 view, with the
    size re-audit at mmap time: a shard truncated AFTER construction-
    time checks must still fail as a named :class:`PackedShardCorrupt`,
    never a bare mmap error mid-corpus."""
    sh = index["shards"][si]
    n_s = int(sh["num_samples"])
    want = n_s * _sample_stride(index)
    path = os.path.join(pack_dir, sh["file"])
    with open(path, "rb") as f:
        got = os.fstat(f.fileno()).st_size
        if got != want:
            raise PackedShardCorrupt(
                f"{path}: {got} bytes at mmap time, "
                f"expected {want} ({n_s} samples)")
        mm = mmap.mmap(f.fileno(), want, access=mmap.ACCESS_READ)
    h, w = (int(v) for v in index["sample_hw"])
    return np.frombuffer(mm, np.uint8, count=want).reshape(
        (n_s, h, w, 3 * int(index["frames_per_clip"])))


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

class PackedDataset(DeepFakeClipDataset):
    """``DeepFakeClipDataset`` whose clip source is a pack directory.

    Same constructor knobs as the decode dataset (split, balance,
    noise_fake, frac/n subsetting) — those run on the *index-recorded*
    lists, which :func:`pack_fingerprint` ties to the source list files —
    plus:

    * ``pack_dir`` — the directory ``tools/pack_dataset.py`` wrote.
    * ``roots`` — optional; when given (the trainer always passes
      ``--data``), the CURRENT list files are re-read and compared against
      the index so a pack that drifted from its source fails loudly.
    * ``image_size`` — optional expected pack resolution
      (``--pack-image-size``); mismatch is a :class:`PackedCacheStale`.
    * ``verify`` — full shard checksum pass at construction (size checks
      always run; checksums cost one sequential read of the pack).

    ``__getitem__`` returns :class:`PackedFrames` views into the mmapped
    shard — zero-copy until the collate — feeding the inherited transform
    chain, so crop/flip/mixup/AugMix and the ``(seed, epoch, index)`` RNG
    stream are untouched.
    """

    def __init__(self, pack_dir: str, roots=None,
                 frames_per_clip: Optional[int] = None,
                 transform=None, train_split: bool = False,
                 train_ratio: float = 0.0, is_training: bool = False,
                 label_balance: bool = False, noise_fake: bool = False,
                 split_seed: int = 0, frac: float = 1.0,
                 n: Optional[int] = None,
                 image_size: Optional[int] = None, verify: bool = False):
        self.pack_dir = os.fspath(pack_dir)
        self.index = load_index(self.pack_dir)
        k = int(self.index["frames_per_clip"])
        if frames_per_clip is not None and int(frames_per_clip) != k:
            raise PackedCacheStale(
                f"{self.pack_dir}: packed at {k} frames/clip, the run "
                f"requests {frames_per_clip} — re-pack with "
                f"--frames {frames_per_clip}")
        hw = [int(v) for v in self.index["sample_hw"]]
        if image_size and [int(image_size)] * 2 != hw:
            raise PackedCacheStale(
                f"{self.pack_dir}: packed at {hw[1]}x{hw[0]}, "
                f"--pack-image-size requests {image_size} — re-pack or "
                f"drop the flag")
        self._lists = self.index["lists"]
        if roots is not None:
            if isinstance(roots, str):
                roots = [r for r in roots.split(":") if r]
            roots = list(roots)
            if len(roots) != len(self._lists):
                raise PackedCacheStale(
                    f"{self.pack_dir}: packed from {len(self._lists)} "
                    f"root(s), the run passes {len(roots)}")
            current = read_source_lists(roots)
            if current != self._lists:
                raise PackedCacheStale(
                    f"{self.pack_dir}: source list files under {roots} "
                    f"changed since the pack was built (fingerprint "
                    f"{self.index['fingerprint'][:12]}…) — re-run "
                    f"tools/pack_dataset.py")
        self._sample_shape = (hw[0], hw[1], 3 * k)
        self._stride = _sample_stride(self.index)
        # sample lookup: (kind, root_index, name) → (shard, slot)
        self._records = clip_records(self.index)
        # shard audit up front: a truncated pack must fail at
        # construction, not yield garbage pixels mid-epoch (checksums
        # cost one sequential read of the pack — opt-in via verify)
        problems = _shard_size_problems(self.pack_dir, self.index,
                                        checksums=verify)
        if problems:
            raise PackedShardCorrupt("; ".join(problems))
        self._mmaps: Dict[int, np.ndarray] = {}
        self._open_lock: Optional[threading.Lock] = threading.Lock()
        super().__init__(
            roots if roots is not None else list(self.index["roots"]),
            frames_per_clip=k, transform=transform, train_split=train_split,
            train_ratio=train_ratio, is_training=is_training,
            label_balance=label_balance, noise_fake=noise_fake,
            split_seed=split_seed, frac=frac, n=n)

    # -- clip-source hooks ---------------------------------------------
    def _read_root_lists(self, root_index: int):
        ls = self._lists[root_index]
        return ([(name, int(num), root_index) for name, num in ls["real"]],
                [(name, int(num), root_index) for name, num in ls["fake"]])

    def _load_clip(self, kind: str, clip: Tuple[str, int, int]):
        name, _num, ri = clip
        rec = self._records.get((kind, int(ri), name))
        if rec is None:
            raise PackedCacheStale(
                f"{self.pack_dir}: clip {kind}/{name} (root {ri}) is not "
                f"in the pack index")
        si, slot = rec
        base = self._shard_arrays(si)[slot]
        k = self.frames_per_clip
        return PackedFrames([base[..., 3 * i:3 * i + 3] for i in range(k)],
                            base)

    # -- mmap management ------------------------------------------------
    def _shard_arrays(self, si: int) -> np.ndarray:
        arr = self._mmaps.get(si)
        if arr is None:
            if self._open_lock is None:            # post-unpickle safety
                self._open_lock = threading.Lock()
            with self._open_lock:
                arr = self._mmaps.get(si)
                if arr is None:
                    arr = open_shard_array(self.pack_dir, self.index, si)
                    self._mmaps[si] = arr
        return arr

    def __getstate__(self):
        # shm-ring workers unpickle the dataset in a spawned process: mmap
        # handles and locks don't cross; each process reopens lazily
        d = dict(self.__dict__)
        d["_mmaps"] = {}
        d["_open_lock"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._open_lock = threading.Lock()

    # -- introspection --------------------------------------------------
    @property
    def packed_hw(self) -> Tuple[int, int]:
        """(H, W) of the stored pre-augment frames."""
        return self._sample_shape[0], self._sample_shape[1]

    def sample_array(self, index: int,
                     epoch: Optional[int] = None) -> np.ndarray:
        """Zero-copy ``(H, W, 3·frames)`` uint8 view of one sample's
        packed bytes (no transform, no RNG)."""
        kind, clip, _ = self.sample_clip(index, epoch)
        return self._load_clip(kind, clip).base


# ---------------------------------------------------------------------------
# Writer (driven by tools/pack_dataset.py; importable for tests/benches)
# ---------------------------------------------------------------------------

def _wipe_pack(out_dir: str) -> None:
    for fn in os.listdir(out_dir):
        if fn in (PACK_INDEX, PACK_PARTIAL) or (
                fn.startswith("shard-") and
                (fn.endswith(".bin") or ".bin.tmp" in fn)):
            try:
                os.remove(os.path.join(out_dir, fn))
            except OSError:
                pass


def write_pack(roots, out_dir: str, image_size: int = 0,
               frames_per_clip: int = 4, interpolation: str = "bilinear",
               shard_size: int = 256, workers: int = 4, max_shards: int = 0,
               force: bool = False, log=None) -> Dict[str, Any]:
    """One-time decode-and-pack pass; resumable at shard granularity.

    Walks every clip of every root's v3 lists in deterministic order
    (root-major, fakes before reals — the dataset's own index-space
    convention), decodes through the same ``_load_images`` path the
    runtime uses (native C++ pool when available), resamples to
    ``image_size``² unless the frame already is that size (``0`` keeps the
    native resolution, which must then be uniform), and streams
    fixed-stride samples into ``shard-NNNNN.bin`` files.  After each shard
    lands (write → fsync → atomic rename) the partial index is rewritten
    atomically, so a killed packer resumes from the first missing shard;
    the final ``index.json`` only appears when every clip is packed.

    ``max_shards`` stops early after N shards (testing/smoke hook).
    Returns the index dict (partial if stopped early).
    """
    from concurrent.futures import ThreadPoolExecutor

    if log is None:
        log = lambda *_: None                                    # noqa: E731
    shard_size = int(shard_size)
    if shard_size < 1:
        # entries[done:done+0] would loop forever writing empty shards
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    if isinstance(roots, str):
        roots = [r for r in roots.split(":") if r]
    roots = [os.fspath(r) for r in roots]
    lists = read_source_lists(roots)
    entries = []
    for ri in range(len(roots)):
        for kind in ("fake", "real"):
            entries += [(kind, ri, name, int(num))
                        for name, num in lists[ri][kind]]
    if not entries:
        raise ValueError(f"no clips listed under roots {roots}")
    image_size = int(image_size or 0)
    fp = pack_fingerprint(lists, image_size or None, interpolation,
                          frames_per_clip)
    os.makedirs(out_dir, exist_ok=True)
    idx_path = os.path.join(out_dir, PACK_INDEX)
    partial_path = os.path.join(out_dir, PACK_PARTIAL)

    if os.path.isfile(idx_path):
        try:
            existing = load_index(out_dir)
        except PackedCacheStale:
            existing = None
        if existing is not None and existing["fingerprint"] == fp \
                and not force:
            log(f"{out_dir}: pack is up to date "
                f"({len(existing['clips'])} clips); nothing to do")
            return existing
        if not force:
            raise PackedCacheStale(
                f"{out_dir} already holds a pack built from different "
                f"sources or parameters — pass force/--force to rebuild")
        _wipe_pack(out_dir)

    state: Optional[Dict[str, Any]] = None
    if os.path.isfile(partial_path):
        try:
            with open(partial_path) as f:
                state = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError):
            state = None
        if state is not None and (state.get("fingerprint") != fp or force):
            if not force:
                raise PackedCacheStale(
                    f"{partial_path}: partial pack was built from "
                    f"different sources or parameters — pass force/--force "
                    f"to restart")
            state = None
        if state is not None and state["shards"] and not state["sample_hw"]:
            # a partial that records shards but no geometry is torn
            if not force:
                raise PackedCacheStale(
                    f"{partial_path}: torn partial index — pass "
                    f"force/--force to restart the pack")
            state = None
        if state is None:
            _wipe_pack(out_dir)
        else:
            # recorded shards landed before their partial-index write; a
            # size mismatch means on-disk damage, not a torn resume point
            problems = _shard_size_problems(out_dir, state)
            if problems:
                raise PackedShardCorrupt(
                    "; ".join(problems) + " — remove the pack dir (or "
                    "pass force/--force) to rebuild")
            log(f"{out_dir}: resuming after "
                f"{sum(int(s['num_samples']) for s in state['shards'])}/"
                f"{len(entries)} packed clips")
    if state is None:
        state = {"version": PACK_VERSION, "frames_per_clip": frames_per_clip,
                 "image_size": image_size or None, "sample_hw": None,
                 "dtype": "uint8", "interpolation": interpolation,
                 "roots": roots, "lists": lists, "fingerprint": fp,
                 "shards": [], "clips": []}

    done = sum(int(s["num_samples"]) for s in state["shards"])

    def _decode(entry):
        kind, ri, name, num = entry
        imgs = _load_images(clip_frame_paths(
            roots, kind, (name, num, ri), frames_per_clip))
        return canonical_clip_array(imgs, image_size, interpolation)

    with ThreadPoolExecutor(max(1, int(workers))) as pool:
        si = len(state["shards"])
        while done < len(entries):
            if max_shards and si >= int(max_shards):
                log(f"{out_dir}: stopping after {si} shards (max-shards); "
                    f"{done}/{len(entries)} clips packed")
                break
            chunk = entries[done:done + int(shard_size)]
            arrs = list(pool.map(_decode, chunk))
            for e, a in zip(chunk, arrs):
                if state["sample_hw"] is None:
                    state["sample_hw"] = [int(a.shape[0]), int(a.shape[1])]
                want = tuple(state["sample_hw"]) + (3 * frames_per_clip,)
                if a.shape != want:
                    raise ValueError(
                        f"clip {e[0]}/{e[2]}: decoded shape {a.shape} != "
                        f"pack stride {want} — sources are mixed-resolution;"
                        f" set --pack-image-size to a fixed size")
            fname = f"shard-{si:05d}.bin"
            tmp = os.path.join(out_dir, f"{fname}.tmp.{os.getpid()}")
            h = hashlib.sha256()
            with open(tmp, "wb") as f:
                for a in arrs:
                    b = np.ascontiguousarray(a).tobytes()
                    h.update(b)
                    f.write(b)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(out_dir, fname))
            state["shards"].append({"file": fname,
                                    "num_samples": len(chunk),
                                    "sha256": h.hexdigest()})
            state["clips"] += [[kind, ri, name, num,
                                0 if kind == "fake" else 1]
                               for kind, ri, name, num in chunk]
            _atomic_json(partial_path, state)
            done += len(chunk)
            si += 1
            log(f"{fname}: {done}/{len(entries)} clips "
                f"({done * _sample_stride(state) / 1e9:.2f} GB)")

    if done >= len(entries):
        state["complete"] = True
        _atomic_json(idx_path, state)
        try:
            os.remove(partial_path)
        except OSError:
            pass
        log(f"{out_dir}: pack complete — {done} clips, "
            f"{len(state['shards'])} shards, "
            f"{done * _sample_stride(state) / 1e9:.2f} GB, "
            f"fingerprint {fp[:12]}…")
    return state
