"""Device-side augmentation (host PIL/native stages moved into the prologue).

The reference applies ColorJitter/Flicker on the host with PIL
(``dfd/timm/data/transforms.py:332-350``) — per-pixel python-driven work
that costs more than the JPEG *decode* at 600² (≈31 ms/clip/core vs ≈8).
On TPU the same math is a handful of fused elementwise ops and two tiny
reductions, effectively free inside the loader's jitted prologue
(loader.py DeviceLoader), so the default train pipeline draws the jitter
parameters on device from the per-step PRNG and leaves the host out of it
entirely (``--host-color-jitter`` restores the reference's host path).

Semantics match PIL's ImageEnhance chain per frame, with one shared draw
per clip (MultiColorJitter):

* brightness: ``x·b``
* saturation (ImageEnhance.Color): ``gray + s·(x - gray)`` with the
  ITU-R 601-2 luma (0.299, 0.587, 0.114)
* contrast: ``m + c·(x - m)`` where ``m`` is the per-frame mean luma
* the three ops apply in a uniformly random order (torchvision semantics
  the reference relies on), each followed by a [0, 255] clamp, like PIL's
  intermediate uint8 quantization (minus the rounding, documented drift)
* flicker: each frame independently blacked out with probability p

Known deltas vs the PIL path, all sub-quantization or explicitly accepted:
no intermediate uint8 rounding between ops, PIL's int-rounded contrast mean
is kept fractional, and the PRNG stream differs (explicit-PRNG design).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["make_device_color_jitter", "DeviceAugmentSpec",
           "derive_geometric_batch", "derive_mixup_lam",
           "make_device_geometric", "make_device_blur", "device_mixup_blend"]

_LUMA = (0.299, 0.587, 0.114)          # PIL convert("L"), ITU-R 601-2


# ---------------------------------------------------------------------------
# Full device-side augmentation (--augment-device on)
#
# The geometric warp, per-frame Gaussian blur, and the mixup blend leave
# the host transform chain and run inside the DeviceLoader's single jitted
# prologue.  Parameters stay keyed by the SAME absolute numpy RNG streams
# the host chain draws from — per-sample ``(seed, epoch, index)`` for
# warp/blur, per-batch ``(seed, epoch, batch_index, 0x77)`` for mixup —
# derived on the consumer side (derive_* below) while the host passthrough
# transform consumes the identical draws for stream-position parity
# (transforms.DeviceAugmentPassthrough).  That keying is what makes PR 3's
# bit-continuous mid-epoch resume and ``fast_forward`` survive unchanged:
# every parameter is a pure function of absolute position, never of
# iteration history.
#
# Numerics, pinned by tests/test_device_augment.py:
#
# * warp — float32 bilinear gather, taps outside the source read 0 (the
#   native kernel's black fill), output rounded to the integer grid like
#   the uint8 host path.  Integer-coefficient affines (flip/crop/pad, the
#   scale==1/rotate==0 case) are BIT-exact vs the host chain; fractional
#   coords differ from the native fixed-point kernel (8-bit weights) by
#   the documented resampling tolerance only.
# * blur — true separable Gaussian (sigma = radius, the documented PIL
#   parameter semantics), clamp-to-edge, 3σ support, rounded.  PIL itself
#   approximates the Gaussian with a 3-pass extended box filter whose
#   fixed-point internals vary across Pillow versions, so parity here is
#   tolerance-based by design (documented in the parity suite).
# * mixup — bit-exact vs FastCollateMixup: each scalar is split into
#   high/low mantissa halves so every product is exactly representable
#   and XLA's fma contraction cannot change the rounded sum
#   (device_mixup_blend below).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceAugmentSpec:
    """Static description of the device-side train augmentation.

    Built by the loader factory; consumed by the DeviceLoader both on the
    host (parameter derivation from the absolute RNG streams) and inside
    the jitted prologue (warp/blur/mixup rendering).  ``mixup_blocks`` is
    the number of process-local sub-batches the mixup flip must respect:
    the host collate mixes within each process's local batch, so the
    device blend flips within the matching global-batch blocks.
    """
    size: Tuple[int, int]                # (th, tw) output crop
    rotate_range: int = 0
    scale: Tuple[float, float] = (2.0 / 3, 3.0 / 2.0)
    p_flip: float = 0.5
    blur_prob: float = 0.0
    blur_radius: float = 1.0
    img_num: int = 4
    mixup: bool = False                  # device-side blend active
    mixup_alpha: float = 0.0
    mixup_blocks: int = 1

    @property
    def host_stages_elided(self) -> int:
        """Host-chain stages this spec moves on device, per sample (the
        telemetry counter's increment): geometric warp, blur, mixup."""
        return 1 + (1 if self.blur_prob > 0.0 else 0) + \
            (1 if self.mixup else 0)


def derive_geometric_batch(spec: DeviceAugmentSpec, indices, seed: int,
                           epoch: int, src_hw: Tuple[int, int]
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """(coeffs (B, 6) f32, blur mask (B, F) bool) for one batch.

    Must draw exactly what the host chain would: one
    ``fused_geometric_params`` + ``blur_mask_draws`` per sample from the
    per-sample ``(seed, epoch, index)`` generator — the same calls the
    host passthrough consumes worker-side, so the two cannot drift.
    """
    from .transforms import blur_mask_draws, fused_geometric_params
    h, w = src_hw
    coeffs = np.empty((len(indices), 6), np.float32)
    blur = np.zeros((len(indices), spec.img_num), bool)
    for i, idx in enumerate(indices):
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, epoch, int(idx)]))
        coeffs[i] = fused_geometric_params(
            w, h, spec.size, spec.rotate_range, spec.scale, spec.p_flip,
            rng)
        if spec.blur_prob > 0.0:
            blur[i] = blur_mask_draws(spec.img_num, spec.blur_prob, rng)
    return coeffs, blur


def derive_mixup_lam(seed: int, epoch: int, batch_index: int, alpha: float,
                     enabled: bool) -> Tuple[np.float32, np.float32]:
    """(lam, 1-lam) from FastCollateMixup's exact per-batch stream.

    The generator seed ``[seed, epoch, batch_index, 0x77]`` and the
    single beta draw are byte-for-byte the host collate's (loader.py /
    shm_ring.py), so the device blend and the host-computed soft targets
    share one lambda.  ``1 - lam`` is formed in float64 BEFORE the f32
    cast, matching numpy's scalar arithmetic in the host blend.
    """
    lam = 1.0
    if enabled:
        rng = np.random.default_rng(np.random.SeedSequence(
            [seed, epoch, batch_index, 0x77]))
        lam = float(rng.beta(alpha, alpha))
    return np.float32(lam), np.float32(1.0 - lam)


def make_device_geometric(spec: DeviceAugmentSpec) -> Callable:
    """``fn(x_uint8 (B, Hs, Ws, 3F), coeffs (B, 6)) -> f32 (B, th, tw, 3F)``.

    One bilinear gather per output pixel — rotate, flip, resize, crop and
    pad_if_needed composed into the index-space affine the host chain
    computes (transforms.fused_geometric_params).  Out-of-bounds taps
    contribute 0 (native kernel black fill); output is rounded onto the
    integer grid like every uint8 host stage.
    """
    th, tw = spec.size
    yy, xx = np.mgrid[0:th, 0:tw].astype(np.float32)

    def one(img, coef):                    # (Hs, Ws, C), (6,)
        hs, ws = img.shape[0], img.shape[1]
        sx = coef[0] * xx + coef[1] * yy + coef[2]
        sy = coef[3] * xx + coef[4] * yy + coef[5]
        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        fx = sx - x0
        fy = sy - y0
        x0i = x0.astype(jnp.int32)
        y0i = y0.astype(jnp.int32)

        def tap(yi, xi):
            inb = (yi >= 0) & (yi < hs) & (xi >= 0) & (xi < ws)
            v = img[jnp.clip(yi, 0, hs - 1),
                    jnp.clip(xi, 0, ws - 1)].astype(jnp.float32)
            return jnp.where(inb[..., None], v, 0.0)

        out = (tap(y0i, x0i) * ((1 - fx) * (1 - fy))[..., None]
               + tap(y0i, x0i + 1) * (fx * (1 - fy))[..., None]
               + tap(y0i + 1, x0i) * ((1 - fx) * fy)[..., None]
               + tap(y0i + 1, x0i + 1) * (fx * fy)[..., None])
        return jnp.round(out)

    return jax.vmap(one)


def _gaussian_taps(radius: float) -> np.ndarray:
    """Normalized 1-D Gaussian taps, sigma = radius (PIL's documented
    parameter semantics), support 3σ."""
    sigma = max(float(radius), 1e-3)
    r = max(1, int(math.ceil(3.0 * sigma)))
    xs = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-(xs * xs) / (2.0 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def make_device_blur(spec: DeviceAugmentSpec) -> Callable:
    """``fn(x f32 (B, H, W, 3F), mask (B, F) bool) -> f32`` — separable
    Gaussian per frame where the per-frame host coin fired, clamp-to-edge
    padding (PIL extends edge pixels), rounded like the uint8 host stage;
    unblurred frames pass through untouched (bit-exactness preserved)."""
    taps = _gaussian_taps(spec.blur_radius)
    r = (len(taps) - 1) // 2
    fr = spec.img_num

    def apply(x, mask):                    # (B, H, W, 3F), (B, F)
        b, h, w, c = x.shape
        xp = jnp.pad(x, ((0, 0), (r, r), (0, 0), (0, 0)), mode="edge")
        blurred = sum(taps[i] * lax.dynamic_slice_in_dim(xp, i, h, axis=1)
                      for i in range(len(taps)))
        xp = jnp.pad(blurred, ((0, 0), (0, 0), (r, r), (0, 0)), mode="edge")
        blurred = sum(taps[i] * lax.dynamic_slice_in_dim(xp, i, w, axis=2)
                      for i in range(len(taps)))
        blurred = jnp.round(blurred)
        sel = jnp.repeat(mask, 3, axis=-1)[:, None, None, :]  # (B,1,1,3F)
        return jnp.where(sel, blurred, x)

    return apply


def _split_f32(c):
    """Split an f32 scalar into (high, low) halves with ≤12-bit mantissas
    each, so products against 8-bit integer-valued pixels are EXACT in
    f32 — which makes XLA's fma contraction value-preserving and the
    blend below bit-identical to numpy's mul-round/add-round sequence."""
    ci = lax.bitcast_convert_type(c, jnp.int32)
    hi = lax.bitcast_convert_type(ci & ~jnp.int32(0xFFF), jnp.float32)
    return hi, c - hi


def device_mixup_blend(x, lam, one_minus_lam, blocks: int = 1):
    """FastCollateMixup's uint8 blend, on device, bit-exact.

    ``x`` is the (B, H, W, C) float batch with integer-valued pixels
    (every upstream device stage rounds onto the uint8 grid); ``blocks``
    partitions the batch into process-local sub-batches so the flip
    matches the host collate's per-process ``images[::-1]`` under
    multi-host sharding.  Returns the rounded blend (still float — the
    prologue normalizes next, exactly where the host path's uint8 batch
    would enter).
    """
    if blocks > 1:
        shp = x.shape
        rev = jnp.flip(x.reshape((blocks, shp[0] // blocks) + shp[1:]),
                       axis=1).reshape(shp)
    else:
        rev = jnp.flip(x, axis=0)
    lh, ll = _split_f32(lam)
    oh, ol = _split_f32(one_minus_lam)
    p1 = x * lh + x * ll                  # == RN(x·lam): exact products
    p2 = rev * oh + rev * ol              # == RN(rev·(1-lam))
    return jnp.round(p1 + p2)


def make_device_color_jitter(color_jitter: Optional[Sequence[float]],
                             flicker: float, img_num: int) -> Optional[
                                 Callable[[jnp.ndarray, jax.Array],
                                          jnp.ndarray]]:
    """Build ``fn(x_uint8f, key) -> x`` over (B, H, W, 3·img_num) in 0..255
    float space, or None when there is nothing to apply."""
    if color_jitter is None and flicker <= 0.0:
        return None
    jb, jc, js = (color_jitter if color_jitter is not None else (0., 0., 0.))

    def one_sample(x, key):                       # (H, W, 3·img_num)
        h, w, _ = x.shape
        fr = x.reshape(h, w, img_num, 3)
        kb, kc, ks, kord, kfl = jax.random.split(key, 5)
        if jb or jc or js:
            b = jax.random.uniform(kb, (), minval=max(0.0, 1 - jb),
                                   maxval=1 + jb)
            c = jax.random.uniform(kc, (), minval=max(0.0, 1 - jc),
                                   maxval=1 + jc)
            s = jax.random.uniform(ks, (), minval=max(0.0, 1 - js),
                                   maxval=1 + js)
            luma = jnp.asarray(_LUMA, fr.dtype)

            def op_brightness(z):
                return z * b

            def op_contrast(z):
                gray = (z * luma).sum(-1)                 # (H, W, F)
                m = gray.mean(axis=(0, 1))                # per-frame mean
                return m[None, None, :, None] + c * (z - m[None, None, :,
                                                           None])

            def op_saturation(z):
                gray = (z * luma).sum(-1, keepdims=True)  # (H, W, F, 1)
                return gray + s * (z - gray)

            ops = [op_brightness, op_contrast, op_saturation]
            order = jax.random.permutation(kord, 3)
            for i in range(3):
                fr = lax.switch(order[i], ops, fr)
                fr = jnp.clip(fr, 0.0, 255.0)   # PIL quantizes between ops
        if flicker > 0.0:
            drop = jax.random.uniform(kfl, (img_num,)) < flicker
            fr = jnp.where(drop[None, None, :, None], 0.0, fr)
        return fr.reshape(h, w, img_num * 3)

    def apply(x, key):                             # (B, H, W, 3·img_num)
        keys = jax.random.split(key, x.shape[0])
        return jax.vmap(one_sample)(x, keys)

    return apply
