"""Device-side color augmentation (the host PIL jitter moved into the step).

The reference applies ColorJitter/Flicker on the host with PIL
(``dfd/timm/data/transforms.py:332-350``) — per-pixel python-driven work
that costs more than the JPEG *decode* at 600² (≈31 ms/clip/core vs ≈8).
On TPU the same math is a handful of fused elementwise ops and two tiny
reductions, effectively free inside the loader's jitted prologue
(loader.py DeviceLoader), so the default train pipeline draws the jitter
parameters on device from the per-step PRNG and leaves the host out of it
entirely (``--host-color-jitter`` restores the reference's host path).

Semantics match PIL's ImageEnhance chain per frame, with one shared draw
per clip (MultiColorJitter):

* brightness: ``x·b``
* saturation (ImageEnhance.Color): ``gray + s·(x - gray)`` with the
  ITU-R 601-2 luma (0.299, 0.587, 0.114)
* contrast: ``m + c·(x - m)`` where ``m`` is the per-frame mean luma
* the three ops apply in a uniformly random order (torchvision semantics
  the reference relies on), each followed by a [0, 255] clamp, like PIL's
  intermediate uint8 quantization (minus the rounding, documented drift)
* flicker: each frame independently blacked out with probability p

Known deltas vs the PIL path, all sub-quantization or explicitly accepted:
no intermediate uint8 rounding between ops, PIL's int-rounded contrast mean
is kept fractional, and the PRNG stream differs (explicit-PRNG design).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["make_device_color_jitter"]

_LUMA = (0.299, 0.587, 0.114)          # PIL convert("L"), ITU-R 601-2


def make_device_color_jitter(color_jitter: Optional[Sequence[float]],
                             flicker: float, img_num: int) -> Optional[
                                 Callable[[jnp.ndarray, jax.Array],
                                          jnp.ndarray]]:
    """Build ``fn(x_uint8f, key) -> x`` over (B, H, W, 3·img_num) in 0..255
    float space, or None when there is nothing to apply."""
    if color_jitter is None and flicker <= 0.0:
        return None
    jb, jc, js = (color_jitter if color_jitter is not None else (0., 0., 0.))

    def one_sample(x, key):                       # (H, W, 3·img_num)
        h, w, _ = x.shape
        fr = x.reshape(h, w, img_num, 3)
        kb, kc, ks, kord, kfl = jax.random.split(key, 5)
        if jb or jc or js:
            b = jax.random.uniform(kb, (), minval=max(0.0, 1 - jb),
                                   maxval=1 + jb)
            c = jax.random.uniform(kc, (), minval=max(0.0, 1 - jc),
                                   maxval=1 + jc)
            s = jax.random.uniform(ks, (), minval=max(0.0, 1 - js),
                                   maxval=1 + js)
            luma = jnp.asarray(_LUMA, fr.dtype)

            def op_brightness(z):
                return z * b

            def op_contrast(z):
                gray = (z * luma).sum(-1)                 # (H, W, F)
                m = gray.mean(axis=(0, 1))                # per-frame mean
                return m[None, None, :, None] + c * (z - m[None, None, :,
                                                           None])

            def op_saturation(z):
                gray = (z * luma).sum(-1, keepdims=True)  # (H, W, F, 1)
                return gray + s * (z - gray)

            ops = [op_brightness, op_contrast, op_saturation]
            order = jax.random.permutation(kord, 3)
            for i in range(3):
                fr = lax.switch(order[i], ops, fr)
                fr = jnp.clip(fr, 0.0, 255.0)   # PIL quantizes between ops
        if flicker > 0.0:
            drop = jax.random.uniform(kfl, (img_num,)) < flicker
            fr = jnp.where(drop[None, None, :, None], 0.0, fr)
        return fr.reshape(h, w, img_num * 3)

    def apply(x, key):                             # (B, H, W, 3·img_num)
        keys = jax.random.split(key, x.shape[0])
        return jax.vmap(one_sample)(x, keys)

    return apply
