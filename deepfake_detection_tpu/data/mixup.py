"""Mixup.

Parity with ``/root/reference/dfd/timm/data/mixup.py``: ``one_hot``/
``mixup_target`` (:5-15), in-loop ``mixup_batch`` (:18-25), and the
collate-time ``FastCollateMixup`` (:27-51) that mixes the uint8 batch with its
reversed self under a single Beta-sampled ``lam`` and emits smoothed soft
targets.

The collate variant stays on host (numpy, uint8 — cheap, overlaps with TPU
compute); the in-loop variant is pure jnp so it can live inside the jitted
train step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["one_hot_np", "mixup_target_np", "FastCollateMixup", "mixup_batch"]


def one_hot_np(x: np.ndarray, num_classes: int, on_value: float = 1.0,
               off_value: float = 0.0) -> np.ndarray:
    out = np.full((len(x), num_classes), off_value, dtype=np.float32)
    out[np.arange(len(x)), x] = on_value
    return out


def mixup_target_np(target: np.ndarray, num_classes: int, lam: float = 1.0,
                    smoothing: float = 0.0) -> np.ndarray:
    """Soft targets: lam * y + (1-lam) * y[::-1], label-smoothed (:10-15)."""
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    y1 = one_hot_np(target, num_classes, on, off)
    y2 = one_hot_np(target[::-1], num_classes, on, off)
    return lam * y1 + (1.0 - lam) * y2


class FastCollateMixup:
    """Collate-time uint8 mixup (:27-51), with an explicit RNG.

    Call with the already-stacked uint8 batch ``(B, H, W, C)`` and int labels;
    returns the mixed uint8 batch and float32 soft targets.

    ``blend=False`` (set by the loader factory under ``--augment-device
    on``) elides the image blend only: lambda is still drawn from the
    identical stream and the soft targets still computed here, while the
    DeviceLoader re-derives the same lambda and blends inside its jitted
    prologue (``data/device_augment.py::device_mixup_blend``, bit-exact
    vs the host blend) — host cost drops to the target math.
    """

    def __init__(self, mixup_alpha: float = 1.0, label_smoothing: float = 0.1,
                 num_classes: int = 1000, blend: bool = True):
        self.mixup_alpha = mixup_alpha
        self.label_smoothing = label_smoothing
        self.num_classes = num_classes
        self.mixup_enabled = True
        self.blend = blend

    def __call__(self, images: np.ndarray, targets: np.ndarray,
                 rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
        lam = 1.0
        if self.mixup_enabled:
            lam = float(rng.beta(self.mixup_alpha, self.mixup_alpha))
        soft = mixup_target_np(targets, self.num_classes, lam,
                               self.label_smoothing)
        if lam == 1.0 or not self.blend:
            return images, soft
        mixed = images.astype(np.float32) * lam + \
            images[::-1].astype(np.float32) * (1.0 - lam)
        np.round(mixed, out=mixed)
        return mixed.astype(np.uint8), soft


def mixup_batch(images: jnp.ndarray, targets: jnp.ndarray, rng: jax.Array,
                alpha: float = 0.2, num_classes: int = 1000,
                smoothing: float = 0.1, disable: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """In-loop device-side mixup (:18-25) — jit-safe.

    ``disable=True`` must be a Python (static) bool; everything else traces.
    """
    if disable:
        lam = jnp.float32(1.0)
    else:
        lam = jax.random.beta(rng, alpha, alpha)
    mixed = images * lam + jnp.flip(images, axis=0) * (1.0 - lam)
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    y1 = jax.nn.one_hot(targets, num_classes) * (on - off) + off
    y2 = jax.nn.one_hot(jnp.flip(targets, axis=0), num_classes) * (on - off) + off
    soft = lam * y1 + (1.0 - lam) * y2
    return mixed, soft
