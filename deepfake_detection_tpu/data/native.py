"""ctypes bridge to the native C++ decode pool (native/dfd_native.cc).

The reference gets its input-pipeline parallelism from torch's C++ DataLoader
worker *processes* (fork + pickle IPC).  The TPU-native equivalent is an
in-process C++ thread pool: ctypes releases the GIL for the duration of each
call, so the 4 frames of a deepfake clip decode concurrently, and libjpeg's
DCT-domain scaling (``scale_denom``) decodes straight to 1/2–1/8 size — work
the decode-then-resize PIL path pays in full.

Everything degrades gracefully: if the shared library is missing it is built
once with g++ (toolchain is in the image); if that fails, callers fall back
to PIL via :func:`available` returning False.  No hard dependency anywhere.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_log = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_SRC_DIR, "dfd_native.cc")
_LIB = os.path.join(_SRC_DIR, "libdfd_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build_library() -> bool:
    # no -march=native: the .so path may be shared between heterogeneous
    # hosts (NFS repo, baked images), and a binary tuned for the builder's
    # CPU would SIGILL elsewhere.  The warp's inner loop is fixed-point
    # integer math, which -O3 handles well without ISA extensions.
    # Build to a temp path + atomic rename so a concurrent first-use build
    # on another host can never dlopen a half-written file.
    tmp = f"{_LIB}.build.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           _SRC, "-o", tmp, "-ljpeg", "-lpthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.warning("native decode build failed to launch: %s", e)
        return False
    if proc.returncode != 0:
        _log.warning("native decode build failed:\n%s", proc.stderr[-2000:])
        return False
    try:
        os.replace(tmp, _LIB)
    except OSError as e:
        _log.warning("native decode build rename failed: %s", e)
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        stale = os.path.exists(_LIB) and os.path.exists(_SRC) and \
            os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        if not os.path.exists(_LIB) or stale:
            if not (os.path.exists(_SRC) and _build_library()) and not stale:
                # no library at all and no way to build one
                _build_failed = True
                return None
            # a failed *re*build of a stale .so falls through: the existing
            # library still loads and is better than the PIL path
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _log.warning("native decode library failed to load: %s", e)
            _build_failed = True
            return None
        try:
            _bind_symbols(lib)
        except AttributeError as e:
            # stale .so from an older source whose rebuild failed: missing
            # symbols must degrade to the PIL path, not crash every decode
            _log.warning("native library is stale and rebuild failed "
                         "(missing symbol: %s); falling back to PIL", e)
            _build_failed = True
            return None
        _lib = lib
        return _lib


_ABI_VERSION = 3           # must match dfd_abi_version() in dfd_native.cc


def _bind_symbols(lib) -> None:
    """Declare ctypes signatures; raises AttributeError on a stale .so
    (missing symbol) and RuntimeError on an ABI mismatch — symbols that
    still resolve but whose argument layout moved would otherwise be
    called with shifted arguments and crash instead of falling back."""
    lib.dfd_abi_version.restype = ctypes.c_int
    got = lib.dfd_abi_version()
    if got != _ABI_VERSION:
        raise AttributeError(f"dfd_native ABI {got} != expected "
                             f"{_ABI_VERSION}")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dfd_decode_jpeg_file.restype = u8p
    lib.dfd_decode_jpeg_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.dfd_decode_jpeg.restype = u8p
    lib.dfd_decode_jpeg.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.dfd_free.argtypes = [u8p]
    lib.dfd_pool_new.restype = ctypes.c_void_p
    lib.dfd_pool_new.argtypes = [ctypes.c_int]
    lib.dfd_pool_free.argtypes = [ctypes.c_void_p]
    lib.dfd_pool_decode_files.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.dfd_warp_affine.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double)]
    lib.dfd_pool_warp_affine.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(u8p), ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double)]


def available() -> bool:
    """True if the native decoder is importable (builds it on first call)."""
    if os.environ.get("DFD_NO_NATIVE_DECODE"):
        return False
    return _load() is not None


def _to_array(lib, ptr, w: int, h: int) -> Optional[np.ndarray]:
    if not ptr:
        return None
    try:
        arr = np.ctypeslib.as_array(ptr, shape=(h, w, 3)).copy()
    finally:
        lib.dfd_free(ptr)
    return arr


def decode_jpeg_file(path: str, scale_denom: int = 1
                     ) -> Optional[np.ndarray]:
    """Decode one JPEG file to an (H, W, 3) uint8 array, or None."""
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    ptr = lib.dfd_decode_jpeg_file(path.encode(), scale_denom,
                                   ctypes.byref(w), ctypes.byref(h))
    return _to_array(lib, ptr, w.value, h.value)


def decode_jpeg_bytes(data: bytes, scale_denom: int = 1
                      ) -> Optional[np.ndarray]:
    """Decode a JPEG byte string to an (H, W, 3) uint8 array, or None."""
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    ptr = lib.dfd_decode_jpeg(data, len(data), scale_denom,
                              ctypes.byref(w), ctypes.byref(h))
    return _to_array(lib, ptr, w.value, h.value)


class DecodePool:
    """Persistent C++ worker pool decoding batches of JPEG files.

    ``decode_files`` blocks until every file in the batch is done; failed
    images come back as None so the caller can fall back to PIL per-file.
    """

    def __init__(self, num_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native decode library unavailable")
        self._lib = lib
        self._pool = lib.dfd_pool_new(num_threads)
        self.num_threads = num_threads

    def decode_files(self, paths: Sequence[str], scale_denom: int = 1
                     ) -> List[Optional[np.ndarray]]:
        if not getattr(self, "_pool", None):
            raise ValueError("DecodePool is closed")
        n = len(paths)
        if n == 0:
            return []
        lib = self._lib
        c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
        outs = (ctypes.POINTER(ctypes.c_uint8) * n)()
        ws = (ctypes.c_int * n)()
        hs = (ctypes.c_int * n)()
        lib.dfd_pool_decode_files(self._pool, n, c_paths, scale_denom,
                                  outs, ws, hs)
        return [_to_array(lib, outs[i], ws[i], hs[i]) for i in range(n)]

    def close(self) -> None:
        if getattr(self, "_pool", None):
            self._lib.dfd_pool_free(self._pool)
            self._pool = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


# Source-staging counters (process-local; telemetry's input-pipeline
# gauges read them via warp_copy_stats).  Plain ints under the GIL; shm-
# backend loader workers warp in their own processes and count there.
_warp_copies_elided = 0     # strided sources passed copy-free (pre-ABI-3
#                             these paid an ascontiguousarray copy each)
_warp_copies = 0            # sources that still needed the staging copy


def warp_copy_stats() -> dict:
    """Lifetime warp source-staging counts for this process."""
    return {"elided": _warp_copies_elided, "copied": _warp_copies}


def _stage_warp_src(f) -> tuple:
    """(array, src_pixel_stride) for one warp source frame.

    The ABI-3 kernel reads sources at an arbitrary pixel stride as long
    as rows are dense (``row_stride == width * pixel_stride``) and the 3
    channels are adjacent — exactly the layout of a channel-slice view
    ``base[..., 3i:3i+3]`` of a C-contiguous (H, W, 3·F) packed clip (the
    packed-cache mmap views).  Such views pass through copy-free; anything
    else (PIL images, casts, exotic strides) pays the contiguous staging
    copy it always did.
    """
    global _warp_copies_elided, _warp_copies
    a = f if isinstance(f, np.ndarray) else np.asarray(f)
    if a.dtype == np.uint8 and a.ndim == 3 and a.shape[2] == 3 and \
            a.strides[2] == 1 and a.strides[1] >= 3 and \
            a.strides[0] == a.shape[1] * a.strides[1]:
        if a.strides[1] != 3:
            _warp_copies_elided += 1
        return a, int(a.strides[1])
    _warp_copies += 1
    return np.ascontiguousarray(a, dtype=np.uint8), 3


def warp_affine_batch(frames: Sequence[np.ndarray], coeffs: Sequence[float],
                      out_size, pool: Optional["DecodePool"] = None,
                      packed: bool = False):
    """Bilinear-warp a clip's frames with one shared affine draw.

    ``coeffs`` = (A, B, C, D, E, F) maps output pixel INDEX (x, y) →
    source pixel INDEX (A·x+B·y+C, D·x+E·y+F); ``out_size`` =
    (width, height).  NOTE this is index space, not PIL's
    ``Image.transform`` continuous-coordinate convention (they differ by
    (A+B)/2 − ½ in the constant terms).
    Returns (H, W, 3) uint8 arrays — or, with ``packed=True``, ONE
    (H, W, 3·n) array each frame wrote its channel slice of (strided dst),
    so the downstream channel-concat copy disappears.  None when the
    native library is unavailable (caller falls back to PIL).  Frames warp
    in parallel on the shared worker pool — this is the one-pass
    replacement for the rotate/flip/resize/crop PIL chain
    (transforms.py::MultiFusedGeometric).
    """
    lib = _load()
    if lib is None:
        return None
    tw, th = int(out_size[0]), int(out_size[1])
    n = len(frames)
    if n == 0:
        return np.empty((th, tw, 0), np.uint8) if packed else []
    staged = [_stage_warp_src(f) for f in frames]
    frames = [a for a, _ in staged]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    if packed:
        out = np.empty((th, tw, 3 * n), np.uint8)
        base = out.ctypes.data
        stride = 3 * n
        dsts = (u8p * n)(*[ctypes.cast(base + 3 * i, u8p)
                           for i in range(n)])
    else:
        outs = [np.empty((th, tw, 3), np.uint8) for _ in range(n)]
        stride = 3
        dsts = (u8p * n)(*[o.ctypes.data_as(u8p) for o in outs])
    srcs = (u8p * n)(*[f.ctypes.data_as(u8p) for f in frames])
    sws = (ctypes.c_int * n)(*[f.shape[1] for f in frames])
    shs = (ctypes.c_int * n)(*[f.shape[0] for f in frames])
    sss = (ctypes.c_int * n)(*[ss for _, ss in staged])
    # INDEX-SPACE convention: output pixel index (x, y) samples source
    # INDEX (A·x+B·y+C, D·x+E·y+F).  PIL's Image.transform differs by a
    # half-pixel term (it maps continuous coords: index A·x+B·y+
    # (C+(A+B)/2−½)) — callers holding PIL-convention coeffs must convert
    # (see MultiFusedGeometric's fallback, which does the reverse).
    c = (ctypes.c_double * 6)(*[float(v) for v in coeffs])
    p = pool or default_pool()
    if p is not None:
        lib.dfd_pool_warp_affine(p._pool, n, srcs, sws, shs, sss, dsts,
                                 tw, th, stride, c)
    else:
        for i in range(n):
            lib.dfd_warp_affine(srcs[i], sws[i], shs[i], sss[i], dsts[i],
                                tw, th, stride, c)
    return out if packed else outs


_default_pool: Optional[DecodePool] = None
_pool_lock = threading.Lock()
_default_pool_threads: Optional[int] = None


def set_default_pool_threads(num_threads: int) -> None:
    """Pin the lazily-created default pool's thread count.

    Multi-process loader workers call this before their first decode so N
    worker processes don't each spin up the full 4-thread default pool
    (N×4 native threads on a host with far fewer spare cores).  A no-op if
    the pool already exists; ``DFD_NATIVE_POOL_THREADS`` overrides both.
    """
    global _default_pool_threads
    _default_pool_threads = max(1, int(num_threads))


def default_pool(num_threads: int = 4) -> Optional[DecodePool]:
    """Process-wide shared pool (created lazily); None if unavailable."""
    global _default_pool
    if not available():
        return None
    if _default_pool is None:
        with _pool_lock:
            if _default_pool is None:
                n = int(os.environ.get("DFD_NATIVE_POOL_THREADS", 0)) \
                    or _default_pool_threads or num_threads
                _default_pool = DecodePool(n)
    return _default_pool


def _drop_pool_after_fork() -> None:  # pragma: no cover - fork-start only
    """The pool's C++ threads do not survive fork: calling into an
    inherited pool handle deadlocks the child.  Drop the reference (the C
    allocation is leaked in the child — freeing it would try to join
    threads that don't exist there) so the child lazily builds its own."""
    global _default_pool
    _default_pool = None


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_drop_pool_after_fork)
