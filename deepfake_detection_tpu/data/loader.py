"""Batch loading: host pipeline + device prologue.

Re-design of ``/root/reference/dfd/timm/data/loader.py``:

* ``fast_collate`` (:12-46) → :func:`fast_collate` — numpy uint8 stacking,
  NHWC.
* worker-process decode + transform → :class:`HostLoader` — a thread pool
  (PIL/numpy release the GIL for decode/resize) with a bounded prefetch
  queue; per-sample RNG derived from ``(seed, epoch, index)`` so output is
  identical for any worker count.
* ``PrefetchLoader_v3`` (:213-289 — CUDA-stream double buffering, fp16 cast,
  mean/std tiled ×img_num, GPU RandomErasing) → :class:`DeviceLoader` — a
  jitted prologue (uint8 → compute dtype, normalize, RandomErasing per frame
  slice) dispatched asynchronously; JAX's async dispatch + donated buffers
  replace the explicit CUDA stream dance.
* ``create_deepfake_loader_v3`` (:724-830) → :func:`create_deepfake_loader_v3`
  with the same knob surface.

Normalization parity: mean/std are ×255 (uint8 domain) tiled to all
``3*img_num`` channels (loader.py:228-229); casting happens *on device*, so
host→TPU transfers stay uint8 — 4× less PCIe/DMA traffic than shipping
floats.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
import logging
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_logger = logging.getLogger(__name__)

from .constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from .mixup import FastCollateMixup
from .random_erasing import RandomErasing
from .samplers import (OrderedShardedSampler, ShardedTrainSampler,
                       epoch_batches)
from .transforms_factory import (transforms_deepfake_eval_v3,
                                 transforms_deepfake_train_v3)

__all__ = ["fast_collate", "HostLoader", "DeviceLoader", "LoaderStats",
           "HostLoaderStats", "create_loader", "create_deepfake_loader_v3"]

LOADER_BACKENDS = ("thread", "shm")


class LoaderStats:
    """Monotonic DeviceLoader wait counters (obs/telemetry.py input gauges).

    Two ``time.monotonic`` deltas per batch around blocks the loader
    ALREADY performs — no new syncs, no locks (single writer: the consumer
    thread; telemetry reads are torn-proof float loads under the GIL).
    """

    __slots__ = ("batches", "host_wait_s", "stage_block_s", "augment_elided")

    def __init__(self):
        self.batches = 0        # batches staged to device
        self.host_wait_s = 0.0  # blocked in next(host_loader) — input starved
        self.stage_block_s = 0.0  # blocked in the slab-recycle
        # block_until_ready — prologue/staging backpressure (device busy)
        self.augment_elided = 0  # host augment stages elided by
        # --augment-device (samples x stages moved into the prologue)


class HostLoaderStats:
    """Producer-side thread-backend counters (written by the producer
    thread; same single-writer torn-proof contract as LoaderStats)."""

    __slots__ = ("batches", "fetch_s", "put_wait_s")

    def __init__(self):
        self.batches = 0        # batches collated
        self.fetch_s = 0.0      # decode+transform+collate time
        self.put_wait_s = 0.0   # blocked on the full prefetch queue
        # (consumer slower than the pipeline — healthy backpressure)


def _loader_chaos():
    """Chaos injector for loader-side fault points, None in production
    (``DFD_CHAOS`` unset — the probe then costs one env read per epoch).
    Fresh per iteration: loader points key on the batch index within an
    epoch, unlike the trainer's run-global update counter."""
    if not os.environ.get("DFD_CHAOS"):
        return None
    from ..chaos import chaos_from_env
    return chaos_from_env()


def fast_collate(samples: Sequence[Tuple[np.ndarray, int]]
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Stack uint8 NHWC samples + int labels (reference :12-46).

    AugMix multi-view samples — ``(S, H, W, C)`` per sample — collate
    split-major: ``[view0 of all samples, view1 of all samples, ...]`` with
    labels tiled, the layout ``jsd_cross_entropy`` splits back apart
    (reference fast_collate tuple branch, loader.py:15-27).
    """
    images = np.stack([s[0] for s in samples]).astype(np.uint8, copy=False)
    targets = np.asarray([s[1] for s in samples], dtype=np.int64)
    if images.ndim == 5:                       # (B, S, H, W, C)
        b, s = images.shape[:2]
        images = np.transpose(images, (1, 0, 2, 3, 4)).reshape(
            b * s, *images.shape[2:])
        targets = np.tile(targets, s)
    return images, targets


class HostLoader:
    """Decode + transform + collate on host threads with prefetch.

    Yields ``(images_uint8 (B,H,W,C), targets)`` numpy batches (targets are
    int64, or float32 soft targets when ``collate_mixup`` is set).  A batch's
    content is a pure function of ``(seed, epoch, batch_index)``.
    """

    def __init__(self, dataset, sampler, batch_size: int, seed: int = 42,
                 num_workers: int = 8, prefetch_depth: int = 2,
                 collate_mixup: Optional[FastCollateMixup] = None,
                 valid_mask: bool = False):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = batch_size
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch_depth = max(1, prefetch_depth)
        self.collate_mixup = collate_mixup
        self.valid_mask = valid_mask
        self.epoch = 0
        self.stats = HostLoaderStats()
        # mid-epoch resume: skip producing batches < start_batch while
        # keeping their ABSOLUTE indices for every per-batch RNG, so a
        # fast-forwarded epoch's remaining batches are bit-identical to an
        # uninterrupted one.  Reset by set_epoch (one epoch's worth).
        self.start_batch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.start_batch = 0
        self.sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.sampler) // self.batch_size

    def _load_one(self, index: int) -> Tuple[np.ndarray, int]:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.epoch, int(index)]))
        img, target = self.dataset.__getitem__(int(index), rng=rng)
        return np.asarray(img, dtype=np.uint8), target

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        batches, vms = epoch_batches(self.sampler, self.batch_size,
                                     self.valid_mask)
        start = self.start_batch
        chaos = _loader_chaos()
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        stop = threading.Event()

        def put(item) -> bool:
            """Bounded put that keeps observing ``stop`` (an abandoned
            consumer otherwise deadlocks the producer on the full queue)."""
            t0 = time.monotonic()
            try:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False
            finally:
                self.stats.put_wait_s += time.monotonic() - t0

        def produce():
            with ThreadPoolExecutor(self.num_workers) as pool:
                for bi, batch_idx in enumerate(batches):
                    if bi < start:
                        continue
                    if stop.is_set():
                        return
                    if chaos is not None and chaos.fires("stall_loader", bi):
                        # simulates a wedged data source: no batch reaches
                        # the train loop until the sleep (default 120 s)
                        # ends — long enough to trip any sane watchdog
                        _logger.warning("chaos: stalling loader %.0fs at "
                                        "batch %d",
                                        chaos.arg("stall_loader", 120.0), bi)
                        time.sleep(chaos.arg("stall_loader", 120.0))
                    t_fetch = time.monotonic()
                    samples = list(pool.map(self._load_one, batch_idx))
                    images, targets = fast_collate(samples)
                    if self.collate_mixup is not None:
                        mrng = np.random.default_rng(np.random.SeedSequence(
                            [self.seed, self.epoch, bi, 0x77]))
                        images, targets = self.collate_mixup(images, targets,
                                                             mrng)
                    self.stats.fetch_s += time.monotonic() - t_fetch
                    self.stats.batches += 1
                    if vms is not None:
                        item: Any = (images, targets, vms[bi])
                    else:
                        item = (images, targets)
                    if not put(item):
                        return
                put(None)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                yield item
        finally:
            stop.set()


class DeviceLoader:
    """Device-side prologue with async double buffering.

    The jitted prologue does uint8→``dtype`` cast, mean/std normalize (×255,
    tiled per frame), and train-time RandomErasing — the body of the
    reference's ``PrefetchLoader_v3.__iter__`` (loader.py:242-266) as one
    compiled function.  Because JAX dispatch is asynchronous, iterating one
    batch ahead gives the same copy/compute overlap the reference builds from
    CUDA streams.
    """

    def __init__(self, loader: HostLoader,
                 mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
                 dtype: Any = jnp.bfloat16, re_prob: float = 0.0,
                 re_mode: str = "const", re_count: int = 1,
                 re_num_splits: int = 0, re_max: float = 0.1,
                 img_num: int = 4, seed: int = 0,
                 sharding: Optional[Any] = None,
                 color_jitter=None, flicker: float = 0.0,
                 stem_s2d: bool = False, device_augment: Optional[Any] = None):
        self.loader = loader
        self.img_num = img_num
        self.stem_s2d = stem_s2d
        self.dtype = dtype
        self.sharding = sharding
        self.seed = seed
        self.stats = LoaderStats()
        # --augment-device on: a DeviceAugmentSpec (device_augment.py); the
        # host transform is then the raw-source passthrough and warp/blur/
        # mixup render here, keyed by the absolute (seed, epoch, index) /
        # (seed, epoch, batch) numpy streams the host chain would draw from
        self._augment = device_augment
        self.augment_device = device_augment is not None
        mean = np.tile(np.asarray(mean, np.float32) * 255.0, img_num)
        std = np.tile(np.asarray(std, np.float32) * 255.0, img_num)
        self._mean = mean.reshape(1, 1, 1, -1)
        self._std = std.reshape(1, 1, 1, -1)
        self.random_erasing = RandomErasing(
            probability=re_prob, max_area=re_max, mode=re_mode,
            max_count=re_count, num_splits=re_num_splits,
            img_num=img_num) if re_prob > 0.0 else None
        self._step = 0

        mean_j = jnp.asarray(self._mean)
        std_j = jnp.asarray(self._std)
        erasing = self.random_erasing
        from .device_augment import make_device_color_jitter
        jitter = make_device_color_jitter(color_jitter, flicker, img_num)
        if stem_s2d:
            # lazy: pulls flax via ops; only the consumer process (which
            # already built the model) constructs a DeviceLoader
            from ..ops.conv import space_to_depth
        else:
            space_to_depth = None
        if device_augment is not None:
            from .device_augment import (device_mixup_blend, make_device_blur,
                                         make_device_geometric)
            warp = make_device_geometric(device_augment)
            blur = make_device_blur(device_augment) \
                if device_augment.blur_prob > 0.0 else None
            mix_blocks = device_augment.mixup_blocks
            mix_on = device_augment.mixup
        else:
            warp = blur = None
            device_mixup_blend = None
            mix_blocks, mix_on = 1, False

        # ONE jitted prologue — single dispatch per batch.  Documented op
        # order (augment → normalize → s2d): warp → blur → jitter/flicker →
        # mixup blend → cast → normalize → RandomErasing → s2d pixel
        # shuffle.  That is the host chain's order (geometric → blur →
        # jitter → flicker → collate mixup → prologue), with the s2d stem
        # shuffle folded in last exactly as the two-stage path applied it
        # after normalize.
        def prologue(images, key, geom=None, blur_mask=None,
                     lam=None, one_minus_lam=None):
            # jitter operates in 0..255 float space BEFORE normalize, like
            # the host PIL chain it replaces (device_augment.py)
            jkey, ekey = jax.random.split(key)
            if warp is not None:
                x = warp(images, geom)             # f32, integer-valued
                if blur is not None:
                    x = blur(x, blur_mask)
                if jitter is not None:
                    x = jitter(x, jkey)
                if mix_on:
                    x = device_mixup_blend(x, lam, one_minus_lam,
                                           mix_blocks)
                x = x.astype(dtype)
            else:
                x = images.astype(jnp.float32 if jitter is not None
                                  else dtype)
                if jitter is not None:
                    x = jitter(x, jkey).astype(dtype)
            x = (x.astype(dtype) - mean_j.astype(dtype)) / std_j.astype(dtype)
            if erasing is not None:
                x = erasing(ekey, x).astype(dtype)
            if space_to_depth is not None:
                # s2d stem (PERF.md post-fusion roofline): ship the pixel
                # shuffle with the prologue so the (B, H/2, W/2, 4C) layout
                # lands on device once — the model consumes it directly
                # instead of re-shuffling every step
                x = space_to_depth(x)
            return x

        # NOTE: donating the uint8 wire buffer here would be a no-op — XLA
        # input->output aliasing needs matching byte sizes and the output is
        # 2-4x wider (bf16/f32); refcounting already frees the temporary
        self._prologue = jax.jit(prologue)

    # pass-throughs (reference :274-289)
    @property
    def sampler(self):
        return self.loader.sampler

    @property
    def dataset(self):
        return self.loader.dataset

    @property
    def mixup_enabled(self) -> bool:
        cm = self.loader.collate_mixup
        return bool(cm and cm.mixup_enabled)

    @mixup_enabled.setter
    def mixup_enabled(self, x: bool) -> None:
        if self.loader.collate_mixup is not None:
            self.loader.collate_mixup.mixup_enabled = x

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)
        # pin the prologue key stream to the ABSOLUTE position: every epoch
        # stages exactly len(loader) batches, so _step == epoch * len at an
        # epoch start in ANY run — a no-op for an uninterrupted run, and
        # the thing that makes a freshly-constructed loader's RandomErasing/
        # jitter keys bit-identical to the original run's after auto-resume
        self._step = epoch * len(self.loader)

    def fast_forward(self, start_batch: int) -> None:
        """Resume mid-epoch: the next iteration yields batches from
        ``start_batch`` on, bit-identical to the tail of a full epoch
        (host loaders keep absolute batch indices for their per-batch RNG;
        the prologue key stream advances to match).  Call AFTER
        :meth:`set_epoch`; cleared by the next ``set_epoch``."""
        if start_batch <= 0:
            return
        if not hasattr(self.loader, "start_batch"):
            raise NotImplementedError(
                f"{type(self.loader).__name__} cannot fast-forward")
        self.loader.start_batch = int(start_batch)
        self._step += int(start_batch)

    def close(self) -> None:
        """Tear down the host loader's workers/shm (no-op for threads)."""
        close = getattr(self.loader, "close", None)
        if close is not None:
            close()

    def __len__(self) -> int:
        return len(self.loader)

    def _put(self, arr: np.ndarray):
        if self.sharding is not None:
            from ..parallel.sharding import put_process_local
            return put_process_local(arr, self.sharding)
        return jax.device_put(arr)

    def _stage(self, item, base_key, batch_index: int = 0,
               indices: Optional[Sequence[int]] = None):
        """device_put + dispatch the prologue for one host batch."""
        images, targets = item[0], item[1]
        key = jax.random.fold_in(base_key, self._step)
        self._step += 1
        if self._augment is not None:
            from .device_augment import (derive_geometric_batch,
                                         derive_mixup_lam)
            if indices is None or len(indices) != images.shape[0]:
                raise RuntimeError(
                    "--augment-device: per-sample indices out of step with "
                    f"the host batch ({None if indices is None else len(indices)} "
                    f"vs {images.shape[0]} rows)")
            geom, blur_mask = derive_geometric_batch(
                self._augment, indices, self.loader.seed, self.loader.epoch,
                images.shape[1:3])
            if self._augment.mixup:
                cm = self.loader.collate_mixup
                lam, om = derive_mixup_lam(
                    self.loader.seed, self.loader.epoch, batch_index,
                    self._augment.mixup_alpha,
                    bool(cm is not None and cm.mixup_enabled))
            else:
                lam, om = np.float32(1.0), np.float32(0.0)
            x = self._prologue(self._put(images), key, self._put(geom),
                               self._put(blur_mask), lam, om)
            self.stats.augment_elided += \
                images.shape[0] * self._augment.host_stages_elided
        else:
            x = self._prologue(self._put(images), key)
        # targets/valid views may be ring-slab backed: small, copy before
        # the put so slot recycling can never touch them
        y = self._put(np.array(targets))
        if len(item) == 3:
            return x, y, self._put(np.array(item[2]))
        return x, y

    def __iter__(self):
        base_key = jax.random.PRNGKey(self.seed)
        batches = None
        if self._augment is not None:
            # the device side re-derives each sample's augment parameters
            # from (seed, epoch, index): recompute the host loaders' exact
            # (epoch, batch) → indices mapping (epoch_batches is a pure
            # function of the shared sampler state, and both backends
            # front-end through it)
            batches, _ = epoch_batches(self.loader.sampler,
                                       self.loader.batch_size, False)
        bi = getattr(self.loader, "start_batch", 0)
        it = iter(self.loader)
        # double buffering: stage batch k+1 (host→device transfer +
        # prologue dispatch) BEFORE yielding batch k, so the transfer
        # overlaps the consumer's compiled step on batch k — the async-
        # dispatch equivalent of the reference's CUDA-stream prefetcher.
        pending = None
        prev_x = None
        stats = self.stats
        while True:
            if prev_x is not None:
                # the shm ring recycles batch k's slab once batch k+2 is
                # requested; jax CPU device_put zero-copies aligned host
                # buffers, so batch k's prologue (the only reader of the
                # slab) must have RUN before we pull the next host batch
                t0 = time.monotonic()
                jax.block_until_ready(prev_x)
                stats.stage_block_s += time.monotonic() - t0
                prev_x = None
            try:
                t0 = time.monotonic()
                item = next(it)
                stats.host_wait_s += time.monotonic() - t0
            except StopIteration:
                break
            staged = self._stage(item, base_key, batch_index=bi,
                                 indices=None if batches is None
                                 else batches[bi])
            bi += 1
            stats.batches += 1
            if pending is not None:
                prev_x = staged[0]
                yield pending
            pending = staged
        if pending is not None:
            yield pending


def _build_loader(dataset, transform, batch_size: int, is_training: bool,
                  num_aug_splits: int, collate_mixup, distributed: bool,
                  num_shards: int, shard_index: int, seed: int,
                  num_workers: int, prefetch_depth: int,
                  valid_mask: Optional[bool],
                  device_kwargs: dict, loader_backend: str = "thread",
                  ring_depth: int = 4,
                  worker_heartbeat: float = 120.0) -> DeviceLoader:
    """Shared factory tail: AugMix wrap, transform attach, sharded sampler
    selection, host loader backend, device prologue.  Both
    :func:`create_loader` and :func:`create_deepfake_loader_v3` end here."""
    hw = getattr(dataset, "packed_hw", None)
    if hw is not None:
        # packed pre-decoded cache: the pack replaces the decode STAGE
        # only — transform, sampler, collate and transport below are the
        # shared code paths.  A pack smaller than the crop would make
        # pad_if_needed silently diverge from the decode path: warn loud.
        crop = getattr(transform.transforms[0], "size", None) \
            if getattr(transform, "transforms", None) else None
        if crop is not None and isinstance(crop, tuple) and \
                (crop[0] > hw[0] or crop[1] > hw[1]):
            _logger.warning(
                "packed cache resolution %s is below the crop %s: crops "
                "will pad, diverging from the decode path — re-pack with "
                "a larger --pack-image-size", hw, crop)
    if is_training and num_aug_splits > 1:
        # clean + (num_aug_splits-1) AugMix views per sample, feeding the
        # JSD consistency loss (reference dataset.py:633-670)
        assert collate_mixup is None, \
            "aug_splits and the mixup collate are mutually exclusive " \
            "(reference train.py:446)"
        from .dataset import AugMixDataset
        dataset = AugMixDataset(dataset, num_splits=num_aug_splits)
    dataset.set_transform(transform)

    if not distributed:
        num_shards, shard_index = 1, 0
    if is_training:
        sampler: Any = ShardedTrainSampler(
            len(dataset), num_shards=num_shards, shard_index=shard_index,
            batch_size=batch_size, seed=seed, drop_last=True)
    else:
        sampler = OrderedShardedSampler(
            len(dataset), num_shards=num_shards, shard_index=shard_index,
            batch_size=batch_size)
    if valid_mask is None:
        valid_mask = not is_training
    if loader_backend == "shm":
        from .shm_ring import ShmRingLoader
        host: Any = ShmRingLoader(
            dataset, sampler, batch_size, seed=seed,
            num_workers=num_workers, ring_depth=ring_depth,
            collate_mixup=collate_mixup if is_training else None,
            valid_mask=valid_mask, heartbeat_timeout=worker_heartbeat)
    elif loader_backend == "thread":
        host = HostLoader(dataset, sampler, batch_size, seed=seed,
                          num_workers=num_workers,
                          prefetch_depth=prefetch_depth,
                          collate_mixup=collate_mixup if is_training else None,
                          valid_mask=valid_mask)
    else:
        raise ValueError(f"loader_backend must be one of {LOADER_BACKENDS}, "
                         f"got {loader_backend!r}")
    return DeviceLoader(host, seed=seed, **device_kwargs)


def create_loader(
        dataset, input_size, batch_size: int, is_training: bool = False,
        re_prob: float = 0.0, re_mode: str = "const", re_count: int = 1,
        re_split: bool = False, re_max: float = 0.02,
        color_jitter: Any = 0.4,
        auto_augment: Optional[str] = None, num_aug_splits: int = 0,
        interpolation: str = "bilinear",
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
        num_workers: int = 1, distributed: bool = False,
        num_shards: int = 1, shard_index: int = 0,
        crop_pct: Optional[float] = None,
        collate_mixup: Optional[FastCollateMixup] = None,
        dtype: Any = jnp.bfloat16, tf_preprocessing: bool = False,
        seed: int = 42, prefetch_depth: int = 2,
        sharding: Optional[Any] = None, valid_mask: Optional[bool] = None,
        loader_backend: str = "thread", ring_depth: int = 4,
        worker_heartbeat: float = 120.0, stem_s2d: bool = False,
        ) -> DeviceLoader:
    """Generic single-image loader factory (reference loader.py:372-456).

    The timm-style path for training the backbone families on folder /
    tar / synthetic datasets — the deepfake clip path is
    :func:`create_deepfake_loader_v3`.  Reference knobs map as: torch
    ``DistributedSampler``/``OrderedDistributedSampler`` → the sharded
    samplers (``distributed`` + ``num_shards``/``shard_index``);
    ``use_prefetcher``/``fp16``/``pin_memory``/CUDA streams → the always-on
    uint8-wire :class:`DeviceLoader` with ``dtype``; ``collate_fn`` →
    ``collate_mixup`` (the only non-default collate the reference ever
    passes, train.py:444).
    """
    from .transforms_factory import create_transform

    re_num_splits = 0
    if re_split:
        # RE on the second half of the batch, or aligned with aug splits
        # (reference :397-399)
        re_num_splits = num_aug_splits or 2
    # the host transform uses mean only (auto-augment fill color);
    # normalization with mean AND std happens in the device prologue, so
    # std is deliberately not forwarded here
    transform = create_transform(
        input_size, is_training=is_training, color_jitter=color_jitter,
        auto_augment=auto_augment, interpolation=interpolation, mean=mean,
        crop_pct=crop_pct, tf_preprocessing=tf_preprocessing)
    return _build_loader(
        dataset, transform, batch_size, is_training, num_aug_splits,
        collate_mixup, distributed, num_shards, shard_index, seed,
        num_workers, prefetch_depth, valid_mask,
        dict(mean=mean, std=std, dtype=dtype,
             re_prob=re_prob if is_training else 0.0, re_mode=re_mode,
             re_count=re_count, re_num_splits=re_num_splits, re_max=re_max,
             img_num=1, sharding=sharding, stem_s2d=stem_s2d),
        loader_backend=loader_backend, ring_depth=ring_depth,
        worker_heartbeat=worker_heartbeat)


def create_deepfake_loader_v3(
        dataset, input_size, batch_size: int, is_training: bool = False,
        re_prob: float = 0.0, re_mode: str = "const", re_count: int = 1,
        re_split: bool = False, re_max: float = 0.02,
        color_jitter: Any = 0.4, num_aug_splits: int = 0,
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
        num_workers: int = 1, distributed: bool = False,
        num_shards: int = 1, shard_index: int = 0,
        collate_mixup: Optional[FastCollateMixup] = None,
        dtype: Any = jnp.bfloat16, flicker: float = 0.0,
        rotate_range: float = 0, blur_radius: Optional[float] = None,
        blur_prob: float = 0.0, seed: int = 42, prefetch_depth: int = 2,
        sharding: Optional[Any] = None, valid_mask: Optional[bool] = None,
        eval_crop: str = "random", device_color_jitter: bool = True,
        fused_geom: bool = True, loader_backend: str = "thread",
        ring_depth: int = 4, worker_heartbeat: float = 120.0,
        stem_s2d: bool = False, augment_device: bool = False,
        blur_radiu: Optional[float] = None,
        ) -> DeviceLoader:
    """Loader factory (reference loader.py:724-830): builds the v3 transform,
    picks the train/eval sharded sampler, wires collate mixup and the device
    prologue.

    ``device_color_jitter`` (default) moves ColorJitter/Flicker off the host
    into the jitted device prologue (device_augment.py); ``fused_geom``
    (default) renders the geometric chain as one native warp — together they
    cut host cost per clip ~3× at the flagship shape.  Disabling both
    restores the reference-exact host PIL pipeline.

    ``augment_device`` (``--augment-device on``) moves the REMAINING host
    augment — the geometric warp, per-frame blur, and the mixup blend —
    into the same jitted prologue, keyed by the identical absolute numpy
    RNG streams (device_augment.py); the host transform collapses to a
    raw-source passthrough and host input cost becomes the collate/slab
    memcpy.  Falls back to the host chain (with a log line) for the
    host-only stages: AugMix aug-splits and hue jitter.  ``blur_radiu``
    is the deprecated alias of ``blur_radius``."""
    from .transforms_factory import _blur_radius_compat
    blur_radius = _blur_radius_compat(blur_radius, blur_radiu)
    re_num_splits = 0
    if re_split:
        re_num_splits = num_aug_splits or 2
    img_size = input_size[-2:] if isinstance(input_size, (tuple, list)) \
        else input_size
    if isinstance(img_size, (tuple, list)) and len(img_size) == 2:
        img_size = img_size[0] if img_size[0] == img_size[1] else tuple(img_size)

    aug_device = bool(augment_device and is_training)
    if aug_device and num_aug_splits > 1:
        # the AugMix view augmentation is a host PIL op chain applied to
        # the POST-geometric clip; warping on device would reorder it —
        # keep the host chain rather than silently change what the JSD
        # loss measures
        _logger.info("aug-splits active: device augmentation falls back "
                     "to the host chain")
        aug_device = False
    if aug_device and not fused_geom:
        raise ValueError("augment_device renders the fused geometric warp "
                         "on device; it conflicts with the host_geom / "
                         "fused_geom=False parity escape hatch — pick one")

    device_cj = None
    device_flicker = 0.0
    if is_training and device_color_jitter:
        cj = None
        if color_jitter is not None:
            cj = (color_jitter if isinstance(color_jitter, (list, tuple))
                  else (float(color_jitter),) * 3)
            assert len(cj) in (3, 4)
        if cj is not None and len(cj) == 4 and float(cj[3]) > 0:
            # hue jitter is host-only (HSV round-trip not implemented on
            # device): keep the full PIL chain rather than silently
            # dropping the hue component
            _logger.info("hue jitter requested: color jitter stays on host")
            if aug_device:
                _logger.info("hue jitter requested: device augmentation "
                             "falls back to the host chain")
                aug_device = False
        elif aug_device:
            # the device prologue preserves the host order (jitter BEFORE
            # the mixup blend, device_augment.py op order), so jitter/
            # flicker ride the device even under mixup here
            device_cj = tuple(float(v) for v in cj[:3]) if cj else None
            device_flicker, flicker = flicker, 0.0
            color_jitter = None
        elif collate_mixup is not None and is_training:
            # the host chain jitters each source clip BEFORE mixup blends
            # them; a post-blend device jitter would correlate the two
            # sources' photometrics — keep host order under mixup
            _logger.info("mixup active: color jitter stays on host")
        elif num_aug_splits > 1:
            # AugMix views of one sample share the base transform's single
            # jitter draw (host chain); as separate batch rows they would
            # get INDEPENDENT device draws, changing what the JSD
            # consistency loss measures — keep host jitter under aug-splits
            _logger.info("aug-splits active: color jitter stays on host")
        else:
            device_cj = tuple(float(v) for v in cj[:3]) if cj else None
            device_flicker, flicker = flicker, 0.0
            color_jitter = None
    if aug_device and (color_jitter is not None or flicker > 0.0):
        # --host-color-jitter with --augment-device: the passthrough chain
        # has no host jitter/flicker stage to run them in
        raise ValueError(
            "augment_device leaves no host transform stage for host-side "
            "color jitter/flicker — drop host_color_jitter (hue jitter "
            "already falls back to the host chain automatically)")

    device_augment = None
    if is_training:
        if aug_device:
            from .device_augment import DeviceAugmentSpec
            from .transforms_factory import \
                transforms_deepfake_train_passthrough
            size2 = (img_size, img_size) if isinstance(img_size, int) \
                else tuple(img_size)
            img_num_ = int(input_size[0] / 3) \
                if isinstance(input_size, (tuple, list)) else 1
            device_augment = DeviceAugmentSpec(
                size=size2, rotate_range=int(rotate_range),
                blur_prob=float(blur_prob),
                blur_radius=float(blur_radius or 0.0),
                img_num=max(1, img_num_),
                mixup=collate_mixup is not None,
                mixup_alpha=getattr(collate_mixup, "mixup_alpha", 0.0),
                # the host collate mixes within each PROCESS's local
                # batch; the device blend flips within matching blocks
                mixup_blocks=num_shards if distributed else 1)
            if collate_mixup is not None:
                collate_mixup.blend = False     # lam + soft targets only
            if getattr(dataset, "packed_hw", None) is None:
                _logger.info(
                    "augment_device without a packed cache: the decode "
                    "path must yield one uniform source geometry (the "
                    "warp compiles per source shape)")
            transform = transforms_deepfake_train_passthrough(
                img_size, rotate_range=rotate_range, blur_prob=blur_prob)
        else:
            transform = transforms_deepfake_train_v3(
                img_size, color_jitter=color_jitter, flicker=flicker,
                rotate_range=rotate_range, blur_radius=blur_radius,
                blur_prob=blur_prob, fused_geom=fused_geom)
    else:
        transform = transforms_deepfake_eval_v3(img_size, crop=eval_crop)
    img_num = int(input_size[0] / 3) if isinstance(input_size, (tuple, list)) \
        else 1
    return _build_loader(
        dataset, transform, batch_size, is_training, num_aug_splits,
        collate_mixup, distributed, num_shards, shard_index, seed,
        num_workers, prefetch_depth, valid_mask,
        dict(mean=mean, std=std, dtype=dtype,
             re_prob=re_prob if is_training else 0.0, re_mode=re_mode,
             re_count=re_count, re_num_splits=re_num_splits, re_max=re_max,
             img_num=max(1, img_num), sharding=sharding,
             color_jitter=device_cj, flicker=device_flicker,
             stem_s2d=stem_s2d, device_augment=device_augment),
        loader_backend=loader_backend, ring_depth=ring_depth,
        worker_heartbeat=worker_heartbeat)
