"""Host-side image transforms (PIL/numpy) with explicit RNG.

Re-design of ``/root/reference/dfd/timm/data/transforms.py``: the single-image
ImageNet transforms plus the ``Multi*`` family — transforms over a *list* of
PIL frames that share one random parameter draw across the 4 frames of a clip
(MultiRotate :261, MultiRandomHorizontalFlip :217, MultiRandomResize :281,
MultiRandomCrop :311, MultiBlur :243, MultiColorJitter :332, MultiFlicker
:346, MultiToNumpy :20, MultiConcate :29).

Two deliberate departures from the reference, both TPU-motivated:

* **Explicit RNG.** Every transform is called as ``t(img, rng)`` where ``rng``
  is a ``numpy.random.Generator``; ``Compose`` threads it through.  The
  reference uses the global ``random`` module, which is per-dataloader-worker
  state and irreproducible across worker counts.  Here the loader derives the
  generator from ``(seed, epoch, sample_index)`` so any (host, worker-count)
  layout produces identical batches.
* **NHWC output.** ``MultiToNumpy``/``MultiConcate`` emit ``(H, W, 3)`` frames
  concatenated to ``(H, W, 3*img_num)`` — channels-last, the TPU-native
  layout — instead of the reference's CHW/(12,H,W).
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import numpy as np
from PIL import Image, ImageEnhance, ImageFilter

__all__ = [
    "Compose", "ToNumpy", "RandomResizedCropAndInterpolation", "RandomResize",
    "RandomHorizontalFlip", "RandomVerticalFlip", "CenterCrop", "Resize",
    "RandomCrop", "ColorJitter",
    "MultiToNumpy", "MultiConcate", "MultiRandomHorizontalFlip", "MultiBlur",
    "MultiRotate", "MultiRandomResize", "MultiRandomCrop", "MultiCenterCrop",
    "MultiColorJitter", "MultiFlicker", "MultiFusedGeometric",
    "PackedFrames", "DeviceAugmentPassthrough", "fused_geometric_params",
    "blur_mask_draws",
]

_PIL_INTERP = {
    "nearest": Image.NEAREST,
    "bilinear": Image.BILINEAR,
    "bicubic": Image.BICUBIC,
    "lanczos": Image.LANCZOS,
}
_RANDOM_INTERPOLATION = (Image.BILINEAR, Image.BICUBIC)


def pil_interp(method: str):
    return _PIL_INTERP.get(method, Image.BILINEAR)


def _resolve_interp(interpolation, rng: np.random.Generator):
    if isinstance(interpolation, (tuple, list)):
        return interpolation[rng.integers(len(interpolation))]
    return interpolation


class Compose:
    """Chains transforms, threading the RNG through each."""

    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, img, rng: np.random.Generator):
        for t in self.transforms:
            img = t(img, rng)
        return img

    def __repr__(self):
        return f"Compose({self.transforms!r})"


# ---------------------------------------------------------------------------
# Single-image transforms
# ---------------------------------------------------------------------------

class ToNumpy:
    """PIL → (H, W, C) uint8 (reference emits CHW; we keep NHWC)."""

    def __call__(self, pil_img, rng=None):
        np_img = np.asarray(pil_img, dtype=np.uint8)
        if np_img.ndim < 3:
            np_img = np.expand_dims(np_img, axis=-1)
        return np_img


class Resize:
    def __init__(self, size: Union[int, Tuple[int, int]],
                 interpolation: str = "bilinear"):
        self.size = size
        self.interpolation = pil_interp(interpolation)

    def __call__(self, img, rng=None):
        if isinstance(self.size, int):
            w, h = img.size
            short = min(w, h)
            scale = self.size / short
            tw, th = int(round(w * scale)), int(round(h * scale))
        else:
            th, tw = self.size
        return img.resize((tw, th), self.interpolation)


class CenterCrop:
    def __init__(self, size: Union[int, Tuple[int, int]]):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img, rng=None):
        th, tw = self.size
        w, h = img.size
        left = int(round((w - tw) / 2.0))
        top = int(round((h - th) / 2.0))
        return img.crop((left, top, left + tw, top + th))


def _wh(img) -> Tuple[int, int]:
    """(width, height) for PIL frames and uint8 ndarray frames alike (the
    native warp and the packed-cache mmap path both emit arrays)."""
    if isinstance(img, np.ndarray):
        return img.shape[1], img.shape[0]
    return img.size


def _pad_to(img: Image.Image, tw: int, th: int, fill=0) -> Image.Image:
    """Pad the right/bottom only when needed (torchvision RandomCrop
    ``pad_if_needed`` pads symmetric-ish via (delta, 0); we center-pad)."""
    if isinstance(img, np.ndarray):
        return _pad_to_np(img, tw, th, fill)
    w, h = img.size
    if w >= tw and h >= th:
        return img
    nw, nh = max(w, tw), max(h, th)
    out = Image.new(img.mode, (nw, nh),
                    fill if not isinstance(fill, int) else tuple(
                        [fill] * len(img.getbands())) if len(
                        img.getbands()) > 1 else fill)
    out.paste(img, ((nw - w) // 2, (nh - h) // 2))
    return out


def _pad_to_np(a: np.ndarray, tw: int, th: int, fill=0) -> np.ndarray:
    """ndarray twin of :func:`_pad_to` — same center offsets, same fill —
    so array frames (packed cache / native warp) pad to the exact bytes
    the PIL path produces."""
    h, w = a.shape[:2]
    if w >= tw and h >= th:
        return a
    nw, nh = max(w, tw), max(h, th)
    out = np.full((nh, nw) + a.shape[2:], fill, np.uint8)
    out[(nh - h) // 2:(nh - h) // 2 + h,
        (nw - w) // 2:(nw - w) // 2 + w] = a
    return out


def _crop_frame(img, top: int, left: int, th: int, tw: int):
    """One frame crop: zero-copy slice for arrays, ``Image.crop`` for PIL
    (identical bytes — both are pure windowing on in-bounds coords)."""
    if isinstance(img, np.ndarray):
        return img[top:top + th, left:left + tw]
    return img.crop((left, top, left + tw, top + th))


class RandomCrop:
    """Random crop with ``pad_if_needed`` (torchvision semantics used by the
    reference at transforms.py:311-330)."""

    def __init__(self, size: Union[int, Tuple[int, int]],
                 pad_if_needed: bool = False, fill: int = 0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def get_params(self, img, rng: np.random.Generator) -> Tuple[int, int]:
        th, tw = self.size
        w, h = _wh(img)
        top = int(rng.integers(0, h - th + 1)) if h > th else 0
        left = int(rng.integers(0, w - tw + 1)) if w > tw else 0
        return top, left

    def __call__(self, img, rng: np.random.Generator):
        if self.pad_if_needed:
            img = _pad_to(img, self.size[1], self.size[0], self.fill)
        top, left = self.get_params(img, rng)
        th, tw = self.size
        return _crop_frame(img, top, left, th, tw)


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng: np.random.Generator):
        if rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class RandomVerticalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng: np.random.Generator):
        if rng.random() < self.p:
            return img.transpose(Image.FLIP_TOP_BOTTOM)
        return img


class RandomResizedCropAndInterpolation:
    """Random scale/aspect crop then resize (reference transforms.py:73-170):
    10 area/ratio attempts, fallback to a center crop at the clamped ratio."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation: str = "bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        if interpolation == "random":
            self.interpolation: Any = _RANDOM_INTERPOLATION
        else:
            self.interpolation = pil_interp(interpolation)

    def get_params(self, img, rng: np.random.Generator):
        w, h = img.size
        area = w * h
        for _ in range(10):
            target_area = rng.uniform(*self.scale) * area
            log_ratio = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            aspect_ratio = math.exp(rng.uniform(*log_ratio))
            cw = int(round(math.sqrt(target_area * aspect_ratio)))
            ch = int(round(math.sqrt(target_area / aspect_ratio)))
            if cw <= w and ch <= h:
                top = int(rng.integers(0, h - ch + 1))
                left = int(rng.integers(0, w - cw + 1))
                return top, left, ch, cw
        # fallback: center crop at clamped aspect
        in_ratio = w / h
        if in_ratio < min(self.ratio):
            cw = w
            ch = int(round(cw / min(self.ratio)))
        elif in_ratio > max(self.ratio):
            ch = h
            cw = int(round(ch * max(self.ratio)))
        else:
            cw, ch = w, h
        top = (h - ch) // 2
        left = (w - cw) // 2
        return top, left, ch, cw

    def __call__(self, img, rng: np.random.Generator):
        top, left, ch, cw = self.get_params(img, rng)
        interp = _resolve_interp(self.interpolation, rng)
        img = img.crop((left, top, left + cw, top + ch))
        return img.resize((self.size[1], self.size[0]), interp)


class RandomResize:
    """Uniform random rescale (reference transforms.py:173-211)."""

    def __init__(self, scale=(0.9, 1.1), interpolation: str = "bilinear"):
        if interpolation == "random":
            self.interpolation: Any = _RANDOM_INTERPOLATION
        else:
            self.interpolation = pil_interp(interpolation)
        self.scale = scale

    def _target_size(self, img, rng: np.random.Generator) -> Tuple[int, int]:
        s = rng.uniform(self.scale[0], self.scale[1])
        w, h = _wh(img)
        return int(w * s), int(h * s)

    def __call__(self, img, rng: np.random.Generator):
        interp = _resolve_interp(self.interpolation, rng)
        tw, th = self._target_size(img, rng)
        return img.resize((tw, th), interp)


class ColorJitter:
    """Brightness/contrast/saturation/hue jitter, applied in a shuffled order
    with shared factors (torchvision semantics the reference relies on at
    transforms.py:332-343)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        self.brightness = self._range(brightness)
        self.contrast = self._range(contrast)
        self.saturation = self._range(saturation)
        self.hue = (-hue, hue) if not isinstance(hue, (tuple, list)) else tuple(hue)

    @staticmethod
    def _range(v):
        if isinstance(v, (tuple, list)):
            return tuple(v)
        return (max(0.0, 1.0 - v), 1.0 + v)

    def get_params(self, rng: np.random.Generator):
        order = rng.permutation(4)
        b = rng.uniform(*self.brightness) if self.brightness != (1.0, 1.0) else None
        c = rng.uniform(*self.contrast) if self.contrast != (1.0, 1.0) else None
        s = rng.uniform(*self.saturation) if self.saturation != (1.0, 1.0) else None
        h = rng.uniform(*self.hue) if self.hue != (0.0, 0.0) else None
        return order, b, c, s, h

    @staticmethod
    def _apply(img, order, b, c, s, h):
        for idx in order:
            if idx == 0 and b is not None:
                img = ImageEnhance.Brightness(img).enhance(b)
            elif idx == 1 and c is not None:
                img = ImageEnhance.Contrast(img).enhance(c)
            elif idx == 2 and s is not None:
                img = ImageEnhance.Color(img).enhance(s)
            elif idx == 3 and h is not None:
                hsv = np.array(img.convert("HSV"), dtype=np.int16)
                hsv[..., 0] = (hsv[..., 0] + int(h * 255)) % 256
                img = Image.fromarray(hsv.astype(np.uint8), "HSV").convert("RGB")
        return img

    def __call__(self, img, rng: np.random.Generator):
        return self._apply(img, *self.get_params(rng))


# ---------------------------------------------------------------------------
# Multi-frame (clip) transforms — shared random params across frames
# ---------------------------------------------------------------------------

class PackedFrames(list):
    """Frame views into ONE pre-packed (H, W, 3·F) uint8 buffer.

    The native warp writes every frame's channel slice directly into the
    packed buffer (strided dst), so if no downstream transform replaced a
    frame, MultiConcate can return ``base`` with zero copies.  Any
    replaced item (a blurred PIL frame, a jittered copy) voids the
    shortcut and the normal concatenate runs."""

    def __init__(self, views, base: np.ndarray):
        super().__init__(views)
        self.base = base
        self._orig = tuple(views)

    def untouched(self) -> bool:
        return len(self) == len(self._orig) and all(
            a is b for a, b in zip(self, self._orig))


class MultiToNumpy:
    """List of PIL frames → list of (H, W, 3) uint8 arrays (NHWC)."""

    def __call__(self, pil_imgs, rng=None) -> List[np.ndarray]:
        if isinstance(pil_imgs, PackedFrames) and pil_imgs.untouched():
            return pil_imgs                 # already uint8 ndarray views
        out = []
        for pil_img in pil_imgs:
            a = np.asarray(pil_img, dtype=np.uint8)
            if a.ndim < 3:
                a = np.expand_dims(a, axis=-1)
            out.append(a)
        return out


class MultiConcate:
    """Concatenate frames on the channel axis → (H, W, 3*img_num)."""

    def __call__(self, np_imgs, rng=None) -> np.ndarray:
        if isinstance(np_imgs, PackedFrames) and np_imgs.untouched():
            return np_imgs.base             # frames pre-packed by the warp
        return np.concatenate(np_imgs, axis=-1)


class MultiRandomHorizontalFlip:
    """One coin flip shared by all frames (reference :217-240)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, imgs, rng: np.random.Generator):
        if rng.random() < self.p:
            return [_as_pil(img).transpose(Image.FLIP_LEFT_RIGHT)
                    for img in imgs]
        return imgs


class MultiRotate:
    """One integer angle in ±rotate_range shared by all frames, expand=True
    (reference :261-278 — note ``expand`` changes the canvas size; the fixed
    crop downstream restores static shapes)."""

    def __init__(self, rotate_range: float):
        self.rotate_range = int(rotate_range)

    def __call__(self, imgs, rng: np.random.Generator):
        deg = int(rng.integers(-self.rotate_range, self.rotate_range + 1))
        return [_as_pil(img).rotate(deg, expand=True) for img in imgs]


class MultiRandomResize(RandomResize):
    """One random scale shared by all frames (reference :281-308)."""

    def __call__(self, imgs, rng: np.random.Generator):
        interp = _resolve_interp(self.interpolation, rng)
        tw, th = self._target_size(imgs[0], rng)
        return [_as_pil(img).resize((tw, th), interp) for img in imgs]


def _crop_packed(imgs: "PackedFrames", top: int, left: int,
                 th: int, tw: int) -> "PackedFrames":
    """Window a packed clip by slicing its ONE base buffer: the result is
    again a PackedFrames whose views alias the (possibly mmap-backed)
    base, so MultiConcate stays copy-free on the packed-cache hot path."""
    nb = imgs.base[top:top + th, left:left + tw]
    n = nb.shape[-1] // 3
    return PackedFrames([nb[..., 3 * i:3 * i + 3] for i in range(n)], nb)


class MultiRandomCrop(RandomCrop):
    """One crop window shared by all frames, pad_if_needed (reference
    :311-330).  Packed/array frames (native warp output, mmap-backed
    packed-cache clips) crop as zero-copy base-buffer slices — same rng
    draw order and identical bytes as the PIL path."""

    def __call__(self, imgs, rng: np.random.Generator):
        packed = isinstance(imgs, PackedFrames) and imgs.untouched()
        if packed and self.pad_if_needed:
            base = _pad_to_np(imgs.base, self.size[1], self.size[0],
                              self.fill)
            if base is not imgs.base:
                n = base.shape[-1] // 3
                imgs = PackedFrames(
                    [base[..., 3 * i:3 * i + 3] for i in range(n)], base)
        elif self.pad_if_needed:
            imgs = [_pad_to(img, self.size[1], self.size[0], self.fill)
                    for img in imgs]
        top, left = self.get_params(imgs[0], rng)
        th, tw = self.size
        if packed:
            return _crop_packed(imgs, top, left, th, tw)
        return [_crop_frame(img, top, left, th, tw) for img in imgs]


class MultiCenterCrop(CenterCrop):
    """Deterministic center crop of every frame, pad_if_needed.

    No reference analog — the reference evaluates with a *random* crop
    (transforms_factory.py:225-236); this is the opt-in deterministic eval
    (``--eval-crop center``) for clean AUC comparisons across runs."""

    def __init__(self, size, fill: int = 0):
        super().__init__(size)
        self.fill = fill

    def __call__(self, imgs, rng=None):
        th, tw = self.size
        if isinstance(imgs, PackedFrames) and imgs.untouched():
            base = _pad_to_np(imgs.base, tw, th, self.fill)
            w, h = base.shape[1], base.shape[0]
            return _crop_packed(
                PackedFrames([base[..., 3 * i:3 * i + 3]
                              for i in range(base.shape[-1] // 3)], base),
                int(round((h - th) / 2.0)), int(round((w - tw) / 2.0)),
                th, tw)
        imgs = [_pad_to(img, tw, th, self.fill) for img in imgs]
        return [CenterCrop.__call__(self, _as_pil(img)) for img in imgs]


class MultiColorJitter(ColorJitter):
    """One jitter parameter draw shared by all frames (reference :332-343)."""

    def __call__(self, imgs, rng: np.random.Generator):
        params = self.get_params(rng)
        return [self._apply(_as_pil(img), *params) for img in imgs]


def _rot_canvas(w: int, h: int, deg: float) -> Tuple[int, int]:
    """Canvas size of ``img.rotate(deg, expand=True)``, replicating
    PIL's computation exactly — including the center-offset constant
    INSIDE the ceil/floor, which shifts the result by 1 px for odd
    source extents (the crop-draw bounds must match the sequential
    chain exactly, not just approximately)."""
    # PIL's transpose fast paths keep exact sizes at right angles (its
    # general ceil/floor formula would pad odd extents by 1)
    deg_n = deg % 360
    if deg_n in (0, 180):
        return w, h
    if deg_n in (90, 270):
        return h, w
    a = -math.radians(deg)                     # PIL negates the angle
    # PIL rounds to 15 decimals so near-axis angles produce exact 0/±1
    # entries; raw cos/sin residue (~6e-17) would push corner coords
    # past ceil/floor boundaries
    c, s = round(math.cos(a), 15), round(math.sin(a), 15)
    cx, cy = w / 2.0, h / 2.0
    m2 = cx - (c * cx + s * cy)
    m5 = cy - (-s * cx + c * cy)
    xs, ys = [], []
    for x, y in ((0, 0), (w, 0), (w, h), (0, h)):
        xs.append(c * x + s * y + m2)
        ys.append(-s * x + c * y + m5)
    nw = int(math.ceil(max(xs)) - math.floor(min(xs)))
    nh = int(math.ceil(max(ys)) - math.floor(min(ys)))
    return nw, nh


def fused_geometric_params(w: int, h: int, size: Tuple[int, int],
                           rotate_range: int, scale: Tuple[float, float],
                           p_flip: float, rng: np.random.Generator
                           ) -> Tuple[float, float, float,
                                      float, float, float]:
    """Draw the fused-geometric chain's parameters and compose the
    output→source INDEX-space affine ``(A, B, C, D, E, F)``.

    Exactly the draw order and conditionals of the sequential
    MultiRotate(expand) / MultiRandomHorizontalFlip / MultiRandomResize /
    MultiRandomCrop chain (angle, coin, scale, top, left), so callers
    that only need the rng stream position — the device-augment host
    passthrough — consume the identical draws the render path would.
    Shared by :class:`MultiFusedGeometric` (host render, native or PIL)
    and the device-side warp (``data/device_augment.py``), which is what
    pins the two paths to one parameter distribution by construction.
    """
    th, tw = size
    # identical draw order to the sequential chain
    deg = (int(rng.integers(-rotate_range, rotate_range + 1))
           if rotate_range else 0)
    flip = rng.random() < p_flip
    s = rng.uniform(scale[0], scale[1])
    w1, h1 = _rot_canvas(w, h, deg) if deg else (w, h)
    w2, h2 = int(w1 * s), int(h1 * s)          # RandomResize rounding
    ww, hh = max(w2, tw), max(h2, th)          # pad_if_needed canvas
    px, py = (ww - w2) // 2, (hh - h2) // 2    # center pad offsets
    top = int(rng.integers(0, hh - th + 1)) if hh > th else 0
    left = int(rng.integers(0, ww - tw + 1)) if ww > tw else 0

    # output (x, y) → source (original frame) coords, composed right to
    # left: crop/pad shift → inverse resize → inverse flip → inverse
    # rotate.  All half-pixel center corrections fold into the constant
    # terms.
    a = math.radians(deg)
    cos, sin = math.cos(a), math.sin(a)

    # crop+pad: xp = x + left - px (coords in the resized image)
    # resize:   xr = (xp + .5) * (w1 / w2) - .5
    sx, sy = w1 / w2, h1 / h2
    # flip (on the rotated canvas): xf = w1 - 1 - xr
    # linear parts
    ax, bx = sx, 0.0
    cx = (left - px + 0.5) * sx - 0.5
    dy, ey = 0.0, sy
    fy = (top - py + 0.5) * sy - 0.5
    if flip:
        ax, bx, cx = -ax, -bx, (w1 - 1) - cx
    # rotate inverse (verified against PIL.rotate numerically): output→
    # input is xi = cos·dx - sin·dy + w/2, yi = sin·dx + cos·dy + h/2
    # with dx = xr - w1/2 + .5 etc. (half-pixel center corrections)
    cos, sin = round(cos, 15), round(sin, 15)  # PIL's axis-angle exactness
    A = cos * ax - sin * dy
    B = cos * bx - sin * ey
    C = (cos * (cx - w1 / 2 + 0.5) - sin * (fy - h1 / 2 + 0.5)
         + w / 2 - 0.5)
    D = sin * ax + cos * dy
    E = sin * bx + cos * ey
    F = (sin * (cx - w1 / 2 + 0.5) + cos * (fy - h1 / 2 + 0.5)
         + h / 2 - 0.5)
    return (A, B, C, D, E, F)


def blur_mask_draws(n: int, p: float, rng: np.random.Generator) -> List[bool]:
    """Per-frame blur coin flips in :class:`MultiBlur`'s draw order (one
    ``rng.random()`` per frame, frame-major) — the shared draw for the
    host blur stage and the device-augment blur mask."""
    return [rng.random() < p for _ in range(n)]


class MultiFusedGeometric:
    """rotate → hflip → random-resize → pad-if-needed → random-crop as ONE
    affine resample per frame.

    Numerically composes the exact parameter draws of the sequential
    MultiRotate(expand) / MultiRandomHorizontalFlip / MultiRandomResize /
    MultiRandomCrop chain (same rng call order: angle, coin, scale, top,
    left — so the augmentation *distribution* is identical), then renders
    the 600² output directly with ``Image.transform(AFFINE)``.  The
    sequential chain resamples every frame three times at full canvas size
    (~43 ms/clip at 720² source); this touches each output pixel once
    (~15 ms/clip) — the host-side decode pipeline must outrun the chip
    (SURVEY §7 hard part #4), and the three-pass chain was its biggest
    term.  Pixel values differ from the sequential chain only by resampling
    (one bilinear pass instead of nearest-rotate + bilinear-resize + copy);
    ``transforms_deepfake_train_v3(fused_geom=False)`` restores the
    reference-exact chain.
    """

    def __init__(self, size, rotate_range: float = 0,
                 scale=(2.0 / 3, 3.0 / 2.0), p_flip: float = 0.5,
                 fill: int = 0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.rotate_range = int(rotate_range)
        self.scale = scale
        self.p_flip = p_flip
        self.fill = fill

    # kept as a staticmethod alias for external callers; the computation
    # lives at module level so fused_geometric_params can share it
    _rot_canvas = staticmethod(_rot_canvas)

    def __call__(self, imgs, rng: np.random.Generator):
        th, tw = self.size
        w, h = _wh(imgs[0])
        coeffs = fused_geometric_params(
            w, h, self.size, self.rotate_range, self.scale, self.p_flip, rng)
        A, B, C, D, E, F = coeffs
        from . import native
        if native.available():
            arrs = [np.asarray(im, np.uint8) if not isinstance(
                im, np.ndarray) else im for im in imgs]
            base = native.warp_affine_batch(arrs, coeffs, (tw, th),
                                            packed=True)
            if base is not None:
                # channel-slice views; MultiConcate returns base copy-free
                # if no later transform replaces a frame
                n = len(imgs)
                return PackedFrames(
                    [base[..., 3 * i:3 * i + 3] for i in range(n)], base)
        # coeffs are an INDEX-space map (output pixel index → source pixel
        # index, the native kernel's convention); PIL's Image.transform
        # maps continuous coordinates, which shifts the constant terms by
        # (A+B)/2 − ½ — up to a FULL pixel under a flip (A = −1/s).
        # Unconverted, the fallback silently disagreed with the native
        # path; tests only caught it once they ran this branch explicitly
        # (DFD_NO_NATIVE_DECODE=1 parametrization)
        pil_coeffs = (A, B, C - (A + B) / 2 + 0.5,
                      D, E, F - (D + E) / 2 + 0.5)
        return [_as_pil(img).transform((tw, th), Image.AFFINE, pil_coeffs,
                                       resample=Image.BILINEAR,
                                       fillcolor=(self.fill,) * 3)
                for img in imgs]


def _as_pil(img) -> Image.Image:
    """Frames may be PIL or uint8 ndarray (the native fused-geometric path
    emits arrays); lift to PIL only where a PIL op is actually applied."""
    return Image.fromarray(img) if isinstance(img, np.ndarray) else img


class MultiBlur:
    """Independent per-frame Gaussian blur with probability p (reference
    :243-258 — deliberately *not* shared across frames).

    ``blur_radiu`` (the reference's misspelling) is accepted as a
    deprecated alias for ``blur_radius`` so existing configs keep
    working; it maps to the same attribute.
    """

    def __init__(self, p: float, blur_radius: Optional[float] = None,
                 blur_radiu: Optional[float] = None):
        self.p = p
        if blur_radius is None and blur_radiu is not None:
            import warnings
            warnings.warn("MultiBlur(blur_radiu=...) is deprecated; use "
                          "blur_radius", DeprecationWarning, stacklevel=2)
            blur_radius = blur_radiu
        self.blur_radius = 1.0 if blur_radius is None else blur_radius

    @property
    def blur_radiu(self) -> float:          # deprecated attribute alias
        return self.blur_radius

    def __call__(self, imgs, rng: np.random.Generator):
        mask = blur_mask_draws(len(imgs), self.p, rng)
        out = [_as_pil(img).filter(
                   ImageFilter.GaussianBlur(radius=self.blur_radius))
               if fire else img for img, fire in zip(imgs, mask)]
        if isinstance(imgs, PackedFrames) and all(
                a is b for a, b in zip(out, imgs)):
            return imgs         # keep the copy-free packed fast path alive
        return out


class DeviceAugmentPassthrough:
    """Host half of ``--augment-device on``: ship the RAW source clip.

    Replaces the geometric-warp + blur stages of the train chain with a
    raw passthrough — the clip leaves the host as one ``(H, W, 3·F)``
    uint8 buffer (for packed-cache clips the mmap view itself, so the
    only host work left is the collate/slab memcpy) and the DeviceLoader
    re-derives the SAME parameters from ``(seed, epoch, index)`` and
    renders warp/blur/mixup inside its jitted prologue
    (``data/device_augment.py``).

    Stream-position parity is the load-bearing part: this transform
    **consumes exactly the rng draws the host chain would** (geometric
    angle/coin/scale/top/left via :func:`fused_geometric_params`, one
    blur coin per frame via :func:`blur_mask_draws`), so every later
    per-sample draw — ``noise_fake`` label flipping, any future
    transform — sees the identical stream whether augmentation runs on
    host or device.

    Device augmentation needs a uniform source geometry across the
    dataset (one static warp shape per compile): the packed cache
    guarantees it; decode-path frame trees must be pre-sized (a mixed
    clip raises here, never a silent mis-stack).
    """

    #: host stages whose per-sample work this passthrough elides (the
    #: geometric warp and, when enabled, blur; the mixup blend elision is
    #: counted by the DeviceLoader where the blend actually moves)
    def __init__(self, size, rotate_range: float = 0,
                 scale=(2.0 / 3, 3.0 / 2.0), p_flip: float = 0.5,
                 blur_prob: float = 0.0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.rotate_range = int(rotate_range)
        self.scale = scale
        self.p_flip = p_flip
        self.blur_prob = blur_prob
        self.elided_stages = 1 + (1 if blur_prob > 0.0 else 0)

    def __call__(self, imgs, rng: np.random.Generator):
        w, h = _wh(imgs[0])
        # consume the chain's draws; the DeviceLoader re-derives them
        fused_geometric_params(w, h, self.size, self.rotate_range,
                               self.scale, self.p_flip, rng)
        if self.blur_prob > 0.0:
            blur_mask_draws(len(imgs), self.blur_prob, rng)
        if isinstance(imgs, PackedFrames) and imgs.untouched():
            return imgs.base            # mmap view: collate = one memcpy
        arrs = [np.asarray(im, np.uint8) if isinstance(im, np.ndarray)
                else np.asarray(_as_pil(im), np.uint8) for im in imgs]
        if len({a.shape for a in arrs}) > 1:
            raise ValueError(
                "--augment-device needs a uniform source frame geometry "
                f"(one static warp shape); got {[a.shape for a in arrs]} "
                "within one clip — pack the dataset (tools/pack_dataset.py) "
                "or pre-size the frames")
        return np.concatenate(arrs, axis=-1)


class MultiFlicker:
    """Random frame blackout — temporal-inconsistency augmentation
    (reference :346-350): each frame independently replaced by a black image
    with probability p."""

    def __init__(self, probability: float):
        self.probability = probability

    def __call__(self, imgs, rng: np.random.Generator):
        def black(img):
            if isinstance(img, np.ndarray):
                return np.zeros_like(img)
            return Image.new("RGB", img.size)
        out = [black(img) if rng.random() < self.probability
               else img for img in imgs]
        if isinstance(imgs, PackedFrames) and all(
                a is b for a, b in zip(out, imgs)):
            return imgs         # keep the copy-free packed fast path alive
        return out
