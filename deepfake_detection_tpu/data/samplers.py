"""Sharded samplers — deterministic index-space sharding across hosts.

Replaces the reference's ``torch.utils.data.distributed.DistributedSampler``
(train) and ``OrderedDistributedSampler`` (eval,
``/root/reference/dfd/timm/data/distributed_sampler.py:7-51``).  On TPU one
*process per host* feeds all local devices, so the shard unit is
``jax.process_index()`` rather than one process per accelerator; the index
arithmetic is identical.

Static shapes rule everything (SURVEY.md §7 "hard parts" #5): both samplers
pad the index list to an exact multiple of ``num_shards * batch_size``.  The
eval sampler additionally reports a per-index validity flag so padded
duplicates can be masked out of the metrics — the reference instead lets the
duplicates "slightly alter validation results" (loader.py:794-796); with the
mask we are exact.
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["ShardedTrainSampler", "OrderedShardedSampler", "epoch_batches"]


def epoch_batches(sampler, batch_size: int, valid_mask: bool = False
                  ) -> Tuple[List[List[int]], Optional[List[np.ndarray]]]:
    """Split one epoch of ``sampler`` into full batches.

    Shared front half of every host loader backend (thread pool and shm
    ring), so both iterate the exact same ``(epoch, batch_index) → indices``
    mapping.  Returns ``(batches, valid)``: ``batches`` is a list of
    per-batch index lists (trailing partial batch dropped — samplers pad to
    a batch multiple, see module docstring), ``valid`` is a matching list of
    per-batch bool masks when ``valid_mask`` is set and the sampler reports
    padding validity, else None.
    """
    indices = list(iter(sampler))
    valid = None
    if valid_mask and hasattr(sampler, "local_indices"):
        out = sampler.local_indices()
        if isinstance(out, tuple):
            indices, valid = out[0].tolist(), out[1]
    nb = len(indices) // batch_size
    batches = [indices[i * batch_size:(i + 1) * batch_size]
               for i in range(nb)]
    vms = None if valid is None else \
        [np.asarray(valid[i * batch_size:(i + 1) * batch_size])
         for i in range(nb)]
    return batches, vms


class ShardedTrainSampler:
    """Shuffling train sampler: seeded per-epoch permutation, wrap-padded to a
    multiple of ``num_shards * batch_size``, strided subsample per shard.

    Every shard sees the same permutation, so the global batch order is a
    pure function of ``(seed, epoch)`` regardless of host count.
    """

    def __init__(self, dataset_len: int, num_shards: int = 1,
                 shard_index: int = 0, batch_size: int = 1, seed: int = 42,
                 drop_last: bool = True):
        assert 0 <= shard_index < num_shards
        self.dataset_len = dataset_len
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.batch_size = batch_size
        self.seed = seed
        self.epoch = 0
        chunk = num_shards * batch_size
        if drop_last:
            self.total_size = (dataset_len // chunk) * chunk
        else:
            self.total_size = int(math.ceil(dataset_len / chunk)) * chunk
        self.num_samples = self.total_size // num_shards

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def local_indices(self) -> np.ndarray:
        perm = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.epoch])
        ).permutation(self.dataset_len)
        if self.total_size <= self.dataset_len:
            perm = perm[:self.total_size]
        else:
            reps = int(math.ceil(self.total_size / self.dataset_len))
            perm = np.tile(perm, reps)[:self.total_size]
        return perm[self.shard_index::self.num_shards]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples


class OrderedShardedSampler:
    """Non-shuffling eval sampler with wrap-padding and validity flags
    (reference distributed_sampler.py:37-48 plus exact-eval masking)."""

    def __init__(self, dataset_len: int, num_shards: int = 1,
                 shard_index: int = 0, batch_size: int = 1):
        assert 0 <= shard_index < num_shards
        self.dataset_len = dataset_len
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.batch_size = batch_size
        chunk = num_shards * batch_size
        self.total_size = int(math.ceil(dataset_len / chunk)) * chunk
        self.num_samples = self.total_size // num_shards

    def set_epoch(self, epoch: int) -> None:  # interface parity
        pass

    def local_indices(self) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, valid) for this shard; padding wraps from index 0 and is
        flagged invalid."""
        idx = np.arange(self.total_size)
        valid = idx < self.dataset_len
        idx = idx % self.dataset_len
        sl = slice(self.shard_index, self.total_size, self.num_shards)
        return idx[sl], valid[sl]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices()[0].tolist())

    def __len__(self) -> int:
        return self.num_samples
