"""Convolution factory: plain / depthwise / mixed / conditional.

Replaces ``layers/create_conv2d.py`` (:11), ``layers/conv2d_same.py``,
``layers/mixed_conv2d.py`` (:20) and ``layers/cond_conv2d.py`` (:83-121).

TPU notes:
* Padding carries checkpoint-parity semantics (see :func:`resolve_padding`):
  pad_type ``''`` (non-tf families) is the reference's STATIC symmetric
  torch padding, expressed as an explicit XLA padding config; pad_type
  ``'same'`` (tf_* variants) is TF SAME, which XLA implements natively — so
  only the *dynamic* ``Conv2dSame`` shim vanishes, not the static/dynamic
  distinction itself.  Both forms lower to one conv, no separate pad op.
* CondConv's per-sample expert mixing is an einsum + a vmapped conv; XLA
  lowers the vmap to one batched/grouped convolution on the MXU — same trick
  as the reference's grouped-conv reshape, minus the manual reshapes.

Layout is NHWC, kernels HWIO (XLA/TPU native).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _to_tuple(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def resolve_padding(padding: Union[str, int, None], kernel_size, dilation=1,
                    stride=1):
    """Map reference pad_type strings onto XLA padding specs.

    ``''`` (the non-tf families' default) → the reference's STATIC symmetric
    padding ``((s-1) + d*(k-1)) // 2`` per side (conv2d_same.py
    ``get_padding``).  This equals XLA 'SAME' at stride 1 (odd kernels) and
    at odd input sizes, but at even input + stride>1 torch pads both sides
    where SAME pads only the end — a one-pixel window-grid shift that
    breaks trained-checkpoint parity at the flagship's 600² (found by the
    trained-flagship conversion gate, round 5).

    ``'same'`` → XLA 'SAME' (true TF semantics — the tf_* variants' dynamic
    ``Conv2dSame`` shim is exactly this, natively).  ``'valid'`` → 'VALID';
    int → explicit symmetric.
    """
    if padding is None or padding == "":
        ks, dl, st = _to_tuple(kernel_size), _to_tuple(dilation), \
            _to_tuple(stride)
        return [(p, p) for p in
                (((s - 1) + d * (k - 1)) // 2 for k, d, s in zip(ks, dl, st))]
    if str(padding).lower() == "same":
        return "SAME"
    if str(padding).lower() == "valid":
        return "VALID"
    if isinstance(padding, int):
        return [(padding, padding), (padding, padding)]
    return padding


def conv_kernel_init_goog(key, shape, dtype=jnp.float32):
    """TF/EfficientNet conv init: N(0, sqrt(2/fan_out)), fan_out = kh*kw*out
    (efficientnet_builder.py:537-575)."""
    fan_out = shape[0] * shape[1] * shape[-1]
    return jax.random.normal(key, shape, dtype) * np.sqrt(2.0 / fan_out)


def dense_init_goog(key, shape, dtype=jnp.float32):
    """TF head init: U(-1/sqrt(out), 1/sqrt(out)) (efficientnet_builder.py:566-571)."""
    fan_out = shape[-1]
    init_range = 1.0 / np.sqrt(fan_out)
    return jax.random.uniform(key, shape, dtype, -init_range, init_range)


class Conv2d(nn.Module):
    """NHWC conv; depthwise via ``groups == in_chs`` like the reference factory."""
    out_chs: int
    kernel_size: Union[int, Tuple[int, int]] = 3
    stride: Union[int, Tuple[int, int]] = 1
    dilation: Union[int, Tuple[int, int]] = 1
    groups: int = 1
    padding: Union[str, int, None] = ""
    use_bias: bool = False
    kernel_init: Callable = conv_kernel_init_goog
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        ks = _to_tuple(self.kernel_size)
        return nn.Conv(
            features=self.out_chs,
            kernel_size=ks,
            strides=_to_tuple(self.stride),
            kernel_dilation=_to_tuple(self.dilation),
            feature_group_count=self.groups,
            padding=resolve_padding(self.padding, ks, self.dilation,
                                    self.stride),
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            dtype=self.dtype,
            name="conv",
        )(x)


class MixedConv2d(nn.Module):
    """Channel-split multi-kernel conv (MixNet; mixed_conv2d.py:20-50).

    Channels are split as equally as possible across kernel sizes (first split
    absorbs the remainder, matching the reference's np.array_split behavior).
    """
    out_chs: int
    kernel_size: Sequence[int] = (3, 5)
    stride: int = 1
    dilation: int = 1
    depthwise: bool = False
    padding: Union[str, int, None] = ""
    use_bias: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        in_chs = x.shape[-1]
        n = len(self.kernel_size)
        in_splits = np.array_split(np.arange(in_chs), n)
        out_sizes = [len(a) for a in np.array_split(np.arange(self.out_chs), n)]
        outs = []
        start = 0
        for i, (ks, idx, out_c) in enumerate(zip(self.kernel_size, in_splits, out_sizes)):
            chunk = x[..., start:start + len(idx)]
            start += len(idx)
            # depthwise grouping derives from the INPUT split: groups must
            # equal the split's input channels (flax maps groups onto
            # feature_group_count, whose contract is per-input-channel).
            # Deriving it from out_c silently mis-grouped any depthwise
            # mixed conv whose split had in != out.
            if self.depthwise and len(idx) != out_c:
                raise ValueError(
                    f"MixedConv2d depthwise split {i}: input split has "
                    f"{len(idx)} channels but the output split has {out_c} "
                    f"— depthwise requires in == out per split "
                    f"(in_chs={in_chs}, out_chs={self.out_chs}, "
                    f"kernels={tuple(self.kernel_size)})")
            groups = len(idx) if self.depthwise else 1
            outs.append(Conv2d(out_c, ks, self.stride, self.dilation,
                               groups=groups, padding=self.padding,
                               use_bias=self.use_bias, dtype=self.dtype,
                               name=f"conv_{i}")(chunk))
        return jnp.concatenate(outs, axis=-1)


class CondConv2d(nn.Module):
    """Conditionally-parameterized conv (cond_conv2d.py:83-121).

    Holds ``num_experts`` kernels; ``__call__`` takes per-sample routing
    weights (B, E), mixes kernels with an einsum, then applies one conv per
    sample via vmap (XLA batches it onto the MXU).
    """
    out_chs: int
    kernel_size: Union[int, Tuple[int, int]] = 3
    stride: int = 1
    dilation: int = 1
    groups: int = 1
    num_experts: int = 4
    padding: Union[str, int, None] = ""
    use_bias: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, x, routing_weights):
        kh, kw = _to_tuple(self.kernel_size)
        in_chs = x.shape[-1]
        kshape = (kh, kw, in_chs // self.groups, self.out_chs)

        def expert_init(key, shape, dtype=jnp.float32):
            # per-expert goog init on the underlying kernel shape
            # (cond_conv2d.py:20-31 get_condconv_initializer)
            keys = jax.random.split(key, shape[0])
            return jnp.stack([conv_kernel_init_goog(k, shape[1:], dtype)
                              for k in keys])

        weight = self.param("weight", expert_init,
                            (self.num_experts,) + kshape)
        # per-sample kernel: (B, kh, kw, cin/g, cout)
        mixed = jnp.einsum("be,ehwio->bhwio",
                           routing_weights.astype(weight.dtype), weight)
        pad = resolve_padding(self.padding, (kh, kw), self.dilation,
                              self.stride)
        dn = jax.lax.conv_dimension_numbers(
            (1,) + x.shape[1:], kshape, ("NHWC", "HWIO", "NHWC"))

        def one(xi, ki):
            return jax.lax.conv_general_dilated(
                xi[None], ki, window_strides=_to_tuple(self.stride),
                padding=pad, rhs_dilation=_to_tuple(self.dilation),
                dimension_numbers=dn, feature_group_count=self.groups)[0]

        y = jax.vmap(one)(x.astype(mixed.dtype), mixed)
        if self.use_bias:
            bias = self.param("bias", lambda k, s: jnp.zeros(s),
                              (self.num_experts, self.out_chs))
            y = y + jnp.einsum("be,eo->bo", routing_weights, bias)[:, None, None, :]
        return y


# ---------------------------------------------------------------------------
# Space-to-depth stem rewrite (MLPerf TPU-pod ResNet trick, Kumar et al. 2019)
# ---------------------------------------------------------------------------

def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC pixel-shuffle: ``(B, H, W, C) → (B, H/b, W/b, b²·C)``.

    Channel layout is ``(di, dj, c)``-major — the layout
    :func:`space_to_depth_stem_kernel` assumes.  Pure reshape/transpose: XLA
    lowers it to a copy (loader prologue) or fuses it (in-model fallback).
    """
    b, h, w, c = x.shape
    assert h % block == 0 and w % block == 0, \
        f"space_to_depth needs H, W divisible by {block}, got {(h, w)}"
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c)


def depth_to_space(x, block: int = 2):
    """Inverse of :func:`space_to_depth` (same ``(di, dj, c)``-major channel
    layout); works on jax or numpy arrays."""
    b, h, w, c = x.shape
    assert c % (block * block) == 0, \
        f"depth_to_space needs C divisible by {block * block}, got {c}"
    x = x.reshape(b, h, w, block, block, c // (block * block))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h * block, w * block, c // (block * block))


def space_to_depth_stem_kernel(kernel: jnp.ndarray, pad_type: str = ""):
    """Rewrite a 3×3 stride-2 stem kernel for space-to-depth input.

    ``kernel`` is HWIO ``(3, 3, C, O)``; returns ``(k2, pad)`` where ``k2``
    is the ``(2, 2, 4C, O)`` stride-1 kernel over the s2d input and ``pad``
    the matching block-space padding config.  The rewrite embeds the 3×3
    taps into a zero 4×4 at the offset the original padding dictates (torch
    static-symmetric ``''`` pads 1 low → offset 1 + block-pad (1, 0); TF
    ``'same'`` at even input pads 1 high → offset 0 + block-pad (0, 1)), then
    regroups the 4×4 into 2×2 pixel blocks.  A pure, lossless, invertible
    scatter of the original weights: converted torch checkpoints keep their
    exact values, only the conv's window arithmetic changes (the conv output
    differs from the stride-2 original by float reassociation only — the
    taps and products are identical).
    """
    kh, kw, cin, cout = kernel.shape
    if (kh, kw) != (3, 3):
        raise ValueError(
            f"s2d stem rewrite covers the 3x3 stride-2 stem, got {(kh, kw)}")
    if str(pad_type).lower() == "same":
        off, pad = 0, (0, 1)
    elif pad_type in ("", None):
        off, pad = 1, (1, 0)
    else:
        raise ValueError(
            f"s2d stem supports pad_type ''|'same', got {pad_type!r}")
    k4 = jnp.zeros((4, 4, cin, cout), kernel.dtype)
    k4 = k4.at[off:off + 3, off:off + 3].set(kernel)
    k2 = k4.reshape(2, 2, 2, 2, cin, cout).transpose(0, 2, 1, 3, 4, 5)
    return k2.reshape(2, 2, 4 * cin, cout), [pad, pad]


def create_conv2d(out_chs: int, kernel_size, **kwargs) -> nn.Module:
    """Dispatch like the reference factory (create_conv2d.py:11-30):
    list kernel → MixedConv2d, num_experts>0 → CondConv2d, else Conv2d;
    depthwise=True maps to groups=out_chs."""
    if isinstance(kernel_size, (list, tuple)) and len(kernel_size) > 1:
        depthwise = kwargs.pop("depthwise", False)
        kwargs.pop("groups", None)
        return MixedConv2d(out_chs, kernel_size, depthwise=depthwise, **kwargs)
    if isinstance(kernel_size, (list, tuple)):
        kernel_size = kernel_size[0]
    depthwise = kwargs.pop("depthwise", False)
    if depthwise:
        kwargs["groups"] = out_chs
    if kwargs.pop("num_experts", 0):
        raise ValueError("use CondConv2d directly; it needs routing weights")
    return Conv2d(out_chs, kernel_size, **kwargs)
