"""Normalization layers.

TPU-native replacements for the reference's BN stack:

* ``BatchNorm2d`` — wraps ``flax.linen.BatchNorm``; accepts **torch-convention
  momentum** (running = (1-m)*running + m*batch, default 0.1; the canonical
  deepfake run uses ``--bn-momentum 0.001``) and converts to flax convention.
  Passing ``axis_name`` turns it into cross-replica (sync) BN — the one-liner
  that replaces both apex ``convert_syncbn_model`` (train.py:388-400) *and* the
  epoch-boundary ``distribute_bn`` broadcast/reduce (utils.py:263-274), because
  batch stats are then always computed over the global batch.
* ``SplitBatchNorm2d`` — AdvProp auxiliary BN (layers/split_batchnorm.py:18-38):
  first 1/N of the batch through the main BN, remaining chunks through aux BNs.
* ``GroupNorm`` re-export for norm-free/group-norm model variants.
* ``local_stats_scope`` — the GSPMD expression of the shard_map-era
  "local BN" (ISSUE 12): inside the scope, TRAINING batch statistics are
  computed per contiguous batch *group* (one group per data-parallel mesh
  slot, pinned there by a ``with_sharding_constraint``), so under plain
  ``jax.jit`` each device normalizes with its own shard's statistics — no
  per-layer cross-device collectives in the forward — and the running
  stats are updated with the group-mean, exactly what the old shard_map
  body's per-device update + ``lax.pmean`` produced.  The scope is
  TRACE-time state (entered by the train step's body while jit traces),
  so eval and init never see it and no model-construction plumbing is
  needed across the 25 model families.

Reference BN defaults: torch (momentum .1, eps 1e-5); TF-ported weights need
``BN_MOMENTUM_TF_DEFAULT=0.01`` / ``BN_EPS_TF_DEFAULT=1e-3``
(efficientnet_blocks.py:13-15).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

BN_MOMENTUM_TF_DEFAULT = 0.01
BN_EPS_TF_DEFAULT = 1e-3
BN_MOMENTUM_PT_DEFAULT = 0.1
BN_EPS_PT_DEFAULT = 1e-5


def resolve_bn_args(kwargs: dict) -> dict:
    """Fold bn_tf/bn_momentum/bn_eps kwargs into explicit momentum/eps
    (efficientnet_blocks.py:22-30); momentum stays torch-convention here."""
    bn_args = {}
    if kwargs.pop("bn_tf", False):
        bn_args = dict(momentum=BN_MOMENTUM_TF_DEFAULT, eps=BN_EPS_TF_DEFAULT)
    bn_momentum = kwargs.pop("bn_momentum", None)
    if bn_momentum is not None:
        bn_args["momentum"] = bn_momentum
    bn_eps = kwargs.pop("bn_eps", None)
    if bn_eps is not None:
        bn_args["eps"] = bn_eps
    return bn_args


_local_stats = threading.local()


class local_stats_scope:
    """Trace-time scope: BN training statistics per contiguous batch group.

    ``groups`` is the data-parallel extent of the mesh; ``sharding`` (a
    ``NamedSharding`` whose spec shards axis 0 over the batch axis) pins
    group ``g`` of the ``(groups, B/groups, ...)`` reshape onto mesh slot
    ``g`` so XLA computes every group's statistics locally.  Entered by
    ``make_train_step`` around the forward — i.e. while ``jax.jit`` traces
    — and therefore invisible to eval/init traces.  Reentrant per thread
    (a stack), matching nested tracing.
    """

    def __init__(self, groups: int, sharding: Any = None):
        self.groups = int(groups)
        self.sharding = sharding

    def __enter__(self):
        stack = getattr(_local_stats, "stack", None)
        if stack is None:
            stack = _local_stats.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _local_stats.stack.pop()
        return False


def _active_local_stats() -> Optional["local_stats_scope"]:
    stack = getattr(_local_stats, "stack", None)
    return stack[-1] if stack else None


def grouped_local_stats(x, groups: int, sharding: Any, dtype: Any = None):
    """The ONE implementation of the local-BN grouping semantics.

    Returns ``(xg, mean, var)``: ``xg`` is the ``(groups, B/groups, ...)``
    reshape pinned to ``sharding`` (one group per batch-axis mesh slot),
    ``mean``/``var`` are per-group statistics of shape ``(groups, C)``
    computed by flax's own ``_compute_stats`` (f32 promotion,
    ``max(0, E[x²]−E[x]²)`` clamp) — so every caller (the generic
    ``_LocalStatsBatchNorm`` and the fused-depthwise epilogue) shares the
    exact formula and the exact divisibility contract.
    """
    from flax.linen import normalization as _fnorm
    g = int(groups)
    b = x.shape[0]
    if b % g:
        raise ValueError(
            f"local-BN grouping: batch {b} not divisible by the "
            f"data-parallel extent {g} — pad the global batch to a "
            f"multiple of the mesh's batch axis")
    xg = x.reshape((g, b // g) + x.shape[1:])
    if sharding is not None:
        xg = jax.lax.with_sharding_constraint(xg, sharding)
    red = tuple(range(1, xg.ndim - 1))       # per-group stats → (g, C)
    mean, var = _fnorm._compute_stats(xg, red, dtype)
    return xg, mean, var


def grouped_running_update(ra_value, stat_g, momentum: float):
    """Running-stat update from per-group statistics (FLAX-convention
    ``momentum``): the group-mean update equals the shard_map era's
    per-device update followed by the step's one ``lax.pmean``."""
    return momentum * ra_value + (1.0 - momentum) * stat_g.mean(axis=0)


class _LocalStatsBatchNorm(nn.Module):
    """``flax.linen.BatchNorm``-compatible BN with per-group statistics.

    Declares the SAME variables (params ``scale``/``bias``, batch_stats
    ``mean``/``var``, float32, feature-shaped) and uses flax's own
    ``_compute_stats`` / ``_normalize`` kernels on a ``(groups, B/groups,
    ...)`` reshape — so the math per group is bit-for-bit the formula
    ``nn.BatchNorm`` applied per shard under the old shard_map body, and
    checkpoints are interchangeable between the paths.  ``momentum`` is
    FLAX convention here (running = m*running + (1-m)*batch).
    """
    groups: int = 1
    momentum: float = 0.9
    epsilon: float = BN_EPS_PT_DEFAULT
    use_scale: bool = True
    use_bias: bool = True
    dtype: Any = None
    scale_init: Any = nn.initializers.ones
    sharding: Any = None

    @nn.compact
    def __call__(self, x):
        from flax.linen import normalization as _fnorm
        feature_shape = (x.shape[-1],)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32),
                                feature_shape)
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32),
                               feature_shape)
        xg, mean, var = grouped_local_stats(x, self.groups, self.sharding,
                                            self.dtype)
        if not self.is_initializing():
            ra_mean.value = grouped_running_update(ra_mean.value, mean,
                                                   self.momentum)
            ra_var.value = grouped_running_update(ra_var.value, var,
                                                  self.momentum)
        red = tuple(range(1, xg.ndim - 1))
        y = _fnorm._normalize(
            self, xg, mean, var, red, (xg.ndim - 1,), self.dtype,
            jnp.float32, self.epsilon, self.use_bias, self.use_scale,
            nn.initializers.zeros, self.scale_init)
        if self.sharding is not None:
            y = jax.lax.with_sharding_constraint(y, self.sharding)
        return y.reshape(x.shape)


class BatchNorm2d(nn.Module):
    """NHWC batch norm with torch-style momentum and optional cross-replica sync.

    When ``axis_name`` is set (e.g. 'data' under shard_map/pjit with a named
    mesh axis), batch statistics are pmean-reduced across that axis — global-
    batch statistics, i.e. SyncBN.
    """
    momentum: float = BN_MOMENTUM_PT_DEFAULT   # torch convention
    eps: float = BN_EPS_PT_DEFAULT
    use_scale: bool = True
    use_bias: bool = True
    axis_name: Optional[str] = None
    dtype: Any = None
    scale_init: Any = None          # e.g. zeros for zero-init-last-BN blocks

    @nn.compact
    def __call__(self, x, training: bool = False):
        scope = _active_local_stats()
        if training and self.axis_name is None and scope is not None \
                and scope.groups > 1:
            # unified GSPMD local-BN path (ISSUE 12): same variable tree
            # under the same "bn" name, statistics per batch group
            return _LocalStatsBatchNorm(
                groups=scope.groups,
                sharding=scope.sharding,
                momentum=1.0 - self.momentum,
                epsilon=self.eps,
                use_scale=self.use_scale,
                use_bias=self.use_bias,
                dtype=self.dtype,
                scale_init=(self.scale_init if self.scale_init is not None
                            else nn.initializers.ones),
                name="bn")(x)
        kwargs = {}
        if self.scale_init is not None:
            kwargs["scale_init"] = self.scale_init
        return nn.BatchNorm(
            use_running_average=not training,
            momentum=1.0 - self.momentum,
            epsilon=self.eps,
            use_scale=self.use_scale,
            use_bias=self.use_bias,
            axis_name=self.axis_name,
            dtype=self.dtype,
            name="bn",
            **kwargs,
        )(x)


class SplitBatchNorm2d(nn.Module):
    """AdvProp split BN (layers/split_batchnorm.py:18-38).

    Training: batch is chunked into ``num_splits`` equal parts; chunk 0 uses
    the primary BN, chunk i uses aux BN i.  Eval: everything through primary.
    """
    num_splits: int = 2
    momentum: float = BN_MOMENTUM_PT_DEFAULT
    eps: float = BN_EPS_PT_DEFAULT
    axis_name: Optional[str] = None
    dtype: Any = None

    def setup(self):
        assert self.num_splits >= 2
        mk = lambda name: BatchNorm2d(momentum=self.momentum, eps=self.eps,
                                      axis_name=self.axis_name, dtype=self.dtype,
                                      name=name)
        self.main_bn = mk("main")
        self.aux_bns = [mk(f"aux{i}") for i in range(self.num_splits - 1)]

    def __call__(self, x, training: bool = False):
        if not training:
            return self.main_bn(x, training=False)
        split = x.shape[0] // self.num_splits
        assert split * self.num_splits == x.shape[0], \
            "batch size must be divisible by num_splits"
        parts = [self.main_bn(x[:split], training=True)]
        for i, bn in enumerate(self.aux_bns):
            parts.append(bn(x[(i + 1) * split:(i + 2) * split], training=True))
        return jnp.concatenate(parts, axis=0)


class GroupNorm(nn.Module):
    """GroupNorm for the norm-free deepfake variants (efficientnet.py:354-430)."""
    num_groups: int = 32
    eps: float = 1e-5
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        del training
        return nn.GroupNorm(num_groups=self.num_groups, epsilon=self.eps,
                            dtype=self.dtype, name="gn")(x)


class Identity(nn.Module):
    """No-op norm for use_norm=False paths (efficientnet.py:385)."""

    @nn.compact
    def __call__(self, x, training: bool = False):
        del training
        return x
