"""Pooling layers.

Replaces ``layers/adaptive_avgmax_pool.py`` (SelectAdaptivePool2d :70),
``layers/median_pool.py`` and ``layers/avg_pool2d_same.py``.  TF-"SAME"
average pooling is native XLA padding here — the reference's AvgPool2dSame
shim disappears.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def adaptive_pool_feat_mult(pool_type: str = "avg") -> int:
    """Output-channel multiplier: 2 for catavgmax else 1 (adaptive_avgmax_pool.py:63)."""
    return 2 if pool_type == "catavgmax" else 1


def global_pool_nhwc(x, pool_type: str = "avg"):
    """Global spatial pool NHWC → NC (adaptive_avgmax_pool.py:25-60 semantics)."""
    if not pool_type:
        return x
    avg = jnp.mean(x, axis=(1, 2))
    if pool_type == "avg":
        return avg
    mx = jnp.max(x, axis=(1, 2))
    if pool_type == "max":
        return mx
    if pool_type == "avgmax":
        return 0.5 * (avg + mx)
    if pool_type == "catavgmax":
        return jnp.concatenate([avg, mx], axis=-1)
    raise ValueError(f"Invalid pool type: {pool_type!r}")


class SelectAdaptivePool2d(nn.Module):
    """Selectable global pooling head (adaptive_avgmax_pool.py:70-101)."""
    pool_type: str = "avg"
    flatten: bool = True

    def feat_mult(self) -> int:
        return adaptive_pool_feat_mult(self.pool_type)

    @nn.compact
    def __call__(self, x):
        out = global_pool_nhwc(x, self.pool_type)
        if not self.flatten and out.ndim == 2:
            out = out[:, None, None, :]
        return out


def max_pool2d_torch(x, window: Tuple[int, int], strides: Tuple[int, int],
                     padding: int = 0, ceil_mode: bool = False):
    """torch ``nn.MaxPool2d`` semantics on NHWC (static shapes under jit).

    Symmetric ``padding`` on both sides; ``ceil_mode`` adds end padding so
    a final partial window is kept — torch's rule that a window may not
    *start* in the right padded region is applied.  XLA 'SAME' equals this
    only at odd input sizes; at even input + stride 2 the window grids
    differ by one pixel (same class of parity break as resolve_padding's
    static-symmetric case — found by the trained-flagship conversion gate,
    round 5).  ``nn.max_pool`` pads with -inf, matching torch's
    clip-to-valid semantics for max.
    """
    if not ceil_mode:
        # floor mode: flax's floor output formula already drops partial
        # windows, so plain symmetric padding is torch-exact
        p = ((padding, padding),) * 2
        return nn.max_pool(x, window, strides=strides, padding=p)
    pads = []
    outs = []
    for dim, k, s in zip(x.shape[1:3], window, strides):
        # torch's ceil_mode output count: ceil formula, then drop the last
        # window if it would START in the right padded region
        out = -((dim + 2 * padding - k) // -s) + 1
        if (out - 1) * s >= dim + padding:
            out -= 1
        outs.append(out)
        # end pad so flax's floor formula keeps exactly torch's windows; a
        # NEGATIVE required pad (reachable when stride > kernel interacts
        # with the decrement rule) cannot be expressed as padding — clamp
        # to 0 and slice the surplus trailing window(s) off below instead
        # of silently emitting one extra window (ADVICE.md)
        pads.append((padding, max(0, (out - 1) * s + k - dim - padding)))
    y = nn.max_pool(x, window, strides=strides, padding=pads)
    # both grids start windows at i*s - padding, so torch's output is
    # exactly the first outs[...] windows; a no-op slice in the common case
    return y[:, :outs[0], :outs[1], :]


def avg_pool2d_torch(x, window: Tuple[int, int], strides: Tuple[int, int],
                     padding: int = 0, count_include_pad: bool = True):
    """torch ``nn.AvgPool2d`` (floor mode) on NHWC: symmetric zero padding,
    pad zeros in the divisor when ``count_include_pad`` (torch's default).
    The res2net/dla downsample pools are ``AvgPool2d(3, stride, padding=1)``
    — at even input + stride 2 XLA 'SAME' shifts the window grid one pixel
    (the round-5 parity class)."""
    p = ((padding, padding),) * 2
    return nn.avg_pool(x, window, strides=strides, padding=p,
                       count_include_pad=count_include_pad)


def avg_pool2d_same(x, window: Tuple[int, int], strides: Tuple[int, int],
                    count_include_pad: bool = True):
    """TF-SAME average pool — XLA-native (replaces avg_pool2d_same.py:21)."""
    if count_include_pad:
        return nn.avg_pool(x, window, strides=strides, padding="SAME")
    ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
    summed = nn.avg_pool(x, window, strides=strides, padding="SAME")
    counts = nn.avg_pool(ones, window, strides=strides, padding="SAME")
    return summed / counts


def median_pool2d(x, kernel_size: int = 3, stride: int = 1,
                  padding: str = "SAME"):
    """Median filter (median_pool.py:8) via patch extraction + median.

    Patch extraction lowers to one strided conv-style gather; median is a sort
    over a small static axis — both XLA-friendly, no dynamic shapes.
    """
    B, H, W, C = x.shape
    k = kernel_size
    patches = jax.lax.conv_general_dilated_patches(
        jnp.moveaxis(x, -1, 1), (k, k), (stride, stride), padding,
    )  # (B, C*k*k, H', W')
    Ho, Wo = patches.shape[2], patches.shape[3]
    patches = patches.reshape(B, C, k * k, Ho, Wo)
    med = jnp.median(patches, axis=2)
    return jnp.moveaxis(med, 1, -1)


class MedianPool2d(nn.Module):
    kernel_size: int = 3
    stride: int = 1
    padding: str = "SAME"

    @nn.compact
    def __call__(self, x):
        return median_pool2d(x, self.kernel_size, self.stride, self.padding)
