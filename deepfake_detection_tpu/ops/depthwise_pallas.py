"""Fused depthwise-conv → scale/shift → activation as Pallas TPU kernels.

PERF.md's roofline puts the EfficientNet family within 1.5% of the bf16-VPU
ceiling: the depthwise stages are the binding term, and XLA executes each as
``dw-conv (VPU) → write HBM → read HBM → BN normalize → act → write HBM``
when the epilogue does not fuse cleanly (separate fusions around the conv).
This module collapses the whole stage into one VMEM-resident pass: the conv
accumulator never leaves VMEM between the k²-tap multiply-adds and the
per-channel affine + activation epilogue, so the stage's HBM traffic drops
to the unavoidable ``read x, write y``.

Kernel structure (same conventions as ``ops/flash_attention.py``):

* grid ``(B, C tiles, H tiles)`` with the H-tile axis innermost so Pallas
  pipelines one ``(th_in, W, Ct)`` input block at a time through VMEM.
  Depthwise halos (``th_in = th_out·stride + k − stride``) overlap between
  consecutive H tiles, which plain blocked BlockSpecs cannot express — the
  input spec uses **unblocked (element-offset) indexing** over an input the
  wrapper has already padded in XLA (one pad op; XLA materializes conv
  padding anyway).
* the k² taps unroll as static Python loops of strided ``lax.slice`` +
  multiply-accumulate on the VPU, f32 accumulation regardless of input
  dtype; the affine + activation epilogue runs on the accumulator while it
  is still VMEM-resident.
* backward is a custom VJP: ``dx`` REUSES the forward kernel (a depthwise
  transposed conv is the same kernel over the interior-dilated, re-padded
  upstream gradient with a flipped kernel), ``dw`` is a second Pallas
  reduction kernel accumulating the k²-tap correlation into VMEM scratch
  across the (B, H-tile) grid steps, and the tiny per-channel
  ``dscale``/``dbias`` reductions stay in XLA where they fuse with the
  activation-gradient elementwise pass.

On non-TPU backends the kernels run under the Pallas interpreter
(``interpret=True``), which is how the CPU suite checks forward AND
gradient parity against the XLA lowering (tests/test_depthwise_pallas.py).
Outputs declare their varying-mesh-axes set from the input operand
(``_out_struct``), so the op is check_vma-safe under ``shard_map``
(parallel/_compat.py) exactly like the flash kernels.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised only on exotic installs
    pltpu = None

from .conv import resolve_padding

__all__ = ["fused_depthwise", "FUSED_DW_ACTS"]

#: epilogue activations the kernel fuses; anything else runs act in XLA
FUSED_DW_ACTS = ("none", "silu", "relu")

_LANES = 128


def _vmem_spec(block_shape, index_map, unblocked: bool = False):
    kwargs = {}
    if pltpu is not None:
        kwargs["memory_space"] = pltpu.VMEM
    if unblocked:
        kwargs["indexing_mode"] = pl.Unblocked()
    return pl.BlockSpec(block_shape, index_map, **kwargs)


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # interpreter fallback


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-mesh-axes set so the
    same kernels work standalone and inside ``shard_map`` (check_vma)."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _act_f32(name: str):
    if name == "silu":
        return jax.nn.silu
    if name == "relu":
        return lambda u: jnp.maximum(u, 0.0)
    return lambda u: u


def _act_grad_f32(name: str):
    """d act(u) / du, evaluated in f32."""
    if name == "silu":
        def g(u):
            s = jax.nn.sigmoid(u)
            return s * (1.0 + u * (1.0 - s))
        return g
    if name == "relu":
        return lambda u: (u > 0.0).astype(jnp.float32)
    return lambda u: jnp.ones_like(u)


def _to_tuple(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _pick_block_h(w: int, ct: int, kh: int, stride: int,
                  ho: int, budget: int = 2 * 1024 * 1024) -> int:
    """Largest output-rows-per-tile whose f32 input halo block fits the VMEM
    budget (Pallas double-buffers, so stay well under the 16 MB arena)."""
    th = max(1, min(ho, 8))
    while th > 1 and (th * stride + kh - stride) * w * ct * 4 > budget:
        th -= 1
    return th


def _channel_tile(c: int) -> int:
    """Lane-friendly channel tile: full lanes when divisible, else the whole
    (padded) channel extent for small C."""
    if c % _LANES == 0:
        return _LANES
    return c


# ---------------------------------------------------------------------------
# forward kernel (also computes dx in the backward via kernel reuse)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, *z_ref, stride, kh, kw,
                th_out, wo, act):
    """One (b, c-tile, h-tile) grid cell: k²-tap MAC + affine + act, all on
    the VPU with the accumulator VMEM-resident.  ``z_ref`` (the f32
    pre-affine output the backward consumes) exists only on the
    residual-saving call — the primal never allocates it."""
    ct = x_ref.shape[-1]
    xv = x_ref[0].astype(jnp.float32)
    acc = jnp.zeros((th_out, wo, ct), jnp.float32)
    for r in range(kh):
        for s in range(kw):
            tap = lax.slice(
                xv, (r, s, 0),
                (r + (th_out - 1) * stride + 1, s + (wo - 1) * stride + 1,
                 ct),
                (stride, stride, 1))
            acc = acc + tap * w_ref[r, s][None, None, :].astype(jnp.float32)
    if z_ref:
        z_ref[0][0] = acc
    u = acc * s_ref[0][None, None, :] + b_ref[0][None, None, :]
    y_ref[0] = _act_f32(act)(u).astype(y_ref.dtype)


def _dw_call(xp, w, scale, bias, *, stride, act, ho, wo, out_dtype,
             want_z, interpret):
    """Padded-layout forward: ``xp (B, Hp, Wp, C)`` pre-padded so that every
    H tile's halo block is in-bounds; returns ``y (B, Ho, Wo, C)`` and (when
    ``want_z``) the f32 pre-affine conv output for the backward."""
    b, hp, wp, c = xp.shape
    kh, kw = w.shape[0], w.shape[1]
    ct = _channel_tile(c)
    th_out = _pick_block_h(wp, ct, kh, stride, ho)
    n_h = -(-ho // th_out)
    th_in = th_out * stride + kh - stride
    # tiling may overshoot Ho (last tile) — pad H so every halo block is
    # in-bounds; the overshoot rows are sliced off below
    need_hp = (n_h * th_out - 1) * stride + kh
    if need_hp > hp:
        xp = jnp.pad(xp, ((0, 0), (0, need_hp - hp), (0, 0), (0, 0)))
        hp = need_hp
    ho_p = n_h * th_out

    grid = (b, c // ct, n_h)
    in_specs = [
        _vmem_spec((1, th_in, wp, ct),
                   lambda bi, ci, hi: (bi, hi * th_out * stride, 0, ci * ct),
                   unblocked=True),
        _vmem_spec((kh, kw, ct), lambda bi, ci, hi: (0, 0, ci)),
        _vmem_spec((1, ct), lambda bi, ci, hi: (0, ci)),
        _vmem_spec((1, ct), lambda bi, ci, hi: (0, ci)),
    ]
    out_spec = _vmem_spec((1, th_out, wo, ct),
                          lambda bi, ci, hi: (bi, hi, 0, ci))
    out_specs = [out_spec]
    out_shape = [_out_struct((b, ho_p, wo, c), out_dtype, xp)]
    if want_z:
        # f32 pre-affine conv output, saved as the backward's residual —
        # only the residual-saving forward pays for this buffer
        out_specs.append(out_spec)
        out_shape.append(_out_struct((b, ho_p, wo, c), jnp.float32, xp))
    kern = functools.partial(_fwd_kernel, stride=stride, kh=kh, kw=kw,
                             th_out=th_out, wo=wo, act=act)
    out = pl.pallas_call(
        kern, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=interpret,
    )(xp, w, scale, bias)
    if want_z:
        y, z = out
        return y[:, :ho], z[:, :ho]
    return out[0][:, :ho], None


# ---------------------------------------------------------------------------
# backward dw kernel: k²-tap correlation reduced over (B, H tiles)
# ---------------------------------------------------------------------------

def _dwgrad_kernel(x_ref, dz_ref, dw_ref, acc_ref, *, stride, kh, kw, th_out,
                   wo):
    """One (c-tile, b, h-tile) grid cell accumulating ``dw[r·kw+s, c] +=
    Σ_{rows,cols} dz ⊙ x_shift(r,s)`` into VMEM scratch; written once at the
    last (b, h) step."""
    ct = x_ref.shape[-1]
    bi = pl.program_id(1)
    hi = pl.program_id(2)
    nb = pl.num_programs(1)
    nh = pl.num_programs(2)

    @pl.when(jnp.logical_and(bi == 0, hi == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xv = x_ref[0].astype(jnp.float32)
    dzv = dz_ref[0].astype(jnp.float32)
    for r in range(kh):
        for s in range(kw):
            tap = lax.slice(
                xv, (r, s, 0),
                (r + (th_out - 1) * stride + 1, s + (wo - 1) * stride + 1,
                 ct),
                (stride, stride, 1))
            acc_ref[r * kw + s, :] += jnp.sum(tap * dzv, axis=(0, 1))

    @pl.when(jnp.logical_and(bi == nb - 1, hi == nh - 1))
    def _finalize():
        dw_ref[:] = acc_ref[:]


def _dwgrad_call(xp, dz, kh, kw, *, stride, ho, wo, interpret):
    """dw (kh, kw, C) from the padded input and the (zero-padded to the tile
    grid) upstream conv-output gradient."""
    b, hp, wp, c = xp.shape
    ct = _channel_tile(c)
    th_out = _pick_block_h(wp, ct, kh, stride, ho)
    n_h = -(-ho // th_out)
    th_in = th_out * stride + kh - stride
    need_hp = (n_h * th_out - 1) * stride + kh
    if need_hp > hp:
        xp = jnp.pad(xp, ((0, 0), (0, need_hp - hp), (0, 0), (0, 0)))
    ho_p = n_h * th_out
    if ho_p > ho:
        # zero rows contribute nothing to the correlation
        dz = jnp.pad(dz, ((0, 0), (0, ho_p - ho), (0, 0), (0, 0)))

    kern = functools.partial(_dwgrad_kernel, stride=stride, kh=kh, kw=kw,
                             th_out=th_out, wo=wo)
    dw = pl.pallas_call(
        kern,
        grid=(c // ct, b, n_h),
        in_specs=[
            _vmem_spec((1, th_in, wp, ct),
                       lambda ci, bi, hi: (bi, hi * th_out * stride, 0,
                                           ci * ct),
                       unblocked=True),
            _vmem_spec((1, th_out, wo, ct),
                       lambda ci, bi, hi: (bi, hi, 0, ci)),
        ],
        out_specs=_vmem_spec((kh * kw, ct), lambda ci, bi, hi: (0, ci)),
        out_shape=_out_struct((kh * kw, c), jnp.float32, xp),
        scratch_shapes=[_scratch((kh * kw, ct))],
        interpret=interpret,
    )(xp, dz)
    return dw.reshape(kh, kw, c)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def fused_depthwise(x: jnp.ndarray, w: jnp.ndarray,
                    scale: Optional[jnp.ndarray] = None,
                    bias: Optional[jnp.ndarray] = None,
                    stride: Union[int, Tuple[int, int]] = 1,
                    padding: Union[str, int, None, Sequence] = "",
                    act: str = "silu",
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """``act(depthwise_conv(x, w) · scale + bias)`` in one VMEM pass.

    ``x`` is NHWC ``(B, H, W, C)``; ``w`` is ``(kh, kw, C)`` or the HWIO
    depthwise layout ``(kh, kw, 1, C)``; ``scale``/``bias`` are per-channel
    ``(C,)`` (None → identity affine).  ``padding`` takes the same values as
    :func:`ops.conv.resolve_padding` (``''`` = the reference's static
    symmetric torch padding, ``'same'`` = TF SAME, int, or an explicit
    ``[(lo, hi), (lo, hi)]``).  Equal H/W stride only (the EfficientNet
    families never use anisotropic depthwise strides).  Accumulation and the
    epilogue run in f32; the output is cast back to ``x.dtype``.

    Gradients flow through a custom VJP whose ``dx``/``dw`` are also Pallas
    (see module docstring).  ``interpret`` defaults to True off-TPU so the
    CPU suite runs the kernels under the Pallas interpreter.
    """
    assert x.ndim == 4, f"expected NHWC (B, H, W, C), got {x.shape}"
    if w.ndim == 4:  # HWIO depthwise (kh, kw, 1, C)
        assert w.shape[2] == 1, f"not a depthwise kernel: {w.shape}"
        w = w.reshape(w.shape[0], w.shape[1], w.shape[3])
    assert w.shape[-1] == x.shape[-1], (w.shape, x.shape)
    assert act in FUSED_DW_ACTS, f"act must be one of {FUSED_DW_ACTS}"
    sh, sw = _to_tuple(stride)
    assert sh == sw, f"anisotropic depthwise stride unsupported ({sh},{sw})"
    stride = int(sh)
    kh, kw = int(w.shape[0]), int(w.shape[1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    pad = resolve_padding(padding, (kh, kw), 1, stride)
    if pad == "SAME":
        def _same(n, k):
            need = max((-(-n // stride) - 1) * stride + k - n, 0)
            return (need // 2, need - need // 2)
        pad = [_same(x.shape[1], kh), _same(x.shape[2], kw)]
    elif pad == "VALID":
        pad = [(0, 0), (0, 0)]
    (ph0, ph1), (pw0, pw1) = [tuple(int(p) for p in pr) for pr in pad]

    b, h, wdim, c = x.shape
    hp, wp = h + ph0 + ph1, wdim + pw0 + pw1
    ho = (hp - kh) // stride + 1
    wo = (wp - kw) // stride + 1
    assert ho > 0 and wo > 0, (x.shape, pad, stride)

    out_dtype = x.dtype
    w32 = w.astype(jnp.float32)
    has_affine = scale is not None or bias is not None
    scale32 = (jnp.ones((c,), jnp.float32) if scale is None
               else scale.astype(jnp.float32))
    bias32 = (jnp.zeros((c,), jnp.float32) if bias is None
              else bias.astype(jnp.float32))
    # the backward reads the pre-affine conv output z only through the act
    # gradient and dscale — with an identity epilogue (exactly the training
    # call: stats are computed OUTSIDE the kernel) dz == dy and the affine
    # cotangents are gradients of internal constants, so saving z would
    # re-add the full-size f32 HBM write the fusion exists to remove
    needs_z = has_affine or act != "none"

    def _pad_x(xv):
        return jnp.pad(xv, ((0, 0), (ph0, ph1), (pw0, pw1), (0, 0)))

    @jax.custom_vjp
    def _op(xv, wv, sv, bv):
        y, _ = _dw_call(_pad_x(xv), wv, sv.reshape(1, c), bv.reshape(1, c),
                        stride=stride, act=act, ho=ho, wo=wo,
                        out_dtype=out_dtype, want_z=False,
                        interpret=interpret)
        return y

    def _op_fwd(xv, wv, sv, bv):
        y, z = _dw_call(_pad_x(xv), wv, sv.reshape(1, c), bv.reshape(1, c),
                        stride=stride, act=act, ho=ho, wo=wo,
                        out_dtype=out_dtype, want_z=needs_z,
                        interpret=interpret)
        return y, (xv, wv, sv, bv, z)

    def _op_bwd(res, g):
        xv, wv, sv, bv, z = res
        g32 = g.astype(jnp.float32)
        if needs_z:
            u = z * sv[None, None, None, :] + bv[None, None, None, :]
            du = g32 * _act_grad_f32(act)(u) if act != "none" else g32
            # per-channel reductions fuse with the du pass in XLA
            dbias = jnp.sum(du, axis=(0, 1, 2))
            dscale = jnp.sum(du * z, axis=(0, 1, 2))
            dz = du * sv[None, None, None, :]
        else:
            # identity epilogue: dz == dy; the affine params are internal
            # constants, their cotangents are discarded upstream
            dz = g32
            dscale = jnp.zeros_like(sv)
            dbias = jnp.zeros_like(bv)
        # dx: transposed depthwise conv == the SAME forward kernel over the
        # interior-dilated dz padded by (k-1), with the kernel flipped
        dzd = lax.pad(dz, jnp.float32(0),
                      ((0, 0, 0),
                       (kh - 1, kh - 1, stride - 1),
                       (kw - 1, kw - 1, stride - 1),
                       (0, 0, 0)))
        wf = wv[::-1, ::-1].astype(jnp.float32)
        ones = jnp.ones((1, c), jnp.float32)
        zeros = jnp.zeros((1, c), jnp.float32)
        dxh = (ho - 1) * stride + kh      # rows of xp that received taps
        dxw = (wo - 1) * stride + kw
        dx_p, _ = _dw_call(dzd, wf, ones, zeros, stride=1, act="none",
                           ho=dxh, wo=dxw, out_dtype=jnp.float32,
                           want_z=False, interpret=interpret)
        # rows/cols of the padded input beyond the last tap window got no
        # gradient; re-inflate to (Hp, Wp) then strip the conv padding
        dx_p = jnp.pad(dx_p, ((0, 0), (0, hp - dxh), (0, wp - dxw), (0, 0)))
        dx = dx_p[:, ph0:ph0 + h, pw0:pw0 + wdim]
        dw = _dwgrad_call(_pad_x(xv.astype(jnp.float32)), dz, kh, kw,
                          stride=stride, ho=ho, wo=wo, interpret=interpret)
        return (dx.astype(xv.dtype), dw.astype(wv.dtype),
                dscale.astype(sv.dtype), dbias.astype(bv.dtype))

    _op.defvjp(_op_fwd, _op_bwd)
    return _op(x, w32, scale32, bias32)
