"""Fused flash attention as Pallas TPU kernels (fwd + bwd, custom VJP).

The reference framework has no attention op at all (its temporal axis is a
channel concat, SURVEY.md §2.7); attention enters this framework through the
ViT stretch configs (BASELINE.json) and the sequence-parallel machinery in
``parallel/ring_attention.py``.  XLA's dense softmax-attention materialises
the (L, L) score matrix in HBM — O(L²) memory traffic, which caps sequence
length and wastes HBM bandwidth (the usual TPU bottleneck).  This module
implements the standard blocked online-softmax formulation (FlashAttention-2
schedule) as Pallas kernels so scores never leave VMEM:

* forward:  grid over (batch·heads, Q blocks); K/V stream through VMEM in
  BK-sized tiles under a ``fori_loop``; running max / denominator keep the
  softmax numerically stable; the kernel also emits the per-row logsumexp
  needed by the backward pass.
* backward: two kernels — one gridded over K blocks (computes dK, dV by
  streaming Q/dO blocks), one over Q blocks (computes dQ by streaming K/V
  blocks) — the textbook split that keeps every accumulation local to the
  grid cell writing it (no cross-cell reductions, no atomics).

All matmuls run on the MXU in float32 accumulation (``preferred_element_type``)
regardless of the bf16 inputs; masking (padded keys, causal) is computed from
``broadcasted_iota`` inside the kernel, so padded shapes stay static.

On non-TPU backends the same kernels run under the Pallas interpreter
(``interpret=True``), which is how the CPU test suite checks parity against
``parallel.ring_attention.full_attention`` for values *and* gradients.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - exercised only on exotic installs
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention"]

_NEG_INF = float("-inf")


def _vmem_spec(block_shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(block_shape, index_map)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                seq_len, causal):
    """One (bh, q-block) grid cell: stream K/V tiles, online softmax."""
    bq, d = q_ref.shape[1], q_ref.shape[2]
    lp = k_ref.shape[1]
    nk = lp // block_k
    iq = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale                    # (BQ, D)
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(jk, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        invalid = k_pos >= seq_len
        if causal:
            invalid = jnp.logical_or(invalid, k_pos > q_pos)
        s = jnp.where(invalid, _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))             # (BQ,)
        # rows that have seen no valid key yet: keep exp() argument finite
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(invalid, 0.0, p)
        corr = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    if causal:
        # blocks strictly after the diagonal contribute nothing — skip them
        nk_eff = jax.lax.min(
            jnp.int32(nk), ((iq + 1) * bq + block_k - 1) // block_k)
    else:
        nk_eff = nk
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    m_safe = jnp.where(m == _NEG_INF, 0.0, m)
    lse_ref[0] = m_safe + jnp.log(l_safe)


def _fwd(q, k, v, scale, block_q, block_k, causal, seq_len, interpret):
    bh, lp, d = q.shape
    grid = (bh, lp // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                          seq_len=seq_len, causal=causal),
        grid=grid,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, lp, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, lp, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lp), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, block_q, seq_len, causal):
    """One (bh, k-block) grid cell: stream Q/dO tiles → dK, dV."""
    bk, d = k_ref.shape[1], k_ref.shape[2]
    lp = q_ref.shape[1]
    nq = lp // block_q
    jk = pl.program_id(1)

    k = k_ref[0].astype(jnp.float32)                            # (BK, D)
    v = v_ref[0].astype(jnp.float32)
    k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(iq, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(iq * block_q, block_q)]
        delta = delta_ref[0, pl.ds(iq * block_q, block_q)]
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        invalid = k_pos >= seq_len
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            invalid = jnp.logical_or(invalid, k_pos > q_pos)
        p = jnp.where(invalid, 0.0, jnp.exp(s - lse[:, None]))   # (BQ, BK)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    if causal:
        # q blocks strictly before this k block's diagonal see none of it
        iq0 = (jk * bk) // block_q
    else:
        iq0 = 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(iq0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, scale, block_k, seq_len, causal):
    """One (bh, q-block) grid cell: stream K/V tiles → dQ."""
    bq, d = q_ref.shape[1], q_ref.shape[2]
    lp = k_ref.shape[1]
    nk = lp // block_k
    iq = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(jk, dq):
        k = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        invalid = k_pos >= seq_len
        if causal:
            invalid = jnp.logical_or(invalid, k_pos > q_pos)
        p = jnp.where(invalid, 0.0, jnp.exp(s - lse[:, None]))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        nk_eff = jax.lax.min(
            jnp.int32(nk), ((iq + 1) * bq + block_k - 1) // block_k)
    else:
        nk_eff = nk
    dq = jax.lax.fori_loop(0, nk_eff, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd(scale, block_q, block_k, causal, interpret, seq_len, res, g):
    q, k, v, out, lse = res
    do = g[0] if isinstance(g, (tuple, list)) else g
    bh, lp, d = q.shape
    # delta_i = rowsum(dO_i ⊙ O_i) — tiny elementwise reduce; XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    kern = functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                             seq_len=seq_len, causal=causal)
    dk, dv = pl.pallas_call(
        kern,
        grid=(bh, lp // block_k),
        in_specs=[
            _vmem_spec((1, lp, d), lambda b, j: (b, 0, 0)),        # q
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # k
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # v
            _vmem_spec((1, lp, d), lambda b, j: (b, 0, 0)),        # do
            _vmem_spec((1, lp), lambda b, j: (b, 0)),              # lse
            _vmem_spec((1, lp), lambda b, j: (b, 0)),              # delta
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lp, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    kern = functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k,
                             seq_len=seq_len, causal=causal)
    dq = pl.pallas_call(
        kern,
        grid=(bh, lp // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
            _vmem_spec((1, lp, d), lambda b, i: (b, 0, 0)),        # k
            _vmem_spec((1, lp, d), lambda b, i: (b, 0, 0)),        # v
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # do
            _vmem_spec((1, block_q), lambda b, i: (b, i)),         # lse
            _vmem_spec((1, block_q), lambda b, i: (b, i)),         # delta
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lp, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused O(L) -memory attention.  Shapes ``(B, L, H, D) → (B, L, H, D)``
    (same convention as :func:`parallel.ring_attention.full_attention`).

    Inputs are padded to block/lane multiples (L → block, D → 128) and the
    pad keys masked inside the kernel, so any static shape works.  Gradients
    flow through a custom VJP whose backward is also Pallas.  ``interpret``
    defaults to True off-TPU so tests run on the CPU interpreter.
    """
    assert q.ndim == 4, f"expected (B, L, H, D), got {q.shape}"
    b, l, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, _round_up(l, 128))
    block_k = min(block_k, _round_up(l, 128))
    lp = _round_up(l, max(block_q, block_k))
    dp = _round_up(d, 128)

    def prep(x):  # (B, L, H, D) -> (B*H, Lp, Dp)
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)
        return jnp.pad(x, ((0, 0), (0, lp - l), (0, dp - d)))

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def _op(qp, kp, vp):
        out, _ = _fwd_call(qp, kp, vp)
        return out

    def _op_fwd(qp, kp, vp):
        out, lse = _fwd_call(qp, kp, vp)
        return out, (qp, kp, vp, out, lse)

    def _fwd_call(qp, kp, vp):
        return _fwd(qp, kp, vp, scale, block_q, block_k, causal, l,
                    interpret)

    def _op_bwd(res, g):
        return _bwd(scale, block_q, block_k, causal, interpret, l, res, g)

    _op.defvjp(_op_fwd, _op_bwd)

    out = _op(prep(q), prep(k), prep(v))
    out = out[:, :l, :d].reshape(b, h, l, d)
    return jnp.transpose(out, (0, 2, 1, 3))
