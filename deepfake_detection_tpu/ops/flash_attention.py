"""Fused flash attention as Pallas TPU kernels (fwd + bwd, custom VJP).

The reference framework has no attention op at all (its temporal axis is a
channel concat, SURVEY.md §2.7); attention enters this framework through the
ViT stretch configs (BASELINE.json) and the sequence-parallel machinery in
``parallel/ring_attention.py``.  XLA's dense softmax-attention materialises
the (L, L) score matrix in HBM — O(L²) memory traffic, which caps sequence
length and wastes HBM bandwidth (the usual TPU bottleneck).  This module
implements the standard blocked online-softmax formulation (FlashAttention-2
schedule) as Pallas kernels so scores never leave VMEM.

All three kernels use the canonical TPU grid structure: the *tile* axis is
the innermost (sequential) grid dimension, so Pallas pipelines one
``(block, d)`` tile at a time through VMEM — O(block) on-chip residency
regardless of sequence length — while online-softmax / gradient accumulators
live in VMEM scratch that persists across the inner grid steps:

* forward:          grid (B·H, Q blocks, K tiles) — scratch (acc, m, l);
                    emits O and the per-row logsumexp the backward reuses.
* backward dQ:      grid (B·H, Q blocks, K tiles) — scratch dQ.
* backward dK/dV:   grid (B·H, K blocks, Q tiles) — scratch (dK, dV);
                    the per-(i,j) work is the FlashAttention-2 identity
                    ``dS = P ∘ (dP − δ)`` with δ = rowsum(dO ∘ O).

All matmuls run on the MXU in float32 accumulation
(``preferred_element_type``) regardless of the bf16 inputs; masking (padded
keys, causal) is computed from ``broadcasted_iota`` against dynamic global
offsets held in SMEM, so the same kernels serve the standalone op (offsets
0) and every step of ring attention (offsets = ring position, see
``parallel/ring_attention.py``).  Fully-masked tiles are skipped with
``pl.when``.

On non-TPU backends the same kernels run under the Pallas interpreter
(``interpret=True``), which is how the CPU test suite checks parity against
``parallel.ring_attention.full_attention`` for values *and* gradients.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs; the rest of
    # the package (and the interpreter path) must keep importing
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised only on exotic installs
    pltpu = None

__all__ = ["flash_attention"]

_NEG_INF = float("-inf")
_LANES = 128          # scalar-per-row scratch is lane-replicated to 128


def _vmem_spec(block_shape, index_map):
    if pltpu is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map)


def _smem_scalar_spec():
    """(1, 1) int32 scalar operand (offsets); scalars live in SMEM on TPU."""
    if pltpu is not None:
        return pl.BlockSpec((1, 1), lambda *_: (0, 0),
                            memory_space=pltpu.SMEM)
    return pl.BlockSpec((1, 1), lambda *_: (0, 0))


def _scratch(shape):
    """float32 VMEM scratch buffer declaration."""
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # interpreter fallback


def _as_scalar(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.int32).reshape(1, 1)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct whose varying-mesh-axes set matches ``like``.

    Inside ``shard_map`` (ring attention) pallas outputs must declare which
    mesh axes they vary over; inherit that from an input operand so the same
    kernels work standalone and under any mesh.
    """
    typeof = getattr(jax, "typeof", None)   # pre-0.6 jax: no VMA types
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, seq_len, causal):
    """One (bh, q-block, k-tile) grid cell of the online softmax.

    ``q_off``/``kv_off`` are *global* sequence offsets of this Q shard / KV
    buffer — 0 standalone; under ring attention they locate the shard in the
    global sequence so the causal mask is right at every ring step.
    ``seq_len`` counts the valid (un-padded) keys in the KV buffer.
    """
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    relevant = jk * bk < seq_len               # tile has ≥1 un-padded key
    if causal:
        last_q = q_off + (iq + 1) * bq - 1
        relevant = jnp.logical_and(relevant, kv_off + jk * bk <= last_q)

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_loc = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        invalid = k_loc >= seq_len
        if causal:
            q_pos = q_off + iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            invalid = jnp.logical_or(invalid, kv_off + k_loc > q_pos)
        s = jnp.where(invalid, _NEG_INF, s)

        m_prev = m_ref[:, :1]                                  # (BQ, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # rows that have seen no valid key yet: keep exp() argument finite
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(invalid, 0.0, p)
        corr = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)                   # (BQ, 1)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        m = m_ref[:, :1]
        m_safe = jnp.where(m == _NEG_INF, 0.0, m)
        # lse is lane-replicated to the 128-wide tile (Mosaic requires the
        # last two block dims be (8·k, 128); same layout as the reference
        # jax.experimental.pallas TPU flash kernel's residuals)
        lse_ref[0] = jnp.broadcast_to(m_safe + jnp.log(l),
                                      lse_ref.shape[1:])


def _fwd(q, k, v, scale, block_q, block_k, causal, seq_len, interpret,
         q_off=0, kv_off=0):
    """Padded-layout forward: (BH, Lq, D), (BH, Lk, D)² → (out, lse)."""
    bh, lpq, d = q.shape
    lpk = k.shape[1]
    grid = (bh, lpq // block_q, lpk // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, seq_len=seq_len,
                          causal=causal),
        grid=grid,
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, lpq, d), q.dtype, q),
            _out_struct((bh, lpq, _LANES), jnp.float32, q),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, _LANES)),
            _scratch((block_q, _LANES)),
        ],
        interpret=interpret,
    )(_as_scalar(q_off), _as_scalar(kv_off), q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, seq_len, causal):
    """One (bh, k-block, q-tile) grid cell accumulating dK, dV."""
    bk, d = k_ref.shape[1], k_ref.shape[2]
    bq = q_ref.shape[1]
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    relevant = jk * bk < seq_len
    if causal:
        # this q tile's last global row must reach the k block's first row
        last_q = q_off + (iq + 1) * bq - 1
        relevant = jnp.logical_and(relevant, kv_off + jk * bk <= last_q)

    @pl.when(relevant)
    def _accumulate():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, :1]                                 # (BQ, 1)
        delta = delta_ref[0, :, :1]
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_loc = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        invalid = k_loc >= seq_len
        if causal:
            q_pos = q_off + iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            invalid = jnp.logical_or(invalid, kv_off + k_loc > q_pos)
        p = jnp.where(invalid, 0.0, jnp.exp(s - lse))           # (BQ, BK)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_acc, *, scale, seq_len,
                   causal):
    """One (bh, q-block, k-tile) grid cell accumulating dQ."""
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]

    @pl.when(jk == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    relevant = jk * bk < seq_len
    if causal:
        last_q = q_off + (iq + 1) * bq - 1
        relevant = jnp.logical_and(relevant, kv_off + jk * bk <= last_q)

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, :1]                                 # (BQ, 1)
        delta = delta_ref[0, :, :1]
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_loc = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        invalid = k_loc >= seq_len
        if causal:
            q_pos = q_off + iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            invalid = jnp.logical_or(invalid, kv_off + k_loc > q_pos)
        p = jnp.where(invalid, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv(q, k, v, do, lse, delta, scale, block_q, block_k, causal,
             seq_len, interpret, q_off=0, kv_off=0):
    """dK, dV for one KV buffer, streaming Q tiles.  Padded layout."""
    bh, lpq, d = q.shape
    lpk = k.shape[1]
    kern = functools.partial(_bwd_dkv_kernel, scale=scale, seq_len=seq_len,
                             causal=causal)
    return pl.pallas_call(
        kern,
        grid=(bh, lpk // block_k, lpq // block_q),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            _vmem_spec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # q
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
            _vmem_spec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # do
            _vmem_spec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
            _vmem_spec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((bh, lpk, d), jnp.float32, k),
            _out_struct((bh, lpk, d), jnp.float32, k),
        ],
        scratch_shapes=[
            _scratch((block_k, d)),
            _scratch((block_k, d)),
        ],
        interpret=interpret,
    )(_as_scalar(q_off), _as_scalar(kv_off), q, k, v, do, lse, delta)


def _bwd_dq(q, k, v, do, lse, delta, scale, block_q, block_k, causal,
            seq_len, interpret, q_off=0, kv_off=0):
    """dQ for this Q shard against one KV buffer, streaming K tiles."""
    bh, lpq, d = q.shape
    lpk = k.shape[1]
    kern = functools.partial(_bwd_dq_kernel, scale=scale, seq_len=seq_len,
                             causal=causal)
    return pl.pallas_call(
        kern,
        grid=(bh, lpq // block_q, lpk // block_k),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # q
            _vmem_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),   # k
            _vmem_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),   # v
            _vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # do
            _vmem_spec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((bh, lpq, d), jnp.float32, q),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(_as_scalar(q_off), _as_scalar(kv_off), q, k, v, do, lse, delta)


def _delta(do, out):
    """δ = rowsum(dO ⊙ O), lane-replicated to match the lse layout."""
    d = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    return jnp.broadcast_to(d[..., None], (*d.shape, _LANES))


def _bwd(scale, block_q, block_k, causal, interpret, seq_len, res, g):
    q, k, v, out, lse = res
    do = g[0] if isinstance(g, (tuple, list)) else g
    delta = _delta(do, out)
    dk, dv = _bwd_dkv(q, k, v, do, lse, delta, scale, block_q, block_k,
                      causal, seq_len, interpret)
    dq = _bwd_dq(q, k, v, do, lse, delta, scale, block_q, block_k,
                 causal, seq_len, interpret)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused O(L) -memory attention.  Shapes ``(B, L, H, D) → (B, L, H, D)``
    (same convention as :func:`parallel.ring_attention.full_attention`).

    The Q buffer pads to a ``block_q`` multiple and the KV buffer to a
    ``block_k`` multiple (head dim to the 128-lane width); pad keys are
    masked inside the kernel, so any static shape works.  Gradients flow
    through a custom VJP whose backward is also Pallas.  ``interpret``
    defaults to True off-TPU so tests run on the CPU interpreter.
    """
    assert q.ndim == 4, f"expected (B, L, H, D), got {q.shape}"
    # self-attention shapes only: prep() folds (B, H) together and pads with
    # q's L, so a cross-attention Lk != Lq would die deep inside prep with an
    # opaque reshape error — reject it here instead
    assert q.shape == k.shape == v.shape, (
        f"flash_attention supports self-attention shapes only "
        f"(q{q.shape} k{k.shape} v{v.shape} must be equal)")
    b, l, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, _round_up(l, 128))
    block_k = min(block_k, _round_up(l, 128))
    lpq = _round_up(l, block_q)
    lpk = _round_up(l, block_k)
    dp = _round_up(d, 128)

    def prep(x, lp):  # (B, L, H, D) -> (B*H, lp, Dp)
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)
        return jnp.pad(x, ((0, 0), (0, lp - l), (0, dp - d)))

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def _op(qp, kp, vp):
        out, _ = _fwd_call(qp, kp, vp)
        return out

    def _op_fwd(qp, kp, vp):
        out, lse = _fwd_call(qp, kp, vp)
        return out, (qp, kp, vp, out, lse)

    def _fwd_call(qp, kp, vp):
        return _fwd(qp, kp, vp, scale, block_q, block_k, causal, l,
                    interpret)

    def _op_bwd(res, g):
        return _bwd(scale, block_q, block_k, causal, interpret, l, res, g)

    _op.defvjp(_op_fwd, _op_bwd)

    out = _op(prep(q, lpq), prep(k, lpk), prep(v, lpk))
    out = out[:, :l, :d].reshape(b, h, l, d)
    return jnp.transpose(out, (0, 2, 1, 3))
