"""Ops/layers library (reference layer L1, ``dfd/timm/models/layers/``)."""

from .activations import ACT_FNS, get_act_fn, hard_mish, hard_sigmoid, hard_swish, mish, swish
from .attention import (CbamModule, CecaModule, ChannelAttn, EcaModule,
                        LightCbamModule, SEModule, SelectiveKernelConv,
                        SpatialAttn, create_attn, make_divisible)
from .conv import (CondConv2d, Conv2d, MixedConv2d, conv_kernel_init_goog,
                   create_conv2d, dense_init_goog, resolve_padding,
                   space_to_depth, space_to_depth_stem_kernel)
from .depthwise_pallas import FUSED_DW_ACTS, fused_depthwise
from .drop import DropBlock2d, DropPath, Dropout, drop_block_2d, drop_path
from .flash_attention import flash_attention
from .norm import (BN_EPS_TF_DEFAULT, BN_MOMENTUM_TF_DEFAULT, BatchNorm2d,
                   GroupNorm, Identity, SplitBatchNorm2d, resolve_bn_args)
from .pool import (MedianPool2d, SelectAdaptivePool2d, adaptive_pool_feat_mult,
                   avg_pool2d_same, avg_pool2d_torch, global_pool_nhwc,
                   max_pool2d_torch, median_pool2d)
