"""Env-gated fault injection points (``DFD_CHAOS``).

The resilience layer (train/resilience.py) is only trustworthy if every
recovery path is exercised by an *injected* fault, not just unit-tested.
This module is the one switchboard: a ``DFD_CHAOS`` spec names faults and
the step at which they fire, and the production code paths (trainer loop,
host loaders, shm workers) carry tiny ``chaos.fires(...)`` probes that are
dead when the env var is unset.

Spec grammar — comma-separated entries of::

    <name>@<step>[x<count>][:<arg>]

* ``name``  — injection point (``sigterm``, ``nanbatch``, ``truncate_ckpt``,
  ``stall_loader``, ``kill_shm_worker``, ...; the probe site defines it).
* ``step``  — the counter value at which the fault fires.  What the counter
  means is per-point: global optimizer updates for trainer points, batch
  index for loader points, completed tasks for shm-worker points.
* ``x<count>`` — fire at ``count`` consecutive counter values (a burst:
  ``nanbatch@5x3`` poisons updates 5, 6 and 7).
* ``:<arg>`` — float argument (e.g. ``stall_loader@3:30`` stalls 30 s).

Every (name, step) pair fires AT MOST ONCE per injector instance: a rewind
that re-executes the same steps sees clean data the second time, which is
exactly the transient-fault semantics the recovery machinery targets.

Deliberately jax-free and import-light: spawned shm workers import this
without dragging the jax/flax stack into every worker process.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional, Set, Tuple

__all__ = ["ChaosInjector", "chaos_from_env", "CHAOS_ENV_VAR",
           "KNOWN_POINTS"]

CHAOS_ENV_VAR = "DFD_CHAOS"

#: The one registry of injection-point names.  Every ``fires("name", ...)``
#: probe site and every ``name@step`` spec literal in the harnesses must
#: use a name from this set — a typo'd point is a *dead injection path*
#: (the scenario silently tests nothing), which is exactly what dfdlint
#: rule DFD006 exists to catch.  Add the point here in the same change
#: that adds its probe site.
KNOWN_POINTS = frozenset({
    # trainer loop (train/trainer.py; stepped by optimizer update)
    "sigterm", "nanbatch", "truncate_ckpt",
    # host loaders (data/loader.py, stepped by batch index; shm workers
    # by completed tasks)
    "stall_loader", "kill_shm_worker",
    # serving request path (serving/engine.py, stepped by device-batch seq)
    "serve_exc", "serve_hang", "serve_nan", "serve_kill", "torn_reload",
    # offline backfill (runners/backfill.py; kill/torn stepped by device-
    # batch seq, lease_race by lease-acquisition attempt)
    "backfill_kill", "backfill_lease_race", "backfill_torn_shard",
})

_SPEC_RE = re.compile(
    r"^(?P<name>[a-z][a-z0-9_]*)@(?P<step>\d+)"
    r"(?:x(?P<count>\d+))?(?::(?P<arg>[-+0-9.eE]+))?$")


class ChaosInjector:
    """Parsed ``DFD_CHAOS`` spec with fire-once bookkeeping.

    An empty spec parses to an inactive injector whose probes cost one
    attribute read — probe sites guard on :attr:`active` and skip entirely
    in production runs.
    """

    def __init__(self, spec: str = ""):
        #: name -> (first_step, count, arg)
        self.points: Dict[str, Tuple[int, int, Optional[float]]] = {}
        self._fired: Set[Tuple[str, int]] = set()
        for part in filter(None, (p.strip() for p in spec.split(","))):
            m = _SPEC_RE.match(part)
            if m is None:
                raise ValueError(
                    f"bad {CHAOS_ENV_VAR} entry {part!r}; expected "
                    "<name>@<step>[x<count>][:<arg>]")
            self.points[m["name"]] = (
                int(m["step"]), int(m["count"] or 1),
                float(m["arg"]) if m["arg"] is not None else None)

    @property
    def active(self) -> bool:
        return bool(self.points)

    def fires(self, name: str, step: int) -> bool:
        """True exactly once per (name, step) inside the point's window."""
        p = self.points.get(name)
        if p is None:
            return False
        start, count, _ = p
        if not (start <= int(step) < start + count):
            return False
        key = (name, int(step))
        if key in self._fired:
            return False
        self._fired.add(key)
        return True

    def arg(self, name: str, default: float = 0.0) -> float:
        """The point's ``:<arg>`` value (``default`` when omitted)."""
        p = self.points.get(name)
        if p is None or p[2] is None:
            return default
        return p[2]


def chaos_from_env() -> ChaosInjector:
    """Injector from ``DFD_CHAOS`` (inactive when unset/empty)."""
    return ChaosInjector(os.environ.get(CHAOS_ENV_VAR, ""))
