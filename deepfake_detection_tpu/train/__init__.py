"""Training runtime: state, jitted steps, checkpointing, epoch loops,
fault tolerance."""

from .checkpoint import (CheckpointCorrupt, CheckpointSaver,
                         ShardedCheckpointSaver, find_resume_candidates,
                         load_checkpoint_file, replicate_for_save,
                         restore_resharded, restore_sharded_checkpoint,
                         restore_train_state, save_checkpoint_file,
                         save_sharded_checkpoint, wait_pending_saves)
from .resilience import (EXIT_PREEMPTED, EXIT_WATCHDOG, AnomalyGuard,
                         Preempted, Resilience, RewindRequested,
                         StallWatchdog, allreduce_flags)
from .state import (TrainState, create_train_state, get_learning_rate,
                    set_learning_rate)
from .steps import make_eval_step, make_train_step
from .trainer import save_image_batch, train_one_epoch, validate
