"""Training-loop checkpointing: top-K retention, best-copy, recovery.

Parity with ``CheckpointSaver`` (``/root/reference/dfd/timm/utils.py:36-149``):

* keeps the top ``max_history`` (10) checkpoints ranked by the eval metric
  (``decreasing=True`` for loss, :66-79);
* ``checkpoint-<epoch>.ckpt`` + ``model_best.ckpt`` copy (:86-89) + mirror of
  the best into a ``_bak`` backup dir (:92-93);
* payload = epoch / arch / model state / optimizer state / EMA / config /
  metric / version (:97-112) — here the whole :class:`TrainState` pytree in
  one flax-serialization msgpack blob;
* in-epoch ``save_recovery`` with previous-file cleanup (:128-140) and
  ``find_recovery`` (:142-147).

Atomic writes (tmp + rename) so a preempted TPU host never leaves a torn
checkpoint — the reference's ``torch.save`` has no such guard.
"""

from __future__ import annotations

import functools
import glob
import logging
import operator
import os
import shutil
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

_logger = logging.getLogger(__name__)

__all__ = ["CheckpointSaver", "ShardedCheckpointSaver", "CheckpointCorrupt",
           "save_checkpoint_file", "load_checkpoint_file",
           "replicate_for_save", "restore_train_state",
           "restore_resharded", "wait_pending_saves",
           "save_sharded_checkpoint", "restore_sharded_checkpoint",
           "load_sharded_for_eval", "find_resume_candidates"]

_EXT = ".ckpt"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file exists but cannot be decoded (truncated write,
    torn copy, disk corruption).  Carries the offending path so callers
    can fall back to an older snapshot instead of crashing."""

    def __init__(self, path: str, cause: str):
        super().__init__(
            f"checkpoint {path} is corrupt or truncated ({cause}); "
            "if this was a recovery snapshot, --auto-resume falls back "
            "to the previous one automatically")
        self.path = path


def _recovery_key(path: str):
    """(epoch, batch_idx) ints parsed from recovery-<e>-<b>[.ckpt]."""
    import re
    return tuple(int(n) for n in re.findall(r"\d+", os.path.basename(path)))


def _needs_gather(x: Any) -> bool:
    """True for leaves only a cross-process collective can fetch: sharded
    over devices this process cannot address AND not replicated."""
    return isinstance(x, jax.Array) and not x.is_fully_addressable \
        and not x.is_fully_replicated


def _to_host(x: Any, copy: bool = False) -> np.ndarray:
    """Fetch a (possibly sharded) array to host numpy.

    Fully-replicated and fully-addressable arrays convert directly (the
    local replica / local shards suffice) — this covers single-host runs of
    any sharding and multi-host pure-DP.  Multi-host *model-sharded* leaves
    would need a collective gather that every process enters; the saver runs
    on rank 0 only, so raise with the remedy instead of deadlocking in a
    one-sided all-gather.

    ``copy=True`` guarantees the result OWNS its bytes.  On the CPU
    backend ``np.asarray(jax.Array)`` is a zero-copy VIEW of the device
    buffer — and the train step DONATES its state, so XLA reuses that
    buffer for later steps' outputs and intermediates.  A background
    checkpoint writer serializing such a view races the hot loop and
    produces a silently TORN snapshot (observed: step counter from N steps
    later, params overwritten with unrelated intermediates).  Owning the
    bytes before handing them to the writer thread is the fix; backends
    whose fetch already materializes fresh host memory (TPU/GPU) skip the
    second copy via the ownership check.
    """
    if _needs_gather(x):
        raise RuntimeError(
            "checkpoint save of a multi-host model-sharded array: call "
            "replicate_for_save(state) on ALL processes before saving "
            "(rank-0-only saving cannot enter a collective)")
    a = np.asarray(x)
    if copy and not a.flags["OWNDATA"]:
        a = a.copy()
    return a


def replicate_for_save(state: Any) -> Any:
    """Gather multi-host model-sharded leaves to a replicated layout.

    A rank-0-only saver cannot all-gather (the other ranks never enter the
    collective), so EVERY process calls this first; rank 0 then serializes
    from its local replica.  The gather is a jit identity with replicated
    ``out_shardings`` — the one mechanism that reshards across processes
    (an eager ``device_put`` cannot move non-addressable shards and
    deadlocks).  No-op unless tensor/expert-parallel state actually spans
    hosts (single-host any-sharding and multi-host pure-DP pass through).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    leaves, treedef = jax.tree.flatten(state)
    idx = [i for i, x in enumerate(leaves) if _needs_gather(x)]
    if not idx:
        return state
    # gather ONLY the offending leaves: other leaves (e.g. the step counter
    # on a single device) belong to different device sets and cannot join
    # the same jitted computation
    sub = [leaves[i] for i in idx]
    out_sh = tuple(NamedSharding(x.sharding.mesh, PartitionSpec())
                   for x in sub)
    gathered = _gather_identity(out_sh)(*sub)
    for i, g in zip(idx, gathered):
        leaves[i] = g
    return jax.tree.unflatten(treedef, leaves)


@functools.lru_cache(maxsize=8)
def _gather_identity(out_sh: tuple):
    """Cached jitted identity per output-sharding tuple — a fresh lambda per
    save would retrace + recompile the all-gather every epoch (and expose
    every rank to compile-skew at exactly the rendezvous window)."""
    return jax.jit(lambda *t: t, out_shardings=out_sh)


# one background writer: at most one save in flight, joined before the next
# (in-epoch recovery snapshots must not stall the train loop on disk IO —
# the reference's torch.save blocked the epoch, utils.py:128-140)
_write_pool = ThreadPoolExecutor(max_workers=1,
                                 thread_name_prefix="ckpt-write")
_pending: List = []


def wait_pending_saves() -> None:
    """Block until any in-flight async checkpoint write has completed.

    Async writes are recovery snapshots — best-effort by design — so a
    failed background write is logged against its own path, not raised
    from whichever unrelated checkpoint call happens to join it.
    """
    while _pending:
        path, fut = _pending.pop()
        try:
            fut.result()
        except Exception as e:  # noqa: BLE001 — best-effort snapshot
            _logger.error("async checkpoint write of %s failed: %r", path, e)


def save_checkpoint_file(path: str, state: Any,
                         meta: Optional[Dict[str, Any]] = None,
                         async_write: bool = False) -> None:
    """Serialize {state, meta} atomically to ``path``.

    ``async_write=True`` fetches the state to host *now* (cheap; device
    sync) but serializes + writes on a background thread so the caller
    returns immediately.  Writes are ordered: a new save joins the
    previous one BEFORE building its host payload (bounding host residency
    to one state copy), and :func:`wait_pending_saves` flushes at exit.
    """
    wait_pending_saves()              # at most one write/payload at a time
    from ..models.helpers import stamp_qkv_layout
    sd_dev = serialization.to_state_dict(state)
    # start every device->host copy before the first blocking np.asarray:
    # a per-leaf blocking fetch serializes O(leaves) transfer round trips
    # (painful on high-latency backends; the async pre-pass overlaps them)
    for x in jax.tree.leaves(sd_dev):
        if isinstance(x, jax.Array):
            try:
                x.copy_to_host_async()
            except Exception:  # noqa: BLE001 — _to_host surfaces real errors
                pass
    # async: the background writer must own its bytes (zero-copy views of
    # donated buffers tear — see _to_host); sync serializes before the
    # caller can dispatch another donating step, so views are safe
    sd = jax.tree.map(
        functools.partial(_to_host, copy=async_write), sd_dev)
    meta = stamp_qkv_layout(meta, sd)  # meta stays plain python
    payload = {"state": sd, "meta": meta}

    def _write() -> None:
        blob = serialization.msgpack_serialize(payload)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    if async_write:
        _pending.append((path, _write_pool.submit(_write)))
    else:
        _write()


def load_checkpoint_file(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Read a raw {state_dict, meta} pair.

    A truncated or undecodable file raises :class:`CheckpointCorrupt`
    naming the file — a msgpack stream cut mid-write otherwise surfaces as
    an opaque unpacker exception deep inside flax, and the distinction
    matters: corrupt means "fall back to an older snapshot", not "bug".
    """
    wait_pending_saves()
    with open(path, "rb") as f:
        blob = f.read()
    if not blob:
        raise CheckpointCorrupt(path, "empty file")
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception as e:  # msgpack raises several unpacker classes
        raise CheckpointCorrupt(path, f"msgpack decode failed: {e!r}") \
            from e
    if not isinstance(payload, dict) or "state" not in payload:
        raise CheckpointCorrupt(path, "payload missing 'state'")
    sd, meta = payload["state"], payload.get("meta", {})
    from ..models.helpers import check_qkv_layout
    check_qkv_layout(sd, meta, path)
    return sd, meta


def _meta_json_default(v: Any):
    """json.dumps fallback for checkpoint meta: numpy scalars and arrays
    convert to their Python equivalents; anything else fails fast with a
    TypeError instead of being silently stringified (a str(ndarray) meta
    value survives the save but is garbage at restore time)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    raise TypeError(
        f"checkpoint meta value of type {type(v).__name__} is not "
        "JSON-serializable; convert it to int/float/str/list before save")


def save_sharded_checkpoint(path: str, state: Any,
                            meta: Optional[Dict[str, Any]] = None) -> None:
    """Collective SHARDED save (Orbax/TensorStore): every process calls
    this, and each host writes only its own addressable shards.

    This is the multi-host model-parallel save path the single-file
    msgpack format cannot offer: no :func:`replicate_for_save` all-gather,
    no O(model) host copy on rank 0 (the reference's ``torch.save``
    serializes the full model on one rank, utils.py:97-112).  Restore can
    RE-SHARD onto a different mesh — the template's shardings decide.

    ``path`` becomes a checkpoint directory; ``meta`` goes to
    ``<path>/dfd_meta.json`` (written by process 0 after the collective
    save completes, so a meta file implies a complete checkpoint).
    """
    import orbax.checkpoint as ocp

    import json

    from ..models.helpers import stamp_qkv_layout

    path = os.path.abspath(path)
    sd = serialization.to_state_dict(state)
    if jax.process_count() > 1:
        # host-local leaves (the step counter, injected lr — single-device
        # arrays identical on every rank) cannot join a multi-host
        # collective write; serialize them as host numpy instead (the
        # restore side reloads them placement-free, matching)
        from jax.sharding import NamedSharding
        sd = jax.tree.map(
            lambda x: np.asarray(x)
            if isinstance(x, jax.Array)
            and not isinstance(x.sharding, NamedSharding) else x, sd)
    # serialize meta BEFORE the expensive collective save so a
    # non-serializable value fails fast (numpy scalars/arrays — accepted
    # by the msgpack path's meta — are converted; anything else raises
    # here rather than round-tripping as a useless str() on restore)
    meta_blob = json.dumps(stamp_qkv_layout(meta, sd),
                           default=_meta_json_default)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, sd, force=True)
        ckptr.wait_until_finished()
    if jax.process_index() == 0:
        # atomic, and written only after the collective save returned:
        # the meta file's existence marks a complete checkpoint
        meta_path = os.path.join(path, "dfd_meta.json")
        with open(meta_path + ".tmp", "w") as f:
            f.write(meta_blob)
        os.replace(meta_path + ".tmp", meta_path)
    if jax.process_count() > 1:
        # other ranks must not observe save() as done before the meta
        # marker exists (a save-then-restore flow would read meta={})
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("dfd_sharded_save_meta")


def _partial_restore_kwargs(ocp, partial: bool) -> Dict[str, Any]:
    """PyTreeRestore kwargs for restoring a SUBSET of the saved tree.

    Current orbax spells it ``partial_restore=True``; the legacy idiom is
    ``transforms={}`` (restore exactly the item structure, drop extra
    checkpoint keys).  Detected by signature so both orbax generations
    work."""
    if not partial:
        return {}
    import inspect
    params = inspect.signature(ocp.args.PyTreeRestore.__init__).parameters
    if "partial_restore" in params:
        return {"partial_restore": True}
    return {"transforms": {}}


def _fresh_opt_sd(sd: Dict[str, Any], target_state: Any) -> Dict[str, Any]:
    """``--no-resume-opt`` substitution shared by both restore paths:
    weights/EMA from the checkpoint, optimizer state + step fresh."""
    sd = dict(sd)
    sd["opt_state"] = serialization.to_state_dict(target_state.opt_state)
    sd["step"] = serialization.to_state_dict(target_state.step)
    return sd


def restore_sharded_checkpoint(path: str, target_state: Any,
                               load_opt: bool = True
                               ) -> Tuple[Any, Dict[str, Any]]:
    """Collective sharded restore into ``target_state``'s structure AND
    shardings — each process reads only the shards its template layout
    asks for, resharding from the saved layout where they differ (the
    cross-process TP resume re-layout, without ever materializing the
    full model on any single host).

    ``load_opt=False``: optimizer state and step are neither read from
    disk nor required to match the checkpoint's optimizer — the saved
    ``opt_state``/``step`` entries are skipped entirely, so resuming
    weights under a *different* optimizer works.
    """
    import json

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    # the completeness marker is checked BEFORE the (potentially many-GB,
    # cross-host) shard read — its absence fails in milliseconds
    meta = _check_complete_sharded(path)
    target_sd = serialization.to_state_dict(target_state)

    from jax.sharding import NamedSharding

    def abstract(x):
        # only mesh (NamedSharding) layouts are pinned; leaves the
        # template holds on a single device restore PLACEMENT-FREE (as
        # host arrays below, like the msgpack path) — committing them to
        # one device would fight the train step's mesh placement
        if isinstance(x, jax.Array) and isinstance(x.sharding,
                                                   NamedSharding):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    def uncommit(t, r):
        if isinstance(r, jax.Array) and not (
                isinstance(t, jax.Array)
                and isinstance(t.sharding, NamedSharding)):
            return np.asarray(r)
        return r

    template = {k: jax.tree.map(abstract, v) for k, v in target_sd.items()
                if load_opt or k not in ("opt_state", "step")}
    # None-valued entries (e.g. ema when EMA is off) break the
    # partial-restore metadata walk — drop them there and re-add after
    # (the full restore, conversely, REQUIRES them for the structure match)
    nones = [] if load_opt else [k for k, v in template.items()
                                 if v is None]
    template = {k: v for k, v in template.items() if k not in nones}
    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        # partial_restore skips the saved opt_state/step entirely under
        # load_opt=False — no structure match against (possibly different)
        # optimizer state, no wasted shard reads
        sd = dict(ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=template, restore_args=restore_args,
            **_partial_restore_kwargs(ocp, not load_opt))))
    sd = {k: jax.tree.map(uncommit, target_sd[k], v) for k, v in sd.items()}
    for k in nones:
        sd[k] = None
    if not load_opt:
        sd = _fresh_opt_sd(sd, target_state)
    from ..models.helpers import check_qkv_layout
    check_qkv_layout(sd, meta, path)
    state = serialization.from_state_dict(target_state, sd)
    return state, meta


def _check_complete_sharded(path: str) -> Dict[str, Any]:
    """Validate the completeness marker; returns the checkpoint meta.

    Diagnoses the common wrong-path mistake (the RUN directory, which
    contains checkpoint-N subdirectories, instead of one of them).
    """
    import json

    meta_path = os.path.join(path, "dfd_meta.json")
    if not os.path.exists(meta_path):
        subdirs = [d for d in sorted(glob.glob(os.path.join(path, "*")))
                   if os.path.isfile(os.path.join(d, "dfd_meta.json"))]
        if subdirs:
            raise FileNotFoundError(
                f"{path} is a run directory, not a checkpoint; use one of "
                f"its checkpoints, e.g. {subdirs[-1]} (model_best.json "
                "points at the best one)")
        raise FileNotFoundError(
            f"{path}: no dfd_meta.json — the save was interrupted before "
            "completion (the marker is written last); do not load this "
            "checkpoint")
    with open(meta_path) as f:
        return json.load(f)


def load_sharded_for_eval(path: str, variables: Dict[str, Any],
                          use_ema: bool = True) -> Dict[str, Any]:
    """Model variables {params, batch_stats} from a sharded TRAIN
    checkpoint directory — the serving path for ``--ckpt-sharded`` runs.

    Prefers the EMA stream when the checkpoint carries one (the
    reference ships its released model from the EMA stream,
    ``model_half``); reads ONLY the selected streams, placement-free.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    meta = _check_complete_sharded(path)

    def abstract(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype) \
            if isinstance(x, (jax.Array, np.ndarray)) else x

    tmpl = {k: jax.tree.map(abstract, variables[k])
            for k in ("params", "batch_stats") if k in variables}
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        # key presence is not enough: an EMA-less TrainState serializes
        # ema=None, which still appears in the tree metadata
        md = ckptr.metadata(path)
        ema_md = (getattr(md, "item_metadata", md) or {}).get("ema")
        has_ema = use_ema and isinstance(ema_md, dict) and "params" in ema_md
        item = {"ema": tmpl} if has_ema else tmpl
        restore_args = ocp.checkpoint_utils.construct_restore_args(item)
        out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=item, restore_args=restore_args,
            **_partial_restore_kwargs(ocp, True)))
    out = dict(out["ema"] if has_ema else out)
    if has_ema:
        _logger.info("Loaded EMA stream from %s", path)
    out = {k: jax.tree.map(np.asarray, v) for k, v in out.items()}
    from ..models.helpers import check_qkv_layout
    check_qkv_layout(out, meta, path)
    return out


def find_resume_candidates(checkpoint_dir: str, bak_dir: str = "",
                           sharded: bool = False,
                           recovery_prefix: str = "recovery") -> List[str]:
    """Paths ``--auto-resume`` should try, best first: recovery snapshots
    newest-first, then the ``_bak`` best-copy mirror, then ``model_best``
    itself.  A torn newest snapshot (:class:`CheckpointCorrupt`) makes the
    caller step down this list instead of crashing.

    Standalone (no saver needed) so every rank of a multi-host run can
    compute the same list from the shared filesystem.  ``sharded``
    restricts to COMPLETE Orbax checkpoint directories (dfd_meta.json is
    written last, so its presence marks completion).
    """
    out: List[str] = []
    if sharded:
        cands = [c for c in glob.glob(os.path.join(checkpoint_dir,
                                                   recovery_prefix + "*"))
                 if os.path.isfile(os.path.join(c, "dfd_meta.json"))]
        out.extend(sorted(cands, key=_recovery_key, reverse=True))
        best_ptr = os.path.join(checkpoint_dir, "model_best.json")
        if os.path.isfile(best_ptr):
            import json
            try:
                with open(best_ptr) as f:
                    best = json.load(f).get("checkpoint", "")
            except (OSError, ValueError):
                best = ""
            if best and os.path.isfile(os.path.join(best, "dfd_meta.json")):
                out.append(best)
        return out
    out.extend(sorted(
        glob.glob(os.path.join(checkpoint_dir,
                               recovery_prefix + "*" + _EXT)),
        key=_recovery_key, reverse=True))
    for d in (bak_dir, checkpoint_dir):
        best = os.path.join(d, "model_best" + _EXT) if d else ""
        if best and os.path.isfile(best):
            out.append(best)
    return out


def restore_train_state(path: str, target_state: Any,
                        load_opt: bool = True) -> Tuple[Any, Dict[str, Any]]:
    """Rebuild a TrainState from file given a freshly-built template.

    ``load_opt=False`` mirrors ``--no-resume-opt`` (train.py:89,:365-373):
    weights/EMA restore but the optimizer state stays fresh.
    """
    sd, meta = load_checkpoint_file(path)
    if not load_opt:
        sd = _fresh_opt_sd(sd, target_state)
    state = serialization.from_state_dict(target_state, sd)
    return state, meta


def restore_resharded(path: str, target_state: Any,
                      load_opt: bool = True) -> Tuple[Any, Dict[str, Any]]:
    """msgpack restore into ``target_state``'s structure AND device layout.

    This is the mesh-portable restore (ISSUE 12): the checkpoint file
    carries plain host arrays, the TEMPLATE carries the sharding-rule
    table's ``NamedSharding`` annotations — so a checkpoint written on a
    (1, 1) mesh restores onto an (8, 1) layout (and vice versa) by
    re-laying every leaf onto the template's sharding at load time.
    Shared by ``--resume``, ``--auto-resume`` and the guard's rewind path.

    msgpack restore yields HOST numpy leaves; the compiled train step
    DONATES its state, and jax's CPU backend zero-copies suitably-aligned
    host buffers into jax arrays — donating such an alias frees memory
    numpy still owns, a use-after-free that surfaced as a native
    SIGSEGV/SIGABRT on the first resumed steps of a tp run.  Every
    restored host leaf is therefore copied into a device-OWNED array
    (re-applying the template's sharding where it had one).
    """
    from jax.sharding import NamedSharding

    from ..parallel.sharding import own_and_place

    shard_tree = jax.tree.map(
        lambda x: x.sharding if isinstance(x, jax.Array)
        and isinstance(x.sharding, NamedSharding) else None,
        target_state)
    restored, meta = restore_train_state(path, target_state,
                                         load_opt=load_opt)
    # own_and_place carries the whole ownership discipline: restored host
    # numpy leaves become JAX-OWNED copies (never zero-copy aliases the
    # donating step could free — the PR 2 SIGSEGV class) laid onto the
    # template's sharding, cross-host via per-shard assembly
    return jax.tree.map(own_and_place, restored, shard_tree), meta


class CheckpointSaver:
    #: collective savers (sharded) must be driven by EVERY process;
    #: file-based savers run on rank 0 only
    collective = False
    _ext = _EXT

    def __init__(self, checkpoint_dir: str = "",
                 recovery_dir: str = "", bak_dir: str = "",
                 decreasing: bool = False, max_history: int = 10,
                 checkpoint_prefix: str = "checkpoint",
                 recovery_prefix: str = "recovery"):
        self.checkpoint_files: List[Tuple[str, float]] = []  # (path, metric)
        self.best_epoch: Optional[int] = None
        self.best_metric: Optional[float] = None
        self.curr_recovery_file = ""
        self.last_recovery_file = ""
        self.checkpoint_dir = checkpoint_dir
        self.recovery_dir = recovery_dir or checkpoint_dir
        self.bak_dir = bak_dir
        self.checkpoint_prefix = checkpoint_prefix
        self.recovery_prefix = recovery_prefix
        self.decreasing = decreasing          # lower is better (loss)
        self.cmp = operator.lt if decreasing else operator.gt
        self.max_history = max_history
        assert self.max_history >= 1
        for d in (checkpoint_dir, self.recovery_dir, bak_dir):
            if d:
                os.makedirs(d, exist_ok=True)

    # ------------------------------------------------------------------
    def save_checkpoint(self, state: Any, meta: Dict[str, Any], epoch: int,
                        metric: Optional[float] = None) -> Tuple[Optional[float], Optional[int]]:
        """Epoch-boundary save with top-K pruning (reference :66-95)."""
        worst = self.checkpoint_files[-1] if self.checkpoint_files else None
        if len(self.checkpoint_files) < self.max_history or metric is None \
                or worst[1] is None or self.cmp(metric, worst[1]):
            if len(self.checkpoint_files) >= self.max_history:
                self._cleanup_checkpoints(1)
            path = os.path.join(
                self.checkpoint_dir,
                f"{self.checkpoint_prefix}-{epoch}{self._ext}")
            meta = dict(meta, epoch=epoch, metric=metric)
            self._write(path, state, meta)
            self.checkpoint_files.append((path, metric))
            # best-first; metric-less entries always rank worst (last) so
            # they are the first pruned
            with_metric = sorted(
                (c for c in self.checkpoint_files if c[1] is not None),
                key=lambda x: x[1], reverse=not self.decreasing)
            self.checkpoint_files = with_metric + [
                c for c in self.checkpoint_files if c[1] is None]
            files_str = "\n".join(f" {c}" for c in self.checkpoint_files)
            _logger.info("Current checkpoints:\n%s", files_str)
            if metric is not None and (self.best_metric is None
                                       or self.cmp(metric, self.best_metric)):
                self.best_epoch = epoch
                self.best_metric = metric
                self._mark_best(path, os.path.join(
                    self.checkpoint_dir, f"model_best{self._ext}"))
                if self.bak_dir:
                    self._mark_best(path, os.path.join(
                        self.bak_dir, f"model_best{self._ext}"))
        return (None, None) if self.best_metric is None \
            else (self.best_metric, self.best_epoch)

    def _cleanup_checkpoints(self, trim: int = 0) -> None:
        """Drop the worst ``trim`` retained checkpoints (reference :114-126)."""
        delete_index = self.max_history - trim
        if delete_index < 0 or len(self.checkpoint_files) <= delete_index:
            return
        to_delete = self.checkpoint_files[delete_index:]
        for path, _ in to_delete:
            try:
                _logger.debug("Cleaning checkpoint: %s", path)
                self._delete(path)
            except OSError as e:
                _logger.error("Exception %r while deleting checkpoint", e)
        self.checkpoint_files = self.checkpoint_files[:delete_index]

    # ------------------------------------------------------------------
    def save_recovery(self, state: Any, meta: Dict[str, Any], epoch: int,
                      batch_idx: int = 0, sync: bool = False) -> None:
        """In-epoch recovery snapshot, previous one removed (reference
        :128-140).  ``sync=True`` blocks until the file is durably renamed
        into place — the preemption path needs the snapshot ON DISK before
        the process exits, not queued on a background writer the exit
        would race."""
        path = os.path.join(
            self.recovery_dir,
            f"{self.recovery_prefix}-{epoch}-{batch_idx}{self._ext}")
        self._write_recovery(path, state, dict(meta, epoch=epoch,
                                               batch_idx=batch_idx),
                             sync=sync)
        if os.path.exists(self.last_recovery_file):
            try:
                _logger.debug("Cleaning recovery: %s",
                              self.last_recovery_file)
                self._delete(self.last_recovery_file)
            except OSError as e:
                _logger.error("Exception %r while removing %s", e,
                              self.last_recovery_file)
        self.last_recovery_file = self.curr_recovery_file
        self.curr_recovery_file = path

    def find_recovery(self) -> str:
        """Most recent recovery file, '' if none (reference :142-147;
        numeric epoch/batch ordering — a lexicographic sort would prefer
        recovery-0-999 over recovery-0-1099)."""
        files = glob.glob(os.path.join(
            self.recovery_dir, self.recovery_prefix + "*" + self._ext))
        return max(files, key=_recovery_key) if files else ""

    # -- IO hooks (overridden by the sharded saver) --------------------
    def _write(self, path: str, state: Any, meta: Dict[str, Any]) -> None:
        save_checkpoint_file(path, state, meta)

    def _write_recovery(self, path: str, state: Any,
                        meta: Dict[str, Any], sync: bool = False) -> None:
        save_checkpoint_file(path, state, meta, async_write=not sync)

    def _delete(self, path: str) -> None:
        os.remove(path)

    def _mark_best(self, src: str, dst: str) -> None:
        shutil.copyfile(src, dst)


class ShardedCheckpointSaver(CheckpointSaver):
    """Sharded (Orbax) retention saver: checkpoints are DIRECTORIES and
    saves are COLLECTIVE — drive :meth:`save_checkpoint` /
    :meth:`save_recovery` from EVERY process (the retention decisions are
    deterministic given identical metrics, so ranks stay in lockstep);
    only process 0 touches the filesystem for bookkeeping.

    ``model_best`` is a small JSON pointer to the best checkpoint
    directory, not a copy — duplicating a sharded tree would double
    checkpoint storage.  Recovery snapshots are synchronous (a collective
    cannot run on a background thread).
    """

    collective = True
    _ext = ""

    def _write(self, path: str, state: Any, meta: Dict[str, Any]) -> None:
        save_sharded_checkpoint(path, state, meta)

    def _write_recovery(self, path: str, state: Any,
                        meta: Dict[str, Any], sync: bool = False) -> None:
        # a collective save cannot ride a background thread; always sync
        save_sharded_checkpoint(path, state, meta)

    def _delete(self, path: str) -> None:
        if jax.process_index() == 0:
            shutil.rmtree(path, ignore_errors=True)

    def _mark_best(self, src: str, dst: str) -> None:
        if jax.process_index() != 0:
            return
        if self.bak_dir and dst.startswith(self.bak_dir):
            # a pointer in _bak would reference the SAME primary tree —
            # no durability gained; duplicating a sharded tree would
            # double checkpoint storage, so the bak mirror is skipped
            return
        import json
        with open(dst + ".json.tmp", "w") as f:
            json.dump({"checkpoint": src}, f)
        os.replace(dst + ".json.tmp", dst + ".json")

    def find_recovery(self) -> str:
        """Most recent COMPLETE recovery dir: Orbax leaves
        ``*.orbax-checkpoint-tmp-*`` droppings for torn saves, and only
        dirs whose dfd_meta.json exists finished their collective save."""
        cands = glob.glob(os.path.join(self.recovery_dir,
                                       self.recovery_prefix + "*"))
        done = [c for c in cands
                if os.path.isfile(os.path.join(c, "dfd_meta.json"))]
        return max(done, key=_recovery_key) if done else ""
