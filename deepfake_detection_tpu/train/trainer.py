"""Epoch-level training and validation loops.

Parity with the reference runner's ``train_epoch`` (``/root/reference/dfd/
runners/train.py:594-700``) and ``validate`` (:703-767): the same meters, the
same log line (loss/prec1 val(avg), s/batch, s/image, LR, data time, ETA),
``--save-images`` batch dumps, in-epoch recovery checkpoints, per-update LR
scheduling, and mixup-off-epoch switching.  What disappears on TPU: the
explicit ``torch.cuda.synchronize`` (the runner only blocks when it reads the
logged scalars — JAX async dispatch keeps the device busy) and the per-step
metric allreduce (it lives inside the compiled step).
"""

from __future__ import annotations

import logging
import os
import signal
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.metrics import AverageMeter, auc
from .resilience import Preempted, RewindRequested
from .state import TrainState, get_learning_rate, set_learning_rate

_logger = logging.getLogger(__name__)

__all__ = ["train_one_epoch", "validate", "save_image_batch"]


def save_image_batch(x, path: str, img_num: int = 4) -> None:
    """Dump a normalized NHWC batch as a tiled jpg (reference :679-684).

    Frames of each clip are laid out horizontally, batch vertically; values
    min-max normalized like torchvision's ``save_image(normalize=True)``.
    """
    from PIL import Image
    a = np.asarray(x, np.float32)
    lo, hi = a.min(), a.max()
    a = (a - lo) / max(hi - lo, 1e-6)
    b, h, w, c = a.shape
    assert c % img_num == 0
    cpf = c // img_num
    frames = a.reshape(b, h, w, img_num, cpf).transpose(0, 3, 1, 2, 4)
    grid = frames.reshape(b, img_num * h, w, cpf).transpose(1, 0, 2, 3) \
        .reshape(img_num * h, b * w, cpf)
    if cpf == 1:
        grid = np.repeat(grid, 3, axis=-1)
    Image.fromarray((grid[..., :3] * 255).astype(np.uint8)).save(path)


def train_one_epoch(epoch: int, train_step: Callable, state: TrainState,
                    loader, cfg, rng: jax.Array,
                    lr_scheduler=None, saver=None, output_dir: str = "",
                    meta: Optional[Dict[str, Any]] = None,
                    world_size: int = 1, start_batch: int = 0,
                    resilience=None, telemetry=None):
    """One epoch of the hot loop.  Returns ``(state, metrics)``.

    ``world_size`` is the data-parallel degree; s/image in the log line is
    per-device (the reference's ``bs`` is the per-GPU batch, train.py:658).

    ``start_batch`` > 0 resumes MID-epoch: the caller has already
    fast-forwarded the loader to that batch (loaders are deterministic in
    ``(seed, epoch, batch_index)``, so the stream is bit-identical to an
    uninterrupted epoch) and this loop restores the absolute batch index /
    update count so step RNG folding and LR scheduling continue exactly.

    ``resilience`` (train/resilience.py) hooks the loop into the fault-
    tolerance layer: per-step watchdog heartbeats, the preemption stop
    check at step boundaries (synchronous recovery snapshot + ``Preempted``),
    the NaN/spike guard fed at drain cadence (may raise ``RewindRequested``),
    and the env-gated chaos injection points the recovery tests drive.

    ``telemetry`` (obs/telemetry.py TrainTelemetry) rides the same
    cadences with host floats only — per-step wall/data-wait deltas and,
    at each drain, the time the drain itself blocked (the device-bound
    share) — so enabling it adds NO device syncs; its optional
    ``.profiler`` (obs/profiler.py) gets a per-step window check and a
    per-drain trigger-file poll for on-demand trace capture.
    """
    if cfg.mixup > 0 and hasattr(loader, "mixup_enabled"):
        if cfg.mixup_off_epoch and epoch >= cfg.mixup_off_epoch:
            loader.mixup_enabled = False    # reference :597-599

    batch_time_m, data_time_m = AverageMeter(), AverageMeter()
    losses_m, prec1_m = AverageMeter(), AverageMeter()

    end = time.time()
    num_batches = len(loader)
    last_idx = num_batches - 1
    num_updates = epoch * num_batches + start_batch
    nonfinite_total = 0
    lr = get_learning_rate(state)
    chaos = getattr(resilience, "chaos", None)
    if chaos is not None and not chaos.active:
        chaos = None

    # jax.profiler window (SURVEY §5: the reference has no profiler; an MFU
    # target can't be tuned blind).  Steps [start, start+N) of epoch 0 are
    # traced into <output_dir>/profile — view with TensorBoard or Perfetto.
    # rank-0 only: with a collective (sharded) saver output_dir is set on
    # every rank, but trace/image side effects must not race on shared FS
    profile_n = getattr(cfg, "profile", 0) if epoch == 0 and output_dir \
        and jax.process_index() == 0 else 0
    profile_start = min(10, max(num_batches - profile_n, 0))
    profiling = False

    # Device-side metric scalars are buffered and only materialized at log
    # boundaries: a float() on every step would block the host on each
    # step's completion and serialize dispatch, forfeiting the async-
    # dispatch overlap that replaces the reference's CUDA-stream prefetch.
    # Consequence: batch_time_m.val at a log step absorbs the wait for the
    # whole buffered backlog (so .avg is the accurate number); the plateau
    # scheduler sees a loss avg that is up to log_interval steps stale.
    pending: list = []
    step_exec = None       # multi-process: AOT executable (_compile_aligned)
    first_step = True
    # telemetry window accumulators: how long drains blocked (device-bound
    # time) and how many buffered steps were bad, since the last record
    drain_wait_acc = 0.0
    drain_bad_acc = 0
    profiler = getattr(telemetry, "profiler", None)

    def _drain() -> None:
        nonlocal nonfinite_total, drain_wait_acc, drain_bad_acc
        t_drain = time.monotonic()
        window_bad = 0
        for m, n, step_i in pending:
            loss_value = float(m["loss"])     # host sync, log steps only
            # the device-side guard flag (loss OR grad-norm non-finite)
            # rides the same fetch; absent when the guard is off
            bad = not np.isfinite(loss_value)
            if "nonfinite" in m:
                bad = bad or float(m["nonfinite"]) > 0
            if bad:
                nonfinite_total += 1
                window_bad += 1
                _logger.warning(
                    "non-finite training step at update %d (loss %r%s)",
                    step_i, loss_value,
                    "; update skipped" if "nonfinite" in m else
                    "; UPDATE APPLIED (guard off)")
            else:
                losses_m.update(loss_value, n)
            prec1_m.update(float(m["prec1"]), n)
            if resilience is not None:
                # may raise RewindRequested after K consecutive bad steps
                resilience.observe_step(step_i, loss_value, bad)
        pending.clear()
        # the scalar reads above are the loop's ONLY host syncs, so their
        # block time IS the device-bound share of the window
        drain_wait_acc += time.monotonic() - t_drain
        drain_bad_acc += window_bad

    for batch_idx, batch in enumerate(loader, start=start_batch):
        x, y = batch[0], batch[1]
        last_batch = batch_idx == last_idx
        data_time_m.update(time.time() - end)

        if profile_n and batch_idx == profile_start and not profiling:
            jax.profiler.start_trace(os.path.join(output_dir, "profile"))
            profiling = True

        if chaos is not None and chaos.fires("nanbatch", num_updates):
            # poisoned input → non-finite loss AND grads inside the jitted
            # step (same shape/dtype: no recompile) — exercises the
            # device-side skip and, in a burst, the rewind path
            _logger.warning("chaos: poisoning batch at update %d",
                            num_updates)
            # keep the poisoned batch on the ORIGINAL sharding: the jitted
            # step pins its in_shardings, and an eager full_like lands
            # wherever XLA likes
            x = jax.device_put(jnp.full_like(x, np.nan),
                               getattr(x, "sharding", None)) \
                if hasattr(x, "sharding") else jnp.full_like(x, np.nan)

        step_rng = jax.random.fold_in(rng, num_updates)
        if first_step and step_exec is None:
            step_exec = _compile_aligned(train_step, "train_step",
                                         state, x, y, step_rng)
        first_step = False
        state, metrics = (step_exec or train_step)(state, x, y, step_rng)

        if profiling and (batch_idx + 1 >= profile_start + profile_n
                          or last_batch):
            jax.block_until_ready(metrics["loss"])
            jax.profiler.stop_trace()
            profiling = False
            _logger.info("Profiler trace written to %s",
                         os.path.join(output_dir, "profile"))

        bs = x.shape[0]     # GLOBAL batch: the loader assembles the global
        # sharded array even multi-host (parallel/sharding.py:69-80)
        pending.append((metrics, bs, num_updates))
        num_updates += 1

        if last_batch or batch_idx % cfg.log_interval == 0:
            _drain()
        batch_time_m.update(time.time() - end)
        if telemetry is not None:
            # host floats the loop already holds — no device access
            telemetry.on_step(bs, data_time_m.val, batch_time_m.val)
        if profiler is not None:
            # cheap flag check when idle; manages an active trace window
            profiler.on_step(num_updates, metrics.get("loss"))

        if last_batch or batch_idx % cfg.log_interval == 0:
            lr = get_learning_rate(state) or 0.0
            ets_time = batch_time_m.avg * (num_batches - batch_idx) / 60
            _logger.info(
                "Train:%d [%4d/%d] "
                "Loss:%.5f(%.5f) Prec@1:%7.4f(%7.4f) "
                "Time:%.3f(%.3f)s/batch %.5f(%.5f)s/image "
                "LR:%.3e Data:%.3f(%.3f)s/batch ETS:%.3fmin",
                epoch, batch_idx, num_batches,
                losses_m.val, losses_m.avg, prec1_m.val, prec1_m.avg,
                batch_time_m.val, batch_time_m.avg,
                batch_time_m.val / max(bs // world_size, 1),
                batch_time_m.avg / max(bs // world_size, 1),
                lr, data_time_m.val, data_time_m.avg, ets_time)
            if telemetry is not None:
                # one record per drain cadence: breakdown + JSONL
                telemetry.on_drain(
                    epoch=epoch, batch_idx=batch_idx,
                    num_updates=num_updates, loss=losses_m.avg,
                    prec1=prec1_m.avg, lr=lr, drain_wait_s=drain_wait_acc,
                    nonfinite_steps=drain_bad_acc)
                drain_wait_acc, drain_bad_acc = 0.0, 0
            if profiler is not None:
                profiler.poll()         # PROFILE trigger file: 1 stat/drain
            if cfg.save_images and output_dir and jax.process_index() == 0:
                xd = x
                if getattr(cfg, "stem_s2d", False):
                    # the loader prologue pixel-shuffled the batch for the
                    # s2d stem — un-shuffle so the dump shows real frames,
                    # not 2x2 subpixel phases
                    from ..ops.conv import depth_to_space
                    xd = depth_to_space(np.asarray(x, np.float32))
                save_image_batch(
                    xd, os.path.join(output_dir,
                                     f"train-batch-{batch_idx}.jpg"),
                    img_num=max(1, cfg.resolved_in_chans // 3))

        if cfg.recovery_interval and (
                last_batch or (batch_idx + 1) % cfg.recovery_interval == 0):
            _save_recovery(saver, state, meta, epoch, batch_idx,
                           num_updates)                     # ref :686-689
            if telemetry is not None:
                telemetry.inc("recovery_snapshots_total")

        if chaos is not None and saver is not None and \
                chaos.fires("truncate_ckpt", num_updates):
            _chaos_truncate(saver.curr_recovery_file or saver.find_recovery())

        if lr_scheduler is not None:
            # no stock schedule consumes a per-update metric (plateau is
            # epoch-granular and fed the FRESH eval metric by the runner);
            # one that declares it wants one must get a fresh value, not
            # the log-interval-stale buffered average
            metric = None
            if getattr(lr_scheduler, "wants_update_metric", False):
                _drain()
                metric = losses_m.avg
            new_lr = lr_scheduler.step_update(num_updates=num_updates,
                                              metric=metric)
            if new_lr is not None and new_lr != lr:
                state = set_learning_rate(state, new_lr)

        if resilience is not None:
            resilience.heartbeat(f"epoch {epoch} batch {batch_idx}/"
                                 f"{num_batches} update {num_updates}")
            if chaos is not None and chaos.fires("sigterm", num_updates):
                _logger.warning("chaos: delivering SIGTERM to self at "
                                "update %d", num_updates)
                os.kill(os.getpid(), signal.SIGTERM)
            stop = resilience.stop_requested
            rewind = False
            if jax.process_count() > 1:
                # host-local verdicts (each host gets its own SIGTERM at
                # its own boundary; a guard streak could in principle
                # diverge) cannot drive lockstep actions one-sidedly.
                # Agree IN-BAND at the drain cadence — a pure function of
                # loop indices every host walks identically, so the
                # collective cannot one-side — then every host stops /
                # rewinds at the SAME boundary, which is what makes the
                # snapshot below and the collective restore safe.
                if last_batch or batch_idx % cfg.log_interval == 0:
                    stop, rewind = resilience.sync_verdicts()
                else:
                    stop = rewind = False   # defer to the next boundary
            if rewind:
                raise RewindRequested(resilience.guard.rewind_reason
                                      or "coordinated rewind")
            if stop:
                # stop at THIS step boundary: drain buffered metrics (a
                # host sync, so the state below is the post-step state),
                # write a SYNCHRONOUS recovery snapshot carrying the exact
                # loop position, and unwind — the runner exits with the
                # preemption code so a wrapper can relaunch --auto-resume.
                # Multi-host both save paths (rank-0 gather / collective
                # Orbax write) are lockstep ops — safe exactly because the
                # agreement above put every host here together.
                _drain()
                _save_recovery(saver, state, meta, epoch, batch_idx,
                               num_updates, sync=True)
                if telemetry is not None:
                    telemetry.inc("recovery_snapshots_total")
                raise Preempted(epoch, batch_idx, resilience.stop_signum)
        end = time.time()

    return state, OrderedDict([("loss", losses_m.avg),
                               ("prec1", prec1_m.avg),
                               ("learning_rate", lr),
                               ("nonfinite", nonfinite_total)])


def _save_recovery(saver, state, meta, epoch: int, batch_idx: int,
                   num_updates: int, sync: bool = False) -> None:
    """In-epoch recovery snapshot with exact loop position in the meta.

    EVERY rank calls this. Collective (sharded) saver: the save itself is
    the cross-host path — all ranks drive it, no gather. Otherwise every
    rank enters the gather and only rank 0 (the one holding a saver)
    writes.  ``num_updates`` is the update count AFTER ``batch_idx``
    completed, i.e. the value to continue with at ``batch_idx + 1``.
    """
    meta = dict(meta or {}, num_updates=num_updates)
    if saver is not None and saver.collective:
        saver.save_recovery(state, meta, epoch, batch_idx=batch_idx)
    else:
        from .checkpoint import replicate_for_save
        save_state = replicate_for_save(state) \
            if jax.process_count() > 1 else state
        if saver is not None:
            saver.save_recovery(save_state, meta, epoch,
                                batch_idx=batch_idx, sync=sync)


def _chaos_truncate(path: str) -> None:
    """Chaos point: tear the newest recovery file in half, as a crash mid
    ``os.replace``-less write would (exercises the CheckpointCorrupt
    fallback chain in --auto-resume)."""
    from .checkpoint import wait_pending_saves
    wait_pending_saves()            # the async write must have landed
    if not path or not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))
    _logger.warning("chaos: truncated checkpoint %s (%d -> %d bytes)",
                    path, size, max(size // 2, 1))


def validate(eval_step: Callable, state: TrainState, loader, cfg,
             log_suffix: str = "", resilience=None
             ) -> "OrderedDict[str, float]":
    """Full-dataset eval (reference validate, train.py:703-767), exact thanks
    to the validity mask on padded batches.  ``resilience`` keeps the stall
    watchdog fed during eval (eval batches are its step completions here)."""
    batch_time_m = AverageMeter()
    losses_m, prec1_m = AverageMeter(), AverageMeter()
    all_scores, all_labels, all_valid = [], [], []
    end = time.time()
    num_batches = len(loader)
    last_idx = num_batches - 1
    log_name = "Test" + log_suffix
    eval_exec = None
    for batch_idx, batch in enumerate(loader):
        x, y = batch[0], batch[1]
        valid = batch[2] if len(batch) > 2 else None
        if batch_idx == 0:
            eval_exec = _compile_aligned(eval_step, "eval_step",
                                         state, x, y, valid)
        metrics = (eval_exec or eval_step)(state, x, y, valid)
        n = float(metrics["count"])
        if n > 0:
            losses_m.update(float(metrics["loss"]), n)
            prec1_m.update(float(metrics["prec1"]), n)
        logits = metrics.get("logits")
        if logits is not None and logits.shape[-1] == 2:
            # P(real): labels are 0=fake / 1=real, so AUC ranks real above
            # fake (the released-checkpoint quality gate, BASELINE.md).
            # Accumulate only this process's rows here; the cross-process
            # gather happens ONCE after the loop (a per-batch allgather
            # would force a host sync every eval batch).
            scores = _host_local_rows(jax.nn.softmax(logits, axis=-1)[:, 1])
            all_scores.append(scores.astype(np.float32).reshape(-1))
            all_labels.append(_host_local_rows(y).reshape(-1))
            all_valid.append(np.ones(len(scores), np.float32) if valid is None
                             else _host_local_rows(valid)
                             .astype(np.float32).reshape(-1))
        batch_time_m.update(time.time() - end)
        if resilience is not None:
            resilience.heartbeat(f"eval batch {batch_idx}/{num_batches}")
        if batch_idx == last_idx or batch_idx % cfg.log_interval == 0:
            _logger.info(
                "%s: [%4d/%d] Time:%.3f(%.3f) "
                "Loss:%.4f(%.4f) Prec@1:%7.4f(%7.4f)",
                log_name, batch_idx, num_batches,
                batch_time_m.val, batch_time_m.avg,
                losses_m.val, losses_m.avg, prec1_m.val, prec1_m.avg)
        end = time.time()
    out = OrderedDict([("loss", losses_m.avg), ("prec1", prec1_m.avg)])
    if all_scores:
        scores = np.concatenate(all_scores)
        labels = np.concatenate(all_labels)
        valids = np.concatenate(all_valid)
        if jax.process_count() > 1:
            # one gather for the whole epoch; AUC is a rank statistic, so
            # cross-process row order is irrelevant
            from jax.experimental import multihost_utils
            scores, labels, valids = multihost_utils.process_allgather(
                (scores, labels, valids), tiled=True)
        out["auc"] = float(auc(scores, labels, valids))
        _logger.info("%s: AUC %.5f", log_name, out["auc"])
    return out


def _compile_aligned(fn, tag: str, *args):
    """Multi-process: AOT-compile a step, barrier, return the executable.

    Cross-process collective-context creation (gloo on CPU; similar
    rendezvous elsewhere) has a short (~30 s) deadline that fires during
    the FIRST execution if another rank is still jit-compiling — and jit
    compilation is host-synchronous, so per-rank compile skew (minutes on
    contended hosts) lands entirely between one rank's enqueue and the
    other's.  Compiling ahead-of-time and meeting at a barrier puts every
    rank's first execution within milliseconds; the returned executable is
    then used for EVERY step (batch shapes are static), so nothing
    compiles twice.  Returns None (caller keeps the plain jit path) for
    single-process runs or if AOT lowering fails.
    """
    if jax.process_count() <= 1 or not hasattr(fn, "lower"):
        return None
    # memoize on the jitted-function object (built once per run): later
    # epochs / validate calls reuse the executable with no recompile and
    # no extra barrier
    exe = getattr(fn, "_aligned_exec", None)
    if exe is not None:
        return exe
    try:
        exe = fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 — alignment must never kill a run
        _logger.warning("%s pre-compile failed (%r); continuing on the "
                        "plain jit path", tag, e)
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"{tag}_compiled")
    if exe is not None:
        try:
            fn._aligned_exec = exe
        except AttributeError:
            pass                       # non-writable callables: recompile
    return exe


def _host_local_rows(a) -> np.ndarray:
    """This process's rows of an axis-0-sharded array, as numpy.

    Single-process (and plain numpy input): the whole array.  Multi-process:
    the addressable shards, deduplicated by row range (a replicated array has
    one full copy per local device) and stitched in row order.
    """
    if isinstance(a, np.ndarray) or jax.process_count() == 1:
        return np.asarray(a)
    uniq = {}
    for s in a.addressable_shards:
        idx = s.index[0] if s.index else slice(None)
        uniq.setdefault((idx.start, idx.stop), s)
    shards = [uniq[k] for k in sorted(uniq, key=lambda t: t[0] or 0)]
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
