"""Train state: one pytree carrying everything the train step mutates.

The reference scatters mutable training state across the torch module
(params + BN buffers), the optimizer object, apex AMP, and a deep-copied EMA
module.  Here it is a single immutable pytree — params, batch_stats,
opt_state, EMA — threaded through the jitted step with donated buffers, so
the whole update is in-place on device and checkpointing is one
``to_state_dict``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

__all__ = ["TrainState", "create_train_state", "set_learning_rate",
           "get_learning_rate"]


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    ema: Optional[Any] = None          # {'params':…, 'batch_stats':…} or None

    @property
    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}

    @property
    def ema_variables(self):
        return self.ema if self.ema is not None else self.variables


def _all_single_device(tree: Any) -> bool:
    from jax.sharding import SingleDeviceSharding
    for x in jax.tree.leaves(tree):
        s = getattr(x, "sharding", None)
        if s is not None and not isinstance(s, SingleDeviceSharding):
            return False
    return True


def create_train_state(variables: Any, tx: optax.GradientTransformation,
                       with_ema: bool = False,
                       donate: bool = True) -> TrainState:
    """Build the initial :class:`TrainState` from init/loaded ``variables``.

    By default ``variables`` is CONSUMED on the single-device path (buffers
    donated into the state — accessing them afterwards raises a
    donated-buffer error); pass ``donate=False`` to keep the input tree
    live (at the cost of one params+stats copy), e.g. for param-norm
    logging or building a second state from the same tree.  Mesh-sharded
    inputs are never donated.
    """
    from ..utils.ema import init_ema

    def build(variables: Any) -> TrainState:
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=tx.init(params),
            ema=init_ema({"params": params, "batch_stats": batch_stats})
            if with_ema else None)

    # Single-device inputs run as ONE jitted program: eager ``tx.init`` plus
    # the EMA clone dispatch O(param-leaves) ops, pathological on
    # high-dispatch-latency backends (the axon TPU relay: >10 min for an
    # EfficientNet).  ``variables`` is donated — the state takes ownership
    # of the buffers like the eager path's aliasing did; without donation a
    # full params+stats copy stays live as long as the caller's reference
    # (flagship-scale models care).  Mesh-sharded inputs
    # (tp/fsdp/multi-process) stay eager: ``zeros_like`` inherits each
    # param's sharding exactly, the invariant the checkpoint-resume
    # re-layout and the FSDP opt-state memory footprint both rely on,
    # whereas jit output sharding is GSPMD's choice (observed: replicated
    # opt_state on a (data, model) mesh).
    if _all_single_device(variables):
        return jax.jit(build, donate_argnums=0 if donate else ())(variables)
    return build(variables)


def _find_hyperparams(opt_state):
    """Locate the (path, InjectHyperparamsState) nodes holding hyperparams."""
    return [s for s in jax.tree.leaves(
        opt_state, is_leaf=lambda x: hasattr(x, "hyperparams"))
        if hasattr(s, "hyperparams")]


def set_learning_rate(state: TrainState, lr: float) -> TrainState:
    """Rewrite the injected learning rate (the reference's
    ``param_group['lr']`` rewrite, scheduler.py:81-85) without recompiling."""
    def rewrite(node):
        if hasattr(node, "hyperparams") and "learning_rate" in node.hyperparams:
            hp = dict(node.hyperparams)
            hp["learning_rate"] = jnp.asarray(
                lr, jnp.asarray(hp["learning_rate"]).dtype)
            return node._replace(hyperparams=hp)
        return node
    opt_state = jax.tree.map(
        rewrite, state.opt_state,
        is_leaf=lambda x: hasattr(x, "hyperparams"))
    return state.replace(opt_state=opt_state)


def get_learning_rate(state: TrainState) -> Optional[float]:
    nodes = _find_hyperparams(state.opt_state)
    for n in nodes:
        if "learning_rate" in n.hyperparams:
            return float(n.hyperparams["learning_rate"])
    return None
