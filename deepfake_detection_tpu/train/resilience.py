"""Training resilience layer: preemption-safe stop, NaN/spike guard, stall
watchdog.

The reference stack dies wholesale on any fault: an ``mp.spawn`` worker
fault kills the job, a preempted host restarts from the last *epoch*
boundary, a NaN step silently poisons the params, and a hung collective
hangs forever.  This module gives the runner four coordinated defenses:

* :class:`PreemptionHandler` — SIGTERM/SIGINT request a stop at the next
  step boundary; the trainer then writes a *synchronous* recovery snapshot
  carrying the exact loop position and the run exits :data:`EXIT_PREEMPTED`
  so a restart wrapper (scripts/train.sh) can relaunch into
  ``--auto-resume``.  A second signal falls through to the original
  handler (an impatient operator can still hard-kill).
* :class:`AnomalyGuard` — host-side policy fed at the trainer's existing
  metric-drain cadence (no extra device syncs): counts non-finite steps,
  flags loss spikes against rolling robust statistics (median/MAD), and
  after K *consecutive* bad steps raises :class:`RewindRequested` so the
  runner restores the last recovery snapshot instead of continuing on
  corrupted state.  The device-side skip (train/steps.py ``nonfinite_guard``)
  keeps params finite in the meantime.
* :class:`StallWatchdog` — a monitor thread fed by step-completion
  heartbeats (the shm ring's worker-heartbeat idiom, one level up).  On
  timeout it dumps every Python thread's stack plus the loop position and
  aborts with :data:`EXIT_WATCHDOG` — turning a silent multi-hour hang
  (stuck collective, wedged loader) into a restartable event.
* :class:`Resilience` — the facade the runner owns: installs/restores the
  signal handlers (context manager, so in-process library use — tests —
  leaves no global state behind), carries the chaos injector, the rewind
  budget, and the watchdog.

Multi-host notes: guard decisions are deterministic functions of the
*replicated* loss/nonfinite scalars, so every host raises the same rewind
at the same step and the collective (sharded) restore stays in lockstep.
The preemption flag however is host-local — on multi-host deployments the
watchdog + restart-wrapper path (whole-job relaunch into ``--auto-resume``)
is the supported preemption story; see ROADMAP open items.
"""

from __future__ import annotations

import faulthandler
import logging
import signal
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..chaos import ChaosInjector, chaos_from_env

_logger = logging.getLogger(__name__)

__all__ = ["EXIT_PREEMPTED", "EXIT_WATCHDOG", "Preempted", "RewindRequested",
           "PreemptionHandler", "AnomalyGuard", "StallWatchdog", "Resilience"]

#: exit code after a signal-requested stop with a recovery snapshot on disk
#: (os.EX_TEMPFAIL: "try again later" — the restart wrapper relaunches)
EXIT_PREEMPTED = 75
#: exit code of a stall-watchdog abort (distinct from EXIT_PREEMPTED so the
#: wrapper can count the two failure classes separately if it wants to)
EXIT_WATCHDOG = 85


class Preempted(Exception):
    """Raised by the trainer at a step boundary after a stop request; the
    recovery snapshot is already on disk when this propagates."""

    def __init__(self, epoch: int, batch_idx: int, signum: int):
        super().__init__(
            f"preempted by signal {signum} at epoch {epoch} "
            f"batch {batch_idx}; recovery snapshot written")
        self.epoch = epoch
        self.batch_idx = batch_idx
        self.signum = signum


class RewindRequested(Exception):
    """Raised by the guard when training should rewind to the last
    recovery snapshot instead of continuing on suspect state."""


class PreemptionHandler:
    """First SIGTERM/SIGINT sets a flag checked at step boundaries; a
    second delivery restores the original disposition and re-raises, so a
    stuck run can still be killed the ordinary way."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._previous: dict = {}
        self.stop_requested = False
        self.signum: Optional[int] = None

    def install(self) -> bool:
        """Install handlers; False when not possible (non-main thread)."""
        try:
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handle)
        except ValueError:          # signal only works in the main thread
            self.uninstall()
            return False
        return True

    def uninstall(self) -> None:
        for s, prev in list(self._previous.items()):
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()

    def _handle(self, signum, frame) -> None:
        if self.stop_requested:
            # second signal: hand control back to the original handler
            # (default SIGTERM kills; SIGINT raises KeyboardInterrupt)
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.stop_requested = True
        self.signum = signum
        _logger.warning(
            "signal %d received: stopping at the next step boundary "
            "(second signal force-kills)", signum)


class AnomalyGuard:
    """Host-side anomaly policy over per-step loss scalars.

    Fed from the trainer's metric drain (the only place the host reads
    device scalars anyway).  Three signals combine into one "bad step"
    verdict:

    * the device-side non-finite flag (loss or global grad-norm),
    * a non-finite loss read on host (covers guard-off steps), and
    * a loss spike: ``|loss - median| > zmax * 1.4826 * MAD`` over the last
      ``spike_window`` *accepted* losses (robust statistics — a previous
      spike does not drag the baseline; MAD is floored so a flat early
      window cannot divide by ~0).

    ``rewind_after`` consecutive bad steps raise :class:`RewindRequested`.
    Isolated bad steps only count (the device-side skip already protected
    the params); the streak resets on any good step and on rewind.
    """

    def __init__(self, spike_window: int = 0, spike_zmax: float = 8.0,
                 rewind_after: int = 3):
        self.spike_window = int(spike_window)
        self.spike_zmax = float(spike_zmax)
        self.rewind_after = max(1, int(rewind_after))
        self._hist: deque = deque(maxlen=max(self.spike_window, 1))
        self.bad_streak = 0
        self.nonfinite_total = 0
        self.spike_total = 0

    def is_spike(self, loss: float) -> bool:
        if self.spike_window <= 0 or len(self._hist) < self.spike_window:
            return False
        med = float(np.median(self._hist))
        mad = float(np.median(np.abs(np.asarray(self._hist) - med)))
        scale = max(1.4826 * mad, 1e-3 * max(abs(med), 1.0))
        return abs(loss - med) > self.spike_zmax * scale

    def observe(self, step_index: int, loss: float,
                nonfinite: bool) -> bool:
        """Record one step; returns True when the step was bad.  Raises
        :class:`RewindRequested` on the ``rewind_after``-th consecutive
        bad step."""
        bad = bool(nonfinite) or not np.isfinite(loss)
        if bad:
            self.nonfinite_total += 1
        elif self.is_spike(loss):
            bad = True
            self.spike_total += 1
            _logger.warning(
                "loss spike at update %d: %.5f vs rolling median %.5f",
                step_index, loss, float(np.median(self._hist)))
        else:
            self._hist.append(float(loss))
        if not bad:
            self.bad_streak = 0
            return False
        self.bad_streak += 1
        if self.bad_streak >= self.rewind_after:
            raise RewindRequested(
                f"{self.bad_streak} consecutive bad steps "
                f"(last at update {step_index}, loss {loss!r})")
        return True

    def reset_streak(self) -> None:
        self.bad_streak = 0


class StallWatchdog:
    """Monitor thread fed by step-completion heartbeats.

    ``timeout`` seconds without a :meth:`beat` → dump all Python thread
    stacks + the loop position to stderr and abort the process with
    :data:`EXIT_WATCHDOG`.  ``os._exit`` semantics (via the injectable
    ``exit_fn``) are deliberate: a wedged collective or a deadlocked
    loader thread would block any graceful teardown path.

    The window before the FIRST beat is ``first_grace`` × longer: the
    first train step XLA-compiles (minutes at flagship scale) with no
    chance to heartbeat, and a watchdog sized to steady-state step time
    would otherwise abort during compile on every relaunch — a restart
    loop that never completes a step.  Size ``timeout`` itself to cover
    the post-warmup stragglers (a first *eval* compile, a slow epoch
    boundary) — a few multiples of step time is too tight.
    """

    def __init__(self, timeout: float,
                 position_fn: Optional[Callable[[], str]] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 first_grace: float = 10.0):
        self.timeout = float(timeout)
        self.first_grace = max(1.0, float(first_grace))
        self._position_fn = position_fn or (lambda: "<unknown>")
        self._exit_fn = exit_fn
        self._last = time.monotonic()
        self._seen_beat = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last = time.monotonic()
        self._seen_beat = True

    def start(self) -> None:
        if self.timeout <= 0 or self._thread is not None:
            return
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dfd-stall-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        poll = max(0.05, min(self.timeout / 4.0, 5.0))
        while not self._stop.wait(poll):
            idle = time.monotonic() - self._last
            limit = self.timeout if self._seen_beat \
                else self.timeout * self.first_grace
            if idle <= limit:
                continue
            self._fire(idle)
            return

    def _fire(self, idle: float) -> None:
        msg = (f"stall watchdog: no step completed for {idle:.1f}s "
               f"(timeout {self.timeout:.1f}s) at {self._position_fn()}; "
               f"dumping thread stacks and aborting with exit code "
               f"{EXIT_WATCHDOG}")
        _logger.critical(msg)
        try:
            print(msg, file=sys.stderr, flush=True)
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 — the abort must still happen
            pass
        if self._exit_fn is not None:
            self._exit_fn(EXIT_WATCHDOG)
        else:
            import os
            os._exit(EXIT_WATCHDOG)


class Resilience:
    """Everything the runner threads through the hot loop, in one handle.

    Built by :meth:`from_config`; used as a context manager so signal
    handlers are always restored (the runner is also called in-process by
    tests and by programmatic users).
    """

    def __init__(self, preemption: Optional[PreemptionHandler] = None,
                 guard: Optional[AnomalyGuard] = None,
                 watchdog: Optional[StallWatchdog] = None,
                 chaos: Optional[ChaosInjector] = None,
                 rewind_limit: int = 2):
        self.preemption = preemption
        self.guard = guard
        self.watchdog = watchdog
        self.chaos = chaos if chaos is not None else ChaosInjector("")
        self.rewinds_left = max(0, int(rewind_limit))
        self.position = "<not started>"

    @classmethod
    def from_config(cls, cfg) -> "Resilience":
        guard = None
        if cfg.guard_nonfinite != "off" or cfg.guard_spike_window > 0:
            guard = AnomalyGuard(spike_window=cfg.guard_spike_window,
                                 spike_zmax=cfg.guard_spike_zmax,
                                 rewind_after=cfg.guard_rewind_after)
        self = cls(preemption=PreemptionHandler(), guard=guard,
                   chaos=chaos_from_env(),
                   rewind_limit=cfg.guard_rewind_limit)
        if cfg.watchdog_timeout > 0:
            self.watchdog = StallWatchdog(
                cfg.watchdog_timeout, position_fn=lambda: self.position)
        return self

    # -- lifecycle -----------------------------------------------------
    def __enter__(self) -> "Resilience":
        if self.preemption is not None and not self.preemption.install():
            _logger.warning("not in the main thread: preemption signal "
                            "handlers not installed")
            self.preemption = None
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def __exit__(self, *exc) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.preemption is not None:
            self.preemption.uninstall()

    # -- hot-loop hooks (all cheap; trainer calls them per step) -------
    @property
    def stop_requested(self) -> bool:
        return self.preemption is not None and self.preemption.stop_requested

    @property
    def stop_signum(self) -> int:
        return self.preemption.signum if self.preemption is not None \
            and self.preemption.signum is not None else signal.SIGTERM

    def heartbeat(self, position: Optional[str] = None) -> None:
        if position is not None:
            self.position = position
        if self.watchdog is not None:
            self.watchdog.beat()

    def note(self, position: str) -> None:
        """Update the reported loop position WITHOUT feeding the watchdog
        a beat — for markers that precede the first completed step (epoch
        start), where a beat would end the watchdog's first-compile grace
        window before the compile it exists to protect."""
        self.position = position

    def observe_step(self, step_index: int, loss: float,
                     nonfinite: bool) -> bool:
        """Guard hook; returns True for a bad step, may raise
        :class:`RewindRequested`."""
        if self.guard is None:
            return bool(nonfinite) or not np.isfinite(loss)
        return self.guard.observe(step_index, loss, nonfinite)

    def start_rewind(self, reason: str) -> None:
        """Account one rewind; raises when the budget is exhausted."""
        if self.rewinds_left <= 0:
            raise RuntimeError(
                f"rewind budget exhausted ({reason}); aborting rather "
                "than looping on corrupted state")
        self.rewinds_left -= 1
        if self.guard is not None:
            self.guard.reset_streak()
        _logger.warning("rewinding to the last recovery snapshot (%s); "
                        "%d rewind(s) left", reason, self.rewinds_left)
