"""Training resilience layer: preemption-safe stop, NaN/spike guard, stall
watchdog.

The reference stack dies wholesale on any fault: an ``mp.spawn`` worker
fault kills the job, a preempted host restarts from the last *epoch*
boundary, a NaN step silently poisons the params, and a hung collective
hangs forever.  This module gives the runner four coordinated defenses:

* :class:`PreemptionHandler` — SIGTERM/SIGINT request a stop at the next
  step boundary; the trainer then writes a *synchronous* recovery snapshot
  carrying the exact loop position and the run exits :data:`EXIT_PREEMPTED`
  so a restart wrapper (scripts/train.sh) can relaunch into
  ``--auto-resume``.  A second signal falls through to the original
  handler (an impatient operator can still hard-kill).
* :class:`AnomalyGuard` — host-side policy fed at the trainer's existing
  metric-drain cadence (no extra device syncs): counts non-finite steps,
  flags loss spikes against rolling robust statistics (median/MAD), and
  after K *consecutive* bad steps raises :class:`RewindRequested` so the
  runner restores the last recovery snapshot instead of continuing on
  corrupted state.  The device-side skip (train/steps.py ``nonfinite_guard``)
  keeps params finite in the meantime.
* :class:`StallWatchdog` — a monitor thread fed by step-completion
  heartbeats (the shm ring's worker-heartbeat idiom, one level up).  On
  timeout it dumps every Python thread's stack plus the loop position and
  aborts with :data:`EXIT_WATCHDOG` — turning a silent multi-hour hang
  (stuck collective, wedged loader) into a restartable event.
* :class:`Resilience` — the facade the runner owns: installs/restores the
  signal handlers (context manager, so in-process library use — tests —
  leaves no global state behind), carries the chaos injector, the rewind
  budget, and the watchdog.

Multi-host notes: guard decisions are deterministic functions of the
*replicated* loss/nonfinite scalars, so under normal operation every host
computes the same verdict — but "normal operation" is exactly what a fault
layer must not assume, and the preemption flag is genuinely host-local (each
host gets its own SIGTERM, at its own step boundary).  Multi-process runs
therefore agree on the verdicts IN-BAND: the guard defers its rewind raise
(``coordinated=True``) and :meth:`Resilience.sync_verdicts` max-reduces the
``[stop, rewind]`` flag pair across processes at the trainer's drain cadence
(a deterministic boundary every host reaches, so the collective cannot
one-side).  Any host's verdict wins everywhere, and every host raises
:class:`Preempted` / :class:`RewindRequested` at the SAME boundary — which is
what makes the lockstep recovery snapshot and the collective sharded restore
safe to enter.
"""

from __future__ import annotations

import faulthandler
import itertools
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

import numpy as np

from ..chaos import ChaosInjector, chaos_from_env

_logger = logging.getLogger(__name__)

__all__ = ["EXIT_PREEMPTED", "EXIT_WATCHDOG", "Preempted", "RewindRequested",
           "PreemptionHandler", "AnomalyGuard", "StallWatchdog", "Resilience",
           "allreduce_flags"]

#: exit code after a signal-requested stop with a recovery snapshot on disk
#: (os.EX_TEMPFAIL: "try again later" — the restart wrapper relaunches)
EXIT_PREEMPTED = 75
#: exit code of a stall-watchdog abort (distinct from EXIT_PREEMPTED so the
#: wrapper can count the two failure classes separately if it wants to)
EXIT_WATCHDOG = 85


class Preempted(Exception):
    """Raised by the trainer at a step boundary after a stop request; the
    recovery snapshot is already on disk when this propagates."""

    def __init__(self, epoch: int, batch_idx: int, signum: int):
        super().__init__(
            f"preempted by signal {signum} at epoch {epoch} "
            f"batch {batch_idx}; recovery snapshot written")
        self.epoch = epoch
        self.batch_idx = batch_idx
        self.signum = signum


class RewindRequested(Exception):
    """Raised by the guard when training should rewind to the last
    recovery snapshot instead of continuing on suspect state."""


#: lockstep round counter for :func:`allreduce_flags` key namespacing —
#: advances identically on every host because the trainer only syncs at
#: deterministic loop boundaries
_sync_round = itertools.count()
#: how long one host waits for a peer's verdict before declaring the job
#: wedged; generous — peers reach the same LOOP boundary at skewed wall
#: times (compile variance, straggler steps)
SYNC_TIMEOUT_MS = int(os.environ.get("DFD_VERDICT_SYNC_TIMEOUT_MS",
                                     str(10 * 60 * 1000)))


def allreduce_flags(flags: np.ndarray) -> np.ndarray:
    """Max-reduce a small int32 flag vector across all jax processes.

    The in-band agreement primitive for the host-local verdict scalars
    (preemption stop, guard rewind): any host's 1 becomes every host's 1.
    Runs over the jax.distributed coordination-service KV store — a few
    bytes of gRPC, no XLA computation — so it works on every backend
    (CPU cross-process XLA computations are unimplemented in some jaxlib
    builds) and never competes with the step for device time.

    COLLECTIVE in cadence: every process must call it the same number of
    times, at the same boundary; the trainer guarantees that by syncing
    only at the metric-drain cadence (``last_batch or batch_idx %
    log_interval == 0``), a pure function of loop indices every host walks
    identically.  Single-process runs return the input unchanged without
    touching the runtime.
    """
    import jax                          # lazy: keep this module jax-light
    flags = np.asarray(flags, np.int32)
    if jax.process_count() == 1:
        return flags
    from ..parallel._compat import coordination_client
    client = coordination_client()
    if client is None:  # pragma: no cover - pod runtimes init elsewhere
        raise RuntimeError(
            "multi-process run without a jax.distributed coordination "
            "client: verdict agreement needs the KV store")
    rnd = next(_sync_round)
    me = jax.process_index()
    client.key_value_set(f"dfd/verdict/{rnd}/{me}",
                         ",".join(str(int(v)) for v in flags))
    out = flags.copy()
    for r in range(jax.process_count()):
        if r == me:
            continue
        peer = client.blocking_key_value_get(f"dfd/verdict/{rnd}/{r}",
                                             SYNC_TIMEOUT_MS)
        out = np.maximum(out, np.fromiter(
            (int(v) for v in peer.split(",")), np.int32, len(flags)))
    # a long run syncs every drain boundary — drop a FINISHED round's keys
    # or the coordination service leaks a key per process per round.  The
    # previous round is complete by construction (every peer answered it
    # before writing this one); deleting our own rnd key would race a slow
    # peer's pending get.
    delete = getattr(client, "key_value_delete", None)
    if rnd > 0 and delete is not None:
        delete(f"dfd/verdict/{rnd - 1}/{me}")
    return out


class PreemptionHandler:
    """First SIGTERM/SIGINT sets a flag checked at step boundaries; a
    second delivery restores the original disposition and re-raises, so a
    stuck run can still be killed the ordinary way."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._previous: dict = {}
        self.stop_requested = False
        self.signum: Optional[int] = None

    def install(self) -> bool:
        """Install handlers; False when not possible (non-main thread)."""
        try:
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handle)
        except ValueError:          # signal only works in the main thread
            self.uninstall()
            return False
        return True

    def uninstall(self) -> None:
        for s, prev in list(self._previous.items()):
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()

    def _handle(self, signum, frame) -> None:
        if self.stop_requested:
            # second signal: hand control back to the original handler
            # (default SIGTERM kills; SIGINT raises KeyboardInterrupt)
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.stop_requested = True
        self.signum = signum
        _logger.warning(
            "signal %d received: stopping at the next step boundary "
            "(second signal force-kills)", signum)


class AnomalyGuard:
    """Host-side anomaly policy over per-step loss scalars.

    Fed from the trainer's metric drain (the only place the host reads
    device scalars anyway).  Three signals combine into one "bad step"
    verdict:

    * the device-side non-finite flag (loss or global grad-norm),
    * a non-finite loss read on host (covers guard-off steps), and
    * a loss spike: ``|loss - median| > zmax * 1.4826 * MAD`` over the last
      ``spike_window`` *accepted* losses (robust statistics — a previous
      spike does not drag the baseline; MAD is floored so a flat early
      window cannot divide by ~0).

    ``rewind_after`` consecutive bad steps raise :class:`RewindRequested`.
    Isolated bad steps only count (the device-side skip already protected
    the params); the streak resets on any good step and on rewind.
    """

    def __init__(self, spike_window: int = 0, spike_zmax: float = 8.0,
                 rewind_after: int = 3, coordinated: bool = False):
        self.spike_window = int(spike_window)
        self.spike_zmax = float(spike_zmax)
        self.rewind_after = max(1, int(rewind_after))
        # multi-process: defer the rewind raise — the verdict scalar is
        # max-reduced across hosts (Resilience.sync_verdicts) so every host
        # raises at the same boundary, or none does
        self.coordinated = bool(coordinated)
        self.rewind_wanted = False
        self.rewind_reason = ""
        self._hist: deque = deque(maxlen=max(self.spike_window, 1))
        self.bad_streak = 0
        self.nonfinite_total = 0
        self.spike_total = 0

    def is_spike(self, loss: float) -> bool:
        if self.spike_window <= 0 or len(self._hist) < self.spike_window:
            return False
        med = float(np.median(self._hist))
        mad = float(np.median(np.abs(np.asarray(self._hist) - med)))
        scale = max(1.4826 * mad, 1e-3 * max(abs(med), 1.0))
        return abs(loss - med) > self.spike_zmax * scale

    def observe(self, step_index: int, loss: float,
                nonfinite: bool) -> bool:
        """Record one step; returns True when the step was bad.  Raises
        :class:`RewindRequested` on the ``rewind_after``-th consecutive
        bad step."""
        bad = bool(nonfinite) or not np.isfinite(loss)
        if bad:
            self.nonfinite_total += 1
        elif self.is_spike(loss):
            bad = True
            self.spike_total += 1
            _logger.warning(
                "loss spike at update %d: %.5f vs rolling median %.5f",
                step_index, loss, float(np.median(self._hist)))
        else:
            self._hist.append(float(loss))
        if not bad:
            self.bad_streak = 0
            return False
        self.bad_streak += 1
        if self.bad_streak >= self.rewind_after:
            reason = (f"{self.bad_streak} consecutive bad steps "
                      f"(last at update {step_index}, loss {loss!r})")
            if not self.coordinated:
                raise RewindRequested(reason)
            # multi-process: record the verdict; sync_verdicts raises it on
            # EVERY host at the next drain boundary
            self.rewind_wanted = True
            self.rewind_reason = reason
        return True

    def reset_streak(self) -> None:
        self.bad_streak = 0
        self.rewind_wanted = False
        self.rewind_reason = ""


class StallWatchdog:
    """Monitor thread fed by step-completion heartbeats.

    ``timeout`` seconds without a :meth:`beat` → dump all Python thread
    stacks + the loop position to stderr and abort the process with
    :data:`EXIT_WATCHDOG`.  ``os._exit`` semantics (via the injectable
    ``exit_fn``) are deliberate: a wedged collective or a deadlocked
    loader thread would block any graceful teardown path.

    The window before the FIRST beat is ``first_grace`` × longer: the
    first train step XLA-compiles (minutes at flagship scale) with no
    chance to heartbeat, and a watchdog sized to steady-state step time
    would otherwise abort during compile on every relaunch — a restart
    loop that never completes a step.  Size ``timeout`` itself to cover
    the post-warmup stragglers (a first *eval* compile, a slow epoch
    boundary) — a few multiples of step time is too tight.

    ``dump_path``: the all-thread stack dump also lands in this file
    (``<outdir>/watchdog_dump.txt``) — stderr is routinely lost when the
    restart wrapper relaunches, and a post-mortem needs the stacks.
    Telemetry counters: ``beats_total``; ``near_miss_total`` counts beats
    that arrived with the previous beat older than half the timeout — a
    run skating toward an abort shows up as a rising gauge before it dies.
    """

    def __init__(self, timeout: float,
                 position_fn: Optional[Callable[[], str]] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 first_grace: float = 10.0,
                 dump_path: Optional[str] = None):
        self.timeout = float(timeout)
        self.first_grace = max(1.0, float(first_grace))
        self._position_fn = position_fn or (lambda: "<unknown>")
        self._exit_fn = exit_fn
        self.dump_path = dump_path
        self._last = time.monotonic()
        self._seen_beat = False
        self.beats_total = 0
        self.near_miss_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        now = time.monotonic()
        if self._seen_beat and now - self._last > 0.5 * self.timeout:
            self.near_miss_total += 1
        self._last = now
        self._seen_beat = True
        self.beats_total += 1

    def beat_age(self) -> float:
        """Seconds since the last heartbeat (telemetry gauge)."""
        return time.monotonic() - self._last

    def start(self) -> None:
        if self.timeout <= 0 or self._thread is not None:
            return
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dfd-stall-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        poll = max(0.05, min(self.timeout / 4.0, 5.0))
        while not self._stop.wait(poll):
            idle = time.monotonic() - self._last
            limit = self.timeout if self._seen_beat \
                else self.timeout * self.first_grace
            if idle <= limit:
                continue
            self._fire(idle)
            return

    def _fire(self, idle: float) -> None:
        msg = (f"stall watchdog: no step completed for {idle:.1f}s "
               f"(timeout {self.timeout:.1f}s) at {self._position_fn()}; "
               f"dumping thread stacks and aborting with exit code "
               f"{EXIT_WATCHDOG}")
        _logger.critical(msg)
        try:
            print(msg, file=sys.stderr, flush=True)
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 — the abort must still happen
            pass
        if self.dump_path:
            # stderr is routinely lost when the restart wrapper relaunches
            # — persist the same dump where --auto-resume will find it
            try:
                with open(self.dump_path, "w") as f:
                    f.write(msg + "\n")
                    faulthandler.dump_traceback(file=f, all_threads=True)
            except Exception:  # noqa: BLE001 — the abort must still happen
                pass
        if self._exit_fn is not None:
            self._exit_fn(EXIT_WATCHDOG)
        else:
            import os
            os._exit(EXIT_WATCHDOG)


class Resilience:
    """Everything the runner threads through the hot loop, in one handle.

    Built by :meth:`from_config`; used as a context manager so signal
    handlers are always restored (the runner is also called in-process by
    tests and by programmatic users).
    """

    def __init__(self, preemption: Optional[PreemptionHandler] = None,
                 guard: Optional[AnomalyGuard] = None,
                 watchdog: Optional[StallWatchdog] = None,
                 chaos: Optional[ChaosInjector] = None,
                 rewind_limit: int = 2):
        self.preemption = preemption
        self.guard = guard
        self.watchdog = watchdog
        self.chaos = chaos if chaos is not None else ChaosInjector("")
        self.rewinds_left = max(0, int(rewind_limit))
        self.position = "<not started>"

    @classmethod
    def from_config(cls, cfg, output_dir: str = "") -> "Resilience":
        import jax                      # lazy: keep this module jax-light
        guard = None
        if cfg.guard_nonfinite != "off" or cfg.guard_spike_window > 0:
            guard = AnomalyGuard(spike_window=cfg.guard_spike_window,
                                 spike_zmax=cfg.guard_spike_zmax,
                                 rewind_after=cfg.guard_rewind_after,
                                 coordinated=jax.process_count() > 1)
        self = cls(preemption=PreemptionHandler(), guard=guard,
                   chaos=chaos_from_env(),
                   rewind_limit=cfg.guard_rewind_limit)
        if cfg.watchdog_timeout > 0:
            dump = os.path.join(output_dir, "watchdog_dump.txt") \
                if output_dir else None
            self.watchdog = StallWatchdog(
                cfg.watchdog_timeout, position_fn=lambda: self.position,
                dump_path=dump)
        return self

    # -- lifecycle -----------------------------------------------------
    def __enter__(self) -> "Resilience":
        if self.preemption is not None and not self.preemption.install():
            _logger.warning("not in the main thread: preemption signal "
                            "handlers not installed")
            self.preemption = None
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def __exit__(self, *exc) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.preemption is not None:
            self.preemption.uninstall()

    # -- hot-loop hooks (all cheap; trainer calls them per step) -------
    @property
    def stop_requested(self) -> bool:
        return self.preemption is not None and self.preemption.stop_requested

    @property
    def stop_signum(self) -> int:
        return self.preemption.signum if self.preemption is not None \
            and self.preemption.signum is not None else signal.SIGTERM

    def heartbeat(self, position: Optional[str] = None) -> None:
        if position is not None:
            self.position = position
        if self.watchdog is not None:
            self.watchdog.beat()

    def note(self, position: str) -> None:
        """Update the reported loop position WITHOUT feeding the watchdog
        a beat — for markers that precede the first completed step (epoch
        start), where a beat would end the watchdog's first-compile grace
        window before the compile it exists to protect."""
        self.position = position

    def observe_step(self, step_index: int, loss: float,
                     nonfinite: bool) -> bool:
        """Guard hook; returns True for a bad step, may raise
        :class:`RewindRequested`."""
        if self.guard is None:
            return bool(nonfinite) or not np.isfinite(loss)
        return self.guard.observe(step_index, loss, nonfinite)

    def sync_verdicts(self) -> Tuple[bool, bool]:
        """Multi-host in-band agreement on the ``[stop, rewind]`` verdicts.

        Max-reduces the host-local preemption flag and the guard's deferred
        rewind verdict across processes and returns the agreed ``(stop,
        rewind)`` pair — any host's verdict wins everywhere.  COLLECTIVE:
        call only at a boundary every process reaches (the trainer's drain
        cadence).  A remote host's stop is adopted locally (so this host
        also exits :data:`EXIT_PREEMPTED` and the restart wrapper relaunches
        the whole job), and an agreed rewind resets every host's streak so
        the replayed span starts clean.
        """
        want_stop = self.stop_requested
        want_rewind = self.guard is not None and self.guard.rewind_wanted
        stop, rewind = (bool(v) for v in
                        allreduce_flags(np.array([want_stop, want_rewind],
                                                 np.int32)))
        if stop and not want_stop:
            # adopt the remote stop so stop_signum/exit-code logic runs
            # exactly as if this host had been signalled itself
            if self.preemption is None:
                self.preemption = PreemptionHandler()   # uninstalled is fine
            self.preemption.stop_requested = True
            _logger.warning("adopting a remote host's preemption stop")
        if rewind and self.guard is not None and not self.guard.rewind_reason:
            self.guard.rewind_reason = "remote host requested rewind"
        return stop, rewind

    def start_rewind(self, reason: str) -> None:
        """Account one rewind; raises when the budget is exhausted."""
        if self.rewinds_left <= 0:
            raise RuntimeError(
                f"rewind budget exhausted ({reason}); aborting rather "
                "than looping on corrupted state")
        self.rewinds_left -= 1
        if self.guard is not None:
            self.guard.reset_streak()
        _logger.warning("rewinding to the last recovery snapshot (%s); "
                        "%d rewind(s) left", reason, self.rewinds_left)
