"""Jitted train / eval steps — ONE GSPMD program from 1 chip to a pod.

The reference's per-batch hot loop (``/root/reference/dfd/runners/train.py:
594-700``: forward → loss → accuracy → metric allreduce → backward with DDP
grad allreduce → optimizer step → full device sync → EMA update) becomes ONE
compiled function per step.  XLA fuses the whole thing; there is no per-step
host sync (the runner only blocks on the scalars it logs) and no separate
allreduce launches — gradient reduction is part of the compiled program
riding ICI.

Since ISSUE 12 the step is a plain ``jax.jit`` with ``NamedSharding``
annotations over the unified ``('batch', 'model')`` mesh
(parallel/mesh.py:make_train_mesh) — the shard_map-era dispatch is gone.
``in_shardings``/``out_shardings`` come from the sharding-rule table
(parallel/sharding.py:train_state_shardings) when the caller provides it;
``donate_argnums=(0,)`` keeps the state update in-place on device.  The
same program lowers for an abstract v5e-256 topology exactly as it does
for one chip (tools/bench_multichip.py, tests/test_mesh_aot.py).

Two BN strategies (SURVEY.md §7 hard part #2):

* ``bn_mode='global'`` — BN statistics are computed over the *global*
  batch (XLA inserts the per-layer reductions): semantically apex SyncBN
  (train.py:388-400), always on.
* ``bn_mode='local'`` (default, matches the reference default) — BN
  normalizes each contiguous batch group (one per data-parallel mesh
  slot) with that group's *own* statistics.  This used to be a bespoke
  ``shard_map`` body; it is now a ``with_sharding_constraint`` over the
  batch axis inside the BN layer itself (ops/norm.py:local_stats_scope),
  so there are still no per-layer collectives in the forward — XLA keeps
  every group's statistics local to its mesh slot — and the running stats
  are updated with the group mean (what the old per-device update + one
  ``lax.pmean`` produced).

Both modes produce bit-identical optimizer updates given the same gradients;
they differ only in BN normalization statistics (per-group vs global).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..losses import cross_entropy
from ..utils.ema import update_ema
from ..utils.metrics import accuracy
from .state import TrainState

__all__ = ["make_train_step", "make_eval_step"]


def _clip_grads(grads, clip_grad: Optional[float]):
    if not clip_grad:
        return grads
    gnorm = optax.global_norm(grads)
    scale = jnp.minimum(1.0, clip_grad / (gnorm + 1e-6))
    return jax.tree.map(lambda g: g * scale, grads)


def make_train_step(model, tx: optax.GradientTransformation,
                    loss_fn: Callable = cross_entropy,
                    mesh: Optional[Mesh] = None, axis: Optional[str] = None,
                    bn_mode: str = "local", ema_decay: float = 0.0,
                    clip_grad: Optional[float] = None,
                    grad_accum: int = 1,
                    donate: bool = True,
                    nonfinite_guard: bool = False,
                    state_shardings: Optional[Any] = None) -> Callable:
    """Build ``train_step(state, x, y, rng) -> (state, metrics)``.

    ``x`` is the (globally) batch-sharded NHWC input, ``y`` int labels or
    soft targets.  ``metrics`` = {'loss', 'prec1'} global-batch scalars
    (replaces the per-step ``reduce_tensor`` calls, train.py:625-627).

    ``mesh`` + ``axis`` (default: the mesh's own data axis) select the
    unified GSPMD path: the batch is constrained to ``P(axis)``, local-BN
    statistics group over the mesh's batch extent, and — when
    ``state_shardings`` (the parallel/sharding.py rule table) is given —
    the jit carries explicit ``in_shardings``/``out_shardings`` so the
    compiled executable's I/O layout is pinned, CI-assertable and
    donation-aliased.  Callers passing ``state_shardings`` must place the
    state accordingly first (``place_train_state``).

    ``grad_accum > 1`` splits the batch into that many microbatches inside
    the compiled step (a ``lax.scan``): gradients are averaged across
    microbatches before ONE optimizer update, so effective batch = what the
    reference reaches with more GPUs (no reference analog — the standard
    TPU lever for the flagship 600²×12 config on few chips).  BN stats
    thread through the scan (each microbatch updates the running stats,
    like sequential smaller steps would).

    ``nonfinite_guard`` adds a device-side all-finite check on the loss and
    the global grad-norm: a bad step SELECTS the previous state (params,
    BN stats, optimizer moments, EMA, step counter all unchanged — a skip,
    not a zero-grad update, since NaN grads would still poison Adam/RMSProp
    moments through ``tx.update``) and reports ``metrics['nonfinite']`` = 1.
    One scalar flag rides the existing metrics fetch — no extra host syncs.
    The reference *meter* dropped NaN losses while the poisoned update was
    applied anyway (the exact failure this guard closes).
    """
    assert bn_mode in ("local", "global"), bn_mode
    assert grad_accum >= 1

    def forward_backward_one(params, batch_stats, x, y, rng):
        def lossf(p):
            variables = {"params": p, "batch_stats": batch_stats}
            out = model.apply(variables, x, training=True,
                              mutable=["batch_stats"], rngs={"dropout": rng})
            logits, mut = out
            return loss_fn(logits, y), (logits, mut["batch_stats"])
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            lossf, has_aux=True)(params)
        prec1 = accuracy(logits, y)
        return loss, grads, new_stats, prec1

    def forward_backward(params, batch_stats, x, y, rng):
        if grad_accum == 1:
            return forward_backward_one(params, batch_stats, x, y, rng)
        b = x.shape[0]
        assert b % grad_accum == 0, (b, grad_accum)
        # strided split (row j of microbatch i = global row j*A + i): under
        # a data-sharded batch each device keeps 1/A of ITS OWN rows per
        # microbatch, so no per-iteration cross-device reshuffle is needed
        # (a contiguous split would put microbatch 0 on the first dp/A
        # devices only); gradient averaging is partition-invariant
        xm = jnp.moveaxis(
            x.reshape((b // grad_accum, grad_accum) + x.shape[1:]), 1, 0)
        ym = jnp.moveaxis(
            y.reshape((b // grad_accum, grad_accum) + y.shape[1:]), 1, 0)

        def micro(carry, inp):
            stats, gsum, lsum, psum_ = carry
            xi, yi, i = inp
            loss, grads, stats, prec1 = forward_backward_one(
                params, stats, xi, yi, jax.random.fold_in(rng, i))
            gsum = jax.tree.map(jnp.add, gsum, grads)
            return (stats, gsum, lsum + loss, psum_ + prec1), None

        g0 = jax.tree.map(jnp.zeros_like, params)
        z = jnp.zeros((), jnp.float32)
        (new_stats, gsum, lsum, psum_), _ = jax.lax.scan(
            micro, (batch_stats, g0, z, z), (xm, ym, jnp.arange(grad_accum)))
        inv = 1.0 / grad_accum
        grads = jax.tree.map(lambda g: g * inv, gsum)
        return lsum * inv, grads, new_stats, psum_ * inv

    def apply_updates(state: TrainState, grads, new_stats, loss, prec1):
        grads = _clip_grads(grads, clip_grad)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ema = state.ema
        if ema is not None:
            ema = update_ema(ema, {"params": params,
                                   "batch_stats": new_stats}, ema_decay)
        new_state = state.replace(step=state.step + 1, params=params,
                                  batch_stats=new_stats, opt_state=opt_state,
                                  ema=ema)
        metrics = {"loss": loss, "prec1": prec1}
        if nonfinite_guard:
            # the clipped-grad norm: clipping rescales by a finite factor
            # (or NaN-propagates), so finiteness is unchanged vs raw grads
            # and the norm is reused-shape-wise from the clip when present
            gnorm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            # scalar-pred select per leaf: cheap (one fused select each)
            # and total — moments, EMA, BN stats and the step counter all
            # roll back together, leaving the state exactly pre-step
            new_state = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_state, state)
            metrics["nonfinite"] = (~ok).astype(jnp.float32)
            metrics["gnorm"] = gnorm
        return new_state, metrics

    if mesh is None:
        def step(state: TrainState, x, y, rng):
            loss, grads, new_stats, prec1 = forward_backward(
                state.params, state.batch_stats, x, y, rng)
            return apply_updates(state, grads, new_stats, loss, prec1)
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    # ---- unified GSPMD path: plain jit over the mesh -------------------
    from ..parallel.mesh import data_axis_name
    axis = axis or data_axis_name(mesh)
    dp = int(mesh.shape[axis])
    batch_sh = NamedSharding(mesh, P(axis))
    if bn_mode == "local" and dp > 1:
        from ..ops.norm import local_stats_scope

        def bn_scope():
            return local_stats_scope(dp, batch_sh)
    else:
        bn_scope = contextlib.nullcontext

    def step(state: TrainState, x, y, rng):
        # pin the batch to the batch axis: with inferred in_shardings this
        # is what keeps GSPMD from gathering the batch onto one device; the
        # BN grouping constraint inside the scope does the rest of the
        # local-stats layout
        x = lax.with_sharding_constraint(x, batch_sh)
        y = lax.with_sharding_constraint(y, batch_sh)
        with bn_scope():        # entered at TRACE time (ops/norm.py)
            loss, grads, new_stats, prec1 = forward_backward(
                state.params, state.batch_stats, x, y, rng)
        return apply_updates(state, grads, new_stats, loss, prec1)

    jit_kwargs: Dict[str, Any] = {}
    if state_shardings is not None:
        rep = NamedSharding(mesh, P())
        jit_kwargs["in_shardings"] = (state_shardings, batch_sh, batch_sh,
                                      rep)
        # metrics is a dict of global scalars — a single replicated
        # sharding is a valid prefix pytree for it
        jit_kwargs["out_shardings"] = (state_shardings, rep)
    return jax.jit(step, donate_argnums=(0,) if donate else (),
                   **jit_kwargs)


def make_eval_step(model, loss_fn: Callable = cross_entropy,
                   use_ema: bool = False) -> Callable:
    """Build ``eval_step(state, x, y, valid) -> metrics``.

    ``valid`` masks padded duplicates from the ordered sharded sampler so
    validation is exact (the reference accepted the duplicate error,
    loader.py:794-796).  Returns {'loss', 'prec1', 'count'} where loss/prec1
    are means over valid samples in this batch (reference validate,
    train.py:703-767).
    """

    @jax.jit
    def step(state: TrainState, x, y,
             valid: Optional[jnp.ndarray] = None) -> Dict[str, jnp.ndarray]:
        variables = state.ema_variables if use_ema else state.variables
        logits = model.apply(variables, x, training=False)
        loss = loss_fn(logits, y, weight=valid)
        prec1 = accuracy(logits, y, weight=valid)
        count = (valid.sum() if valid is not None
                 else jnp.asarray(x.shape[0]))
        return {"loss": loss, "prec1": prec1, "count": count,
                "logits": logits}

    return step
