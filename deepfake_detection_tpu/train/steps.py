"""Jitted train / eval steps.

The reference's per-batch hot loop (``/root/reference/dfd/runners/train.py:
594-700``: forward → loss → accuracy → metric allreduce → backward with DDP
grad allreduce → optimizer step → full device sync → EMA update) becomes ONE
compiled function per step.  XLA fuses the whole thing; there is no per-step
host sync (the runner only blocks on the scalars it logs) and no separate
allreduce launches — gradient reduction is part of the compiled program
riding ICI.

Two BN strategies (SURVEY.md §7 hard part #2):

* ``bn_mode='global'`` — plain ``jit`` over the data-sharded batch.  BN
  statistics are computed over the *global* batch (XLA inserts the per-layer
  reductions): semantically apex SyncBN (train.py:388-400), always on.
* ``bn_mode='local'`` (default, matches the reference default) — the step is
  a ``shard_map`` over the data axis: BN normalizes with the *local* shard's
  statistics (no per-layer collectives in the forward — faster), gradients
  and metrics are ``lax.pmean``-ed once, and the BN running stats are
  pmean-ed once per step, keeping the state replicated.  The per-step stat
  pmean is the reference's ``--dist-bn reduce`` (utils.py:263-274) applied
  continuously instead of per-epoch — required because pjit state is
  logically one copy.

Both modes produce bit-identical optimizer updates given the same gradients;
they differ only in BN normalization statistics (per-shard vs global).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..losses import cross_entropy
from ..utils.ema import update_ema
from ..utils.metrics import accuracy, masked_mean
from .state import TrainState

__all__ = ["make_train_step", "make_eval_step"]


def _clip_grads(grads, clip_grad: Optional[float]):
    if not clip_grad:
        return grads
    gnorm = optax.global_norm(grads)
    scale = jnp.minimum(1.0, clip_grad / (gnorm + 1e-6))
    return jax.tree.map(lambda g: g * scale, grads)


def make_train_step(model, tx: optax.GradientTransformation,
                    loss_fn: Callable = cross_entropy,
                    mesh: Optional[Mesh] = None, axis: str = "data",
                    bn_mode: str = "local", ema_decay: float = 0.0,
                    clip_grad: Optional[float] = None,
                    grad_accum: int = 1,
                    donate: bool = True,
                    nonfinite_guard: bool = False) -> Callable:
    """Build ``train_step(state, x, y, rng) -> (state, metrics)``.

    ``x`` is the (globally) batch-sharded NHWC input, ``y`` int labels or
    soft targets.  ``metrics`` = {'loss', 'prec1'} global-batch scalars
    (replaces the per-step ``reduce_tensor`` calls, train.py:625-627).

    ``grad_accum > 1`` splits the batch into that many microbatches inside
    the compiled step (a ``lax.scan``): gradients are averaged across
    microbatches before ONE optimizer update, so effective batch = what the
    reference reaches with more GPUs (no reference analog — the standard
    TPU lever for the flagship 600²×12 config on few chips).  BN stats
    thread through the scan (each microbatch updates the running stats,
    like sequential smaller steps would).

    ``nonfinite_guard`` adds a device-side all-finite check on the loss and
    the global grad-norm: a bad step SELECTS the previous state (params,
    BN stats, optimizer moments, EMA, step counter all unchanged — a skip,
    not a zero-grad update, since NaN grads would still poison Adam/RMSProp
    moments through ``tx.update``) and reports ``metrics['nonfinite']`` = 1.
    One scalar flag rides the existing metrics fetch — no extra host syncs.
    The reference *meter* dropped NaN losses while the poisoned update was
    applied anyway (the exact failure this guard closes).
    """
    assert bn_mode in ("local", "global"), bn_mode
    assert grad_accum >= 1

    def forward_backward_one(params, batch_stats, x, y, rng):
        def lossf(p):
            variables = {"params": p, "batch_stats": batch_stats}
            out = model.apply(variables, x, training=True,
                              mutable=["batch_stats"], rngs={"dropout": rng})
            logits, mut = out
            return loss_fn(logits, y), (logits, mut["batch_stats"])
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            lossf, has_aux=True)(params)
        prec1 = accuracy(logits, y)
        return loss, grads, new_stats, prec1

    def forward_backward(params, batch_stats, x, y, rng, vary_axis=None):
        if grad_accum == 1:
            return forward_backward_one(params, batch_stats, x, y, rng)
        b = x.shape[0]
        assert b % grad_accum == 0, (b, grad_accum)
        # strided split (row j of microbatch i = global row j*A + i): under
        # a data-sharded batch each device keeps 1/A of ITS OWN rows per
        # microbatch, so the jit/TP path needs no per-iteration reshuffle
        # (a contiguous split would put microbatch 0 on the first dp/A
        # devices only); gradient averaging is partition-invariant
        xm = jnp.moveaxis(
            x.reshape((b // grad_accum, grad_accum) + x.shape[1:]), 1, 0)
        ym = jnp.moveaxis(
            y.reshape((b // grad_accum, grad_accum) + y.shape[1:]), 1, 0)

        def micro(carry, inp):
            stats, gsum, lsum, psum_ = carry
            xi, yi, i = inp
            loss, grads, stats, prec1 = forward_backward_one(
                params, stats, xi, yi, jax.random.fold_in(rng, i))
            gsum = jax.tree.map(jnp.add, gsum, grads)
            return (stats, gsum, lsum + loss, psum_ + prec1), None

        g0 = jax.tree.map(jnp.zeros_like, params)
        z = jnp.zeros((), jnp.float32)
        carry0 = (batch_stats, g0, z, z)
        if vary_axis is not None:
            # inside shard_map the microbatch outputs are device-varying;
            # the scan carry type must match from step 0 (a no-op on
            # pre-0.6 jax, which has no varying-manual-axes type system)
            from ..parallel._compat import pcast_varying
            carry0 = jax.tree.map(
                lambda v: pcast_varying(v, vary_axis), carry0)
        (new_stats, gsum, lsum, psum_), _ = jax.lax.scan(
            micro, carry0, (xm, ym, jnp.arange(grad_accum)))
        inv = 1.0 / grad_accum
        grads = jax.tree.map(lambda g: g * inv, gsum)
        return lsum * inv, grads, new_stats, psum_ * inv

    def apply_updates(state: TrainState, grads, new_stats, loss, prec1):
        grads = _clip_grads(grads, clip_grad)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ema = state.ema
        if ema is not None:
            ema = update_ema(ema, {"params": params,
                                   "batch_stats": new_stats}, ema_decay)
        new_state = state.replace(step=state.step + 1, params=params,
                                  batch_stats=new_stats, opt_state=opt_state,
                                  ema=ema)
        metrics = {"loss": loss, "prec1": prec1}
        if nonfinite_guard:
            # the clipped-grad norm: clipping rescales by a finite factor
            # (or NaN-propagates), so finiteness is unchanged vs raw grads
            # and the norm is reused-shape-wise from the clip when present
            gnorm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            # scalar-pred select per leaf: cheap (one fused select each)
            # and total — moments, EMA, BN stats and the step counter all
            # roll back together, leaving the state exactly pre-step
            new_state = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_state, state)
            metrics["nonfinite"] = (~ok).astype(jnp.float32)
            metrics["gnorm"] = gnorm
        return new_state, metrics

    if bn_mode == "global" or mesh is None:
        def step(state: TrainState, x, y, rng):
            loss, grads, new_stats, prec1 = forward_backward(
                state.params, state.batch_stats, x, y, rng)
            return apply_updates(state, grads, new_stats, loss, prec1)
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    # ---- local-BN shard_map over the data axis -------------------------
    from ..parallel import _compat
    from ..parallel._compat import shard_map

    def local_step(state: TrainState, x, y, rng):
        rng = jax.random.fold_in(rng, lax.axis_index(axis))
        loss, grads, new_stats, prec1 = forward_backward(
            state.params, state.batch_stats, x, y, rng, vary_axis=axis)
        # one fused cross-replica mean for grads + stats + metrics
        loss, grads, new_stats, prec1 = lax.pmean(
            (loss, grads, new_stats, prec1), axis)
        return apply_updates(state, grads, new_stats, loss, prec1)

    # The fused depthwise path embeds pallas_call in the step: the legacy
    # check_rep machinery has no replication rule for that primitive AT ALL,
    # and off-TPU the Pallas *interpreter* mixes its non-varying block
    # counters with varying refs, which even the modern vma checker rejects
    # (same reason ring_flash disables it, parallel/ring_attention.py).  On
    # compiled Mosaic under a check_vma jax the vma-typed out_shapes keep
    # the check satisfied, so it stays on there.
    check = True
    if getattr(model, "fused_depthwise", "off") == "pallas":
        legacy = "check_rep" in _compat.shard_map_check_kwargs(True)
        check = not legacy and jax.default_backend() == "tpu"
    data_spec = P(axis)
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), data_spec, data_spec, P()),
        out_specs=(P(), P()),
        **_compat.shard_map_check_kwargs(check))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step(model, loss_fn: Callable = cross_entropy,
                   use_ema: bool = False) -> Callable:
    """Build ``eval_step(state, x, y, valid) -> metrics``.

    ``valid`` masks padded duplicates from the ordered sharded sampler so
    validation is exact (the reference accepted the duplicate error,
    loader.py:794-796).  Returns {'loss', 'prec1', 'count'} where loss/prec1
    are means over valid samples in this batch (reference validate,
    train.py:703-767).
    """

    @jax.jit
    def step(state: TrainState, x, y,
             valid: Optional[jnp.ndarray] = None) -> Dict[str, jnp.ndarray]:
        variables = state.ema_variables if use_ema else state.variables
        logits = model.apply(variables, x, training=False)
        loss = loss_fn(logits, y, weight=valid)
        prec1 = accuracy(logits, y, weight=valid)
        count = (valid.sum() if valid is not None
                 else jnp.asarray(x.shape[0]))
        return {"loss": loss, "prec1": prec1, "count": count,
                "logits": logits}

    return step
