"""Frame-once crop rings: preallocated canvas rows, per-crop digests,
and zero-copy window payloads (ISSUE 20).

The streaming hot path used to copy every crop up to ``img_num/hop``
times: once into a standalone canvas at ingest, once per overlapping
window into the ``np.concatenate`` payload, and once more into the
engine's batch slab.  This module makes the frame lifecycle
**write-once, gather-once**:

* :class:`CanvasRing` — a per-track preallocated ``(capacity, H, W, 3)``
  uint8 pool.  ``prepare_canvas`` geometry is written straight into an
  acquired row at ingest (the ONE per-frame copy) and the row is
  refcounted: the windower buffer holds one reference, every in-flight
  window that still needs the bytes holds another, and the row returns
  to the freelist at zero.  Pool exhaustion (pathological scoring lag)
  degrades to counted standalone allocations — never corruption, never
  a stall.
* :func:`frame_digest` — sha256 over the canonical canvas (dtype, shape,
  bytes — the per-frame contribution of ``cache.content.content_hash``),
  computed ONCE per crop and reused by every overlapping window.
* :func:`window_key` — the window's cache identity: a domain-separated
  digest-of-digests in frame order, so keying a window costs hashing
  ``img_num * 32`` bytes instead of re-hashing megapixels.
* :class:`FrameStack` — a window payload that is never materialized:
  it presents ``shape``/``ndim``/``dtype`` like the channel-concatenated
  sample it stands for, and the engine's ``_pad_batch`` calls
  :meth:`FrameStack.write_into` to gather the frames directly into the
  batch slab — one memcpy total, after which the ring rows are released.

jax-free by construction (``lint/manifest.py`` ``JAX_FREE_MODULES``):
numpy + hashlib + threading only.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["CanvasRing", "FrameRef", "RingLease", "FrameStack",
           "frame_digest", "window_key"]

_WINDOW_KEY_DOMAIN = b"dfd.stream.window.v1"


def frame_digest(canvas: np.ndarray) -> bytes:
    """sha256 over the canonical canvas: dtype tag, shape tag, raw bytes
    (the per-frame structure of ``cache.content.content_hash``).  For a
    C-contiguous canvas the bytes are hashed via the buffer protocol —
    no copy."""
    a = canvas if canvas.flags.c_contiguous else np.ascontiguousarray(canvas)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(repr(a.shape).encode())
    h.update(a)
    return h.digest()


def window_key(digests: Sequence[bytes]) -> str:
    """Window content identity from cached per-crop digests: a domain-
    separated digest-of-digests in frame order.  Two windows share a key
    iff they hold the same canvases in the same order — the dedup
    contract ``tests`` pin against a from-scratch recomputation."""
    h = hashlib.sha256(_WINDOW_KEY_DOMAIN)
    for d in digests:
        h.update(d)
    return h.hexdigest()


class FrameRef:
    """Lifetime handle for one canvas: a refcounted pooled row, or a
    standalone array (``ring is None``) whose lifetime the GC manages —
    ``incref``/``decref`` are then no-ops."""

    __slots__ = ("ring", "row", "canvas", "digest")

    def __init__(self, canvas: np.ndarray, digest: Optional[bytes] = None,
                 ring: Optional["CanvasRing"] = None, row: int = -1):
        self.canvas = canvas
        self.digest = digest
        self.ring = ring
        self.row = row

    def incref(self) -> None:
        if self.ring is not None:
            self.ring.incref(self.row)

    def decref(self) -> None:
        if self.ring is not None:
            self.ring.decref(self.row)


class CanvasRing:
    """Preallocated pool of ``capacity`` contiguous ``(H, W, 3)`` uint8
    canvas rows with per-row refcounts.

    ``acquire`` hands out a row at refcount 1 (the windower buffer's
    reference); windows pin rows with ``incref`` and release them after
    the engine's gather.  An exhausted pool (every row pinned by
    in-flight windows) falls back to counted standalone rows rather
    than blocking ingest or recycling pinned bytes.
    """

    def __init__(self, capacity: int, size: int):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.buf = np.zeros((int(capacity), int(size), int(size), 3),
                            np.uint8)
        self._free = list(range(int(capacity) - 1, -1, -1))
        self._refs = [0] * int(capacity)
        self._lock = threading.Lock()
        self.overflow_total = 0

    @property
    def capacity(self) -> int:
        return self.buf.shape[0]

    def free_rows(self) -> int:
        with self._lock:
            return len(self._free)

    def acquire(self) -> FrameRef:
        """A writable canvas row at refcount 1.  Falls back to a counted
        standalone allocation when every pooled row is pinned."""
        with self._lock:
            if self._free:
                row = self._free.pop()
                self._refs[row] = 1
                return FrameRef(self.buf[row], None, self, row)
            self.overflow_total += 1
        size = self.buf.shape[1]
        return FrameRef(np.zeros((size, size, 3), np.uint8))

    def incref(self, row: int) -> None:
        with self._lock:
            self._refs[row] += 1

    def decref(self, row: int) -> None:
        with self._lock:
            n = self._refs[row] - 1
            self._refs[row] = n
            if n == 0:
                self._free.append(row)
            elif n < 0:                              # pragma: no cover
                # a double-release is a bug upstream; clamp so the row
                # can still recirculate instead of leaking forever
                self._refs[row] = 0


class RingLease:
    """The pins one in-flight window holds on its ring rows.  ``release``
    is idempotent — the engine's gather consumes it on the staging
    thread, and the dispatcher's terminal paths (drop/shed/fail/cache
    hit) release it for windows that never staged."""

    __slots__ = ("_refs",)
    _swap_lock = threading.Lock()

    def __init__(self, refs: Sequence[FrameRef]):
        self._refs: Optional[List[FrameRef]] = list(refs)

    def release(self) -> None:
        with RingLease._swap_lock:
            refs, self._refs = self._refs, None
        if refs:
            for r in refs:
                r.decref()


class FrameStack:
    """A window payload that is never materialized host-side.

    Presents the ``shape``/``ndim``/``dtype`` of the channel-concatenated
    sample (``(H, W, 3*img_num)``) so the micro-batcher and the engine's
    bucket grouping treat it like an ndarray, but the pixel bytes stay in
    the ring until the engine's ``_pad_batch`` calls :meth:`write_into`
    on its batch slab — the single gather-memcpy of the window's life.

    ``norm=(mean, std)`` selects the float32 wire: each frame is written
    as ``(f.astype(float32) - mean) / std``, the exact per-frame
    expression of ``params.normalize_concat`` (bit-identical scores).
    Without ``norm`` the uint8 wire ships raw channel-concat bytes.
    """

    __slots__ = ("frames", "shape", "ndim", "dtype", "_norm",
                 "_on_consumed")

    def __init__(self, frames: Sequence[np.ndarray],
                 norm: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                 on_consumed: Optional[Callable[[], None]] = None):
        if not frames:
            raise ValueError("FrameStack needs at least one frame")
        h, w = frames[0].shape[:2]
        self.frames = list(frames)
        self.shape = (h, w, 3 * len(self.frames))
        self.ndim = 3
        self._norm = norm
        self.dtype = np.dtype(np.float32) if norm is not None \
            else np.dtype(frames[0].dtype)
        self._on_consumed = on_consumed

    # ------------------------------------------------------------------
    def _gather(self, out: np.ndarray) -> None:
        norm = self._norm
        for k, f in enumerate(self.frames):
            sl = out[..., 3 * k:3 * (k + 1)]
            if norm is None:
                sl[...] = f
            else:
                mean, std = norm
                sl[...] = (f.astype(np.float32) - mean) / std

    def write_into(self, out: np.ndarray) -> None:
        """Gather the frames into ``out`` (the engine's batch-slab row)
        and release the ring pins — the payload is consumed."""
        self._gather(out)
        cb, self._on_consumed = self._on_consumed, None
        if cb is not None:
            cb()

    def materialize(self) -> np.ndarray:
        """The sample as a standalone ndarray (tests, diagnostics) —
        does NOT consume the payload or release pins."""
        out = np.empty(self.shape, self.dtype)
        self._gather(out)
        return out

    def __array__(self, dtype=None) -> np.ndarray:
        a = self.materialize()
        return a if dtype is None else a.astype(dtype, copy=False)
