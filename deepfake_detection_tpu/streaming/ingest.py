"""Chunked stream ingest: per-stream HTTP sessions → decode → track →
window → engine.

This is the front half of the streaming-video workload: long-lived
*stream sessions* that accept frame sequences in chunks and run the
face-track → temporal-window → verdict pipeline against the serving
engine already resident in the process.  Transport is deliberately plain
HTTP/1.1 on the stdlib server (the serving subsystem's discipline — no
new dependency, keep-alive for cheap chunking):

* ``POST /streams``                    → open a session (201, stream_id)
* ``POST /streams/<id>/frames``        → one chunk of frames; the body is
  - ``multipart/x-mixed-replace`` — an MJPEG chunk (parts are JPEG),
  - ``image/*`` — a single encoded frame (anything PIL/libjpeg decodes),
  - ``application/octet-stream`` — concatenated JPEGs (SOI/EOI scan),
  - ``application/x-dfd-raw`` — raw uint8 RGB frames, shape in the
    ``X-Frame-Width``/``X-Frame-Height`` headers (zero-decode path),
  - ``video/*`` — a container/elementary chunk for the **optional**
    ffmpeg demuxer adapter (soft dependency: 501 when no ffmpeg binary).
  The ack reports frames accepted, decode errors, windows emitted and
  the stream's current verdict, so a pushing client is also polling.
* ``GET /streams`` / ``GET /streams/<id>`` → listing / full status
  (tracks, verdict snapshots, recent schema-versioned events, counters).
* ``DELETE /streams/<id>``             → close, returning final status.
* ``POST /streams/<id>/migrate``       → quiesce + export the session as
  its ``dfd.streaming.session_state.v1`` snapshot (the PR 10 state-dir
  machinery) and detach it; ``POST /streams/restore`` rebuilds the
  session from such a snapshot — together the live-migration pair the
  fleet router's drain path drives (ISSUE 15).  The one reserved id:
  ``POST /streams/restore`` is this verb, not a frame push to a stream
  named "restore".
* ``GET /healthz /readyz /metrics``    → liveness / bucket-warmup
  readiness / Prometheus (serving + streaming catalogs concatenated).

Decode rides the existing native pool (``data/native.decode_jpeg_bytes``,
PIL fallback), tracking/windowing run synchronously on the handler
thread (they are µs-scale and overlap the engine thread's device calls,
exactly like serving's preprocess), and scoring goes through
:class:`~deepfake_detection_tpu.streaming.windows.WindowDispatcher`'s
bounded drop-oldest queues into the engine's fixed buckets — a stream
can stall, flood or die without recompiling, blocking or skewing anyone
else.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import queue
import re
import shutil
import subprocess
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..serving.http import multipart_boundary, split_multipart
from . import ring as ring_mod
from .metrics import StreamingMetrics
from .ring import CanvasRing, FrameStack, RingLease
from .tracker import GreedyIouTracker, crop_box, make_localizer
from .verdict import SEVERITY, VerdictMachine, VerdictThresholds
from .windows import TrackWindower, WindowDispatcher, WindowJob, build_payload

_logger = logging.getLogger(__name__)

__all__ = ["StreamSession", "StreamManager", "StreamServer",
           "multipart_boundary",
           "make_stream_server", "split_multipart", "split_jpeg_stream",
           "decode_frame_bytes", "decode_frames_batch", "FfmpegDemuxer",
           "parse_verdict_vector"]

_MAX_BODY = 64 * 1024 * 1024     # one chunk of frames, not one image
_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")
_STATUS_SCHEMA = "dfd.streaming.status.v1"
#: session-durability snapshot schema — bump on any layout change so a
#: restore can reject snapshots it does not understand instead of
#: resuming from misread state
_STATE_SCHEMA = "dfd.streaming.session_state.v1"


# ---------------------------------------------------------------------------
# chunk parsing
# ---------------------------------------------------------------------------

# re-exported from serving/http.py (the byte-level multipart parsers
# live with the serving front end; streaming depends on serving, never
# the other way)


_SOI = b"\xff\xd8"
_EOI = b"\xff\xd9"


def split_jpeg_stream(body: bytes) -> List[bytes]:
    """Concatenated-JPEG scan: every SOI..EOI span becomes one frame.

    A raw EOI byte pair cannot appear inside entropy-coded data (JPEG
    byte-stuffs 0xFF00), so marker scanning is reliable for baseline
    MJPEG payloads; frames embedding thumbnails should use multipart
    framing instead.
    """
    frames: List[bytes] = []
    pos = 0
    while True:
        start = body.find(_SOI, pos)
        if start < 0:
            break
        end = body.find(_EOI, start + 2)
        if end < 0:
            break
        frames.append(body[start:end + 2])
        pos = end + 2
    return frames


def decode_frame_bytes(data: bytes) -> Optional[np.ndarray]:
    """Encoded frame bytes → (H, W, 3) uint8, or None if undecodable.
    Native libjpeg pool first (the training input path's decoder), PIL
    for everything else."""
    from ..data import native
    arr = native.decode_jpeg_bytes(data)
    if arr is not None:
        return arr
    try:
        import io

        from PIL import Image
        img = Image.open(io.BytesIO(data))
        return np.asarray(img.convert("RGB"), np.uint8)
    except Exception:                              # noqa: BLE001 — 0-accept
        return None


_decode_pool = None
_decode_pool_lock = threading.Lock()


def _get_decode_pool():
    """Lazy shared decode fan-out pool.  ``decode_jpeg_bytes`` is a
    ctypes call into the native libjpeg pool — it releases the GIL, so
    a chunk's frames decode in parallel on a thread pool without any
    new native ABI.  Lazy so pure-host users (tests, raw-wire) never
    spawn the threads."""
    global _decode_pool
    if _decode_pool is None:
        with _decode_pool_lock:
            if _decode_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                workers = max(2, min(8, os.cpu_count() or 2))
                _decode_pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="stream-decode")
    return _decode_pool


def decode_frames_batch(encoded: List[bytes]) -> List[Optional[np.ndarray]]:
    """Decode a whole chunk's encoded frames in ONE fan-out to the
    native pool (the training ``_load_images`` idiom) instead of a
    serial per-frame loop; order is preserved, failures stay ``None``
    (counted by the caller).  Single frames skip the pool round-trip."""
    if len(encoded) < 2:
        return [decode_frame_bytes(d) for d in encoded]
    return list(_get_decode_pool().map(decode_frame_bytes, encoded))


def parse_verdict_vector(spec: str) -> List[float]:
    """Bench/test instrumentation: ``"0.05*8,0.95*12"`` → 20 planted
    per-window scores (``*N`` repeats; the last value holds forever).
    Empty spec → empty list (scores come from the model)."""
    out: List[float] = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "*" in tok:
            v, n = tok.split("*", 1)
            out.extend([float(v)] * int(n))
        else:
            out.append(float(tok))
    for v in out:
        if not 0.0 <= v <= 1.0:
            raise ValueError(f"verdict vector value {v} outside [0, 1]")
    return out


# ---------------------------------------------------------------------------
# optional ffmpeg demuxer (container formats → MJPEG frames)
# ---------------------------------------------------------------------------

class FfmpegDemuxer:
    """Container-chunk adapter: a per-session ``ffmpeg`` subprocess
    transcoding whatever lands on stdin into an MJPEG stream on stdout,
    parsed incrementally by a reader thread.

    Soft dependency: :meth:`available` gates the route — the image does
    not ship ffmpeg, and nothing else imports this class.  Latency note:
    ffmpeg buffers internally, so frames from a fed chunk may only
    surface in a later ``poll_frames`` (or at :meth:`close`); acks count
    frames when they surface.
    """

    @staticmethod
    def available(binary: str = "ffmpeg") -> bool:
        return shutil.which(binary) is not None

    def __init__(self, binary: str = "ffmpeg"):
        if not self.available(binary):
            raise RuntimeError(f"ffmpeg binary {binary!r} not found")
        self._proc = subprocess.Popen(
            [binary, "-hide_banner", "-loglevel", "error", "-i", "pipe:0",
             "-f", "image2pipe", "-c:v", "mjpeg", "-q:v", "2", "pipe:1"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        self._frames: "queue.Queue[bytes]" = queue.Queue()
        self._closing = False        # close() in progress: an exit is
        # deliberate, not a mid-stream death
        self._reader = threading.Thread(target=self._read_loop,
                                        name="ffmpeg-demux", daemon=True)
        self._reader.start()

    @property
    def dead(self) -> bool:
        """ffmpeg exited on its own (killed, codec crash, corrupt input)
        — as opposed to a deliberate :meth:`close`.  The reader thread
        sees EOF and exits cleanly, so a death can never hang it; THIS
        flag is how the ingest path surfaces the failure as a counted
        per-stream error instead of silently dropping frames."""
        return not self._closing and self._proc.poll() is not None

    def _read_loop(self) -> None:
        buf = b""
        out = self._proc.stdout
        while True:
            # read1: return whatever the pipe has (>= 1 byte) instead of
            # blocking for a full 64 KiB — frames surface as ffmpeg emits
            # them, and a death is seen at the next EOF, not 64 KiB later
            chunk = out.read1(65536)
            if not chunk:
                break
            buf += chunk
            while True:
                start = buf.find(_SOI)
                if start < 0:
                    # a SOI can straddle the read boundary: keep a
                    # trailing 0xFF so the next chunk completes it
                    buf = buf[-1:] if buf.endswith(b"\xff") else b""
                    break
                end = buf.find(_EOI, start + 2)
                if end < 0:
                    buf = buf[start:]
                    break
                self._frames.put(buf[start:end + 2])
                buf = buf[end + 2:]

    def feed(self, data: bytes) -> None:
        # a pre-write poll catches a dead process even when the kernel
        # pipe buffer would have swallowed the bytes without an EPIPE
        if self._proc.poll() is not None:
            raise OSError(f"ffmpeg exited with code "
                          f"{self._proc.returncode} mid-stream")
        try:
            self._proc.stdin.write(data)
            self._proc.stdin.flush()
        except ValueError as e:       # stdin already closed
            raise OSError(str(e)) from None

    def poll_frames(self, wait_s: float = 0.2) -> List[bytes]:
        """Drain decoded frames; waits up to ``wait_s`` for the first."""
        frames: List[bytes] = []
        deadline = time.monotonic() + wait_s
        while True:
            try:
                frames.append(self._frames.get_nowait())
            except queue.Empty:
                if frames or time.monotonic() >= deadline:
                    return frames
                time.sleep(0.01)

    def close(self) -> List[bytes]:
        """Flush: close stdin so ffmpeg drains its pipeline, then return
        any trailing frames.  Safe to call on an already-dead process —
        the reader thread exits at stdout EOF (a death can't wedge it),
        and a terminate that won't die escalates to kill."""
        self._closing = True
        try:
            self._proc.stdin.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)
        self._proc.terminate()
        try:
            self._proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:          # pragma: no cover
            self._proc.kill()
            self._proc.wait(timeout=5.0)
        frames: List[bytes] = []
        while True:
            try:
                frames.append(self._frames.get_nowait())
            except queue.Empty:
                return frames


# ---------------------------------------------------------------------------
# session
# ---------------------------------------------------------------------------

class StreamSession:
    """One live stream: tracker + windower + verdict state + counters.

    Thread model: chunk ingest runs on HTTP handler threads, score
    results arrive on the dispatcher's collector thread; ``_lock``
    serializes both (a session is sequential by nature — frames have an
    order — so per-session locking costs nothing and keeps every piece
    of state consistent)."""

    def __init__(self, stream_id: str, cfg, dispatcher: WindowDispatcher,
                 metrics: StreamingMetrics, image_size: int, wire: str,
                 event_log_path: Optional[str] = None):
        self.id = stream_id
        self.cfg = cfg
        self.dispatcher = dispatcher
        self.metrics = metrics
        self.image_size = int(image_size)
        self.wire = wire
        self.created_t = time.time()
        self.last_activity = time.monotonic()
        self._lock = threading.RLock()
        self.localizer = make_localizer(cfg.localizer)
        self.tracker = GreedyIouTracker(
            iou_min=cfg.track_iou_min, ema_alpha=cfg.track_ema_alpha,
            max_coast=cfg.track_max_coast, min_hits=cfg.track_min_hits)
        #: 'ring' (frame-once fast path: preallocated crop rings, digests,
        #: zero-copy FrameStack payloads) or 'concat' (the historical
        #: standalone-canvas + np.concatenate path, kept as the in-tree
        #: parity/bench reference)
        self._assembly = getattr(cfg, "assembly", "ring")
        self._dedup = bool(getattr(cfg, "dedup_frames", False))
        self.windower = TrackWindower(
            cfg.img_num, stride=cfg.window_stride, hop=cfg.window_hop,
            digest_frames=(self._assembly == "ring"))
        #: per-track crop rings (frame-once path).  Capacity covers the
        #: windower span plus every window the per-stream queue bound
        #: allows in flight (+ headroom for engine-staged windows);
        #: exhaustion degrades to counted standalone rows, never a stall
        self._rings: Dict[int, CanvasRing] = {}
        self._ring_capacity = 1 + self.windower.span + self.windower.hop \
            * (int(getattr(cfg, "max_inflight_windows", 4)) + 4)
        self._norm: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # consecutive-duplicate elision state (dedup_frames): encoded-byte
        # digest + decoded array of the LAST frame, the localizer's last
        # detections (deterministic on pixels, so a byte-identical frame
        # reuses them), and each track's last submitted window key
        self._last_enc_digest: Optional[bytes] = None
        self._last_frame: Optional[np.ndarray] = None
        self._last_detections: Optional[Any] = None
        self._last_window_key: Dict[int, str] = {}
        # per-track (smoothed box, pinned FrameRef) of the last canvas
        # built: a byte-identical frame whose track box is exactly
        # unchanged yields an identical crop, so the previous ring row is
        # pinned again instead of re-running resize+pad+digest
        self._crop_memo: Dict[int, Tuple[Tuple[float, ...], Any]] = {}
        self.thresholds = VerdictThresholds(
            cfg.suspect_enter, cfg.suspect_exit,
            cfg.fake_enter, cfg.fake_exit)
        self.stream_verdict = VerdictMachine(
            self.thresholds, ema_alpha=cfg.verdict_ema_alpha,
            min_windows=cfg.verdict_min_windows,
            context={"stream_id": stream_id, "scope": "stream"})
        self.track_verdicts: Dict[int, VerdictMachine] = {}
        # bounded memory of retired tracks (newest last): a dead track's
        # frozen machine must not pin the stream verdict forever, but its
        # final state is still worth surfacing
        self.dead_tracks: "collections.deque" = collections.deque(
            maxlen=32)
        self.verdict_vector = parse_verdict_vector(
            getattr(cfg, "verdict_vector", ""))
        self.events: "list[dict]" = []
        self._event_limit = 256
        self._event_log_path = event_log_path
        self._event_log = None
        self.frame_idx = 0
        self.frames_ingested = 0
        self.decode_errors = 0
        self.demux_failures = 0
        self.windows_emitted = 0
        self.windows_scored = 0
        self.windows_dropped = 0
        self.windows_shed = 0
        self.windows_failed = 0
        self.windows_cache_hit = 0       # resolved from the verdict cache
        self.windows_dup_elided = 0      # identical clip content, skipped
        self.frames_dup_elided = 0       # byte-identical frames, no decode
        self.canvas_copies_elided = 0    # redundant staging copies skipped
        self.demuxer: Optional[FfmpegDemuxer] = None
        self.closed = False
        # migration export set this: the session object may still be
        # referenced by late collector callbacks, but its state has been
        # snapshotted and shipped — nothing may mutate books or metrics
        # behind the snapshot's back
        self.detached = False

    # ------------------------------------------------------------------
    def _emit(self, events: List[dict]) -> None:
        for ev in events:
            self.events.append(ev)
            if len(self.events) > self._event_limit:
                del self.events[:len(self.events) - self._event_limit]
            self.metrics.count_transition(ev["to"])
            if self._event_log_path and not self.closed:
                try:
                    if self._event_log is None:
                        self._event_log = open(self._event_log_path, "a")
                    self._event_log.write(
                        json.dumps(ev, sort_keys=True) + "\n")
                    self._event_log.flush()
                except OSError:
                    _logger.exception(
                        "stream %s: event log unwritable; disabling the "
                        "JSONL sink (events still served via status)",
                        self.id)
                    self._event_log_path = None
                    self._event_log = None

    # ------------------------------------------------------------------
    def touch(self) -> None:
        """Refresh the idle-eviction clock.  Called per CHUNK (not only
        when frames decode) — a stream steadily pushing chunks that
        ffmpeg is still buffering, or that all fail decode, is active,
        not idle."""
        with self._lock:
            self.last_activity = time.monotonic()

    def ingest_arrays(self, frames: List[np.ndarray],
                      dup_flags: Optional[List[bool]] = None
                      ) -> Dict[str, Any]:
        """Run decoded frames through localize → track → window →
        dispatch; returns the chunk ack.

        ``dup_flags[i]`` marks frame *i* byte-identical to its
        predecessor (:meth:`decode_chunk` dedup): the localizer —
        deterministic on pixels — is then skipped and its previous
        detections reused; the tracker still runs, so EMA box state stays
        bit-identical to ingesting the duplicate normally.

        The session lock is taken PER FRAME, not across the chunk: the
        process-wide collector thread needs the same lock to fold scores,
        and a single several-hundred-frame raw chunk must not freeze
        verdict folding for every other stream while its canvases
        resize."""
        emitted = 0
        for j, frame in enumerate(frames):
            dup = bool(dup_flags[j]) if dup_flags is not None else False
            with self._lock:
                self.last_activity = time.monotonic()
                closed = self.closed
                t0 = time.monotonic()
                if dup and self._last_detections is not None:
                    detections = self._last_detections
                else:
                    detections = self.localizer.localize(frame)
                self._last_detections = detections
                born0 = self.tracker.born_total
                upd = self.tracker.update(self.frame_idx, detections)
                self.metrics.tracks_born_total.inc(
                    self.tracker.born_total - born0)
                for t in upd.died:
                    self.windower.drop_track(t.id)
                    self._rings.pop(t.id, None)
                    self._last_window_key.pop(t.id, None)
                    memo = self._crop_memo.pop(t.id, None)
                    if memo is not None:
                        memo[1].decref()
                    vm = self.track_verdicts.pop(t.id, None)
                    if vm is not None:
                        self.dead_tracks.append(
                            {"track_id": t.id, **vm.snapshot()})
                    self.metrics.tracks_died_total.inc()
                for t in upd.fresh:
                    win = self._push_crop(t, frame, dup)
                    if win is not None:
                        emitted += self._emit_window(t.id, win, closed)
                self.frame_idx += 1
                self.frames_ingested += 1
                self.metrics.frames_ingested_total.inc()
                self.metrics.latency["track"].observe(
                    time.monotonic() - t0)
        return {"frames_accepted": len(frames), "windows_emitted": emitted}

    # -- frame-once fast path (ISSUE 20) -------------------------------
    def _push_crop(self, track, frame: np.ndarray, dup: bool):
        """Track → crop → windower entry.  Ring mode runs
        ``prepare_canvas`` geometry straight into an acquired ring row
        (the frame's ONE copy) and digests it once; concat mode is the
        historical standalone-canvas path.  Under ``dedup_frames`` a
        byte-identical frame whose smoothed box is exactly unchanged
        provably yields the same canvas, so the previous row is pinned
        again (counted) instead of rebuilt."""
        track_id = track.id
        if self._assembly != "ring":
            crop = crop_box(frame, track.box, self.cfg.crop_margin)
            canvas = self._canvas(crop)
            return self.windower.push(track_id, self.frame_idx, canvas)
        memo = self._crop_memo.get(track_id) if self._dedup else None
        box = tuple(float(v) for v in track.box)
        if dup and memo is not None and memo[0] == box:
            ref = memo[1]
            ref.incref()                  # the new buffer entry's pin
            self._count_copies_elided(1)
            return self.windower.push(track_id, self.frame_idx,
                                      ref.canvas, digest=ref.digest,
                                      ref=ref)
        crop = crop_box(frame, track.box, self.cfg.crop_margin)
        ring = self._rings.get(track_id)
        if ring is None:
            ring = self._rings[track_id] = CanvasRing(
                self._ring_capacity, self.image_size)
        ref = ring.acquire()
        if ref.ring is None:              # pool exhausted: counted, safe
            self.metrics.ring_overflow_total.inc()
        self._canvas_into(ref.canvas, crop)
        ref.digest = ring_mod.frame_digest(ref.canvas)
        if self._dedup:
            ref.incref()                  # the memo slot's own pin
            if memo is not None:
                memo[1].decref()
            self._crop_memo[track_id] = (box, ref)
        return self.windower.push(track_id, self.frame_idx, ref.canvas,
                                  digest=ref.digest, ref=ref)

    def _emit_window(self, track_id: int, win, closed: bool) -> int:
        """Book one emitted window and stage it for scoring; returns 1
        when a job was dispatched (the ack's ``windows_emitted``)."""
        self.windows_emitted += 1
        self.metrics.windows_emitted_total.inc()
        if closed:
            # close-time tail (ffmpeg flush): scoring a window nobody can
            # observe would also leak a queue slot under a dead stream id
            # — count it dropped instead
            self.windows_dropped += 1
            self.metrics.windows_dropped_total.inc()
            self._release_window(win)
            return 0
        t0 = time.monotonic()
        key = None
        if win.digests is not None:
            key = ring_mod.window_key(win.digests)
            if self._dedup and key == self._last_window_key.get(track_id):
                # identical clip content as this track's previous window
                # (frozen/low-motion stream): the verdict machines already
                # consumed this exact evidence one hop ago — skip
                # submission entirely, counted, never silently
                self.windows_dup_elided += 1
                self.metrics.windows_dup_elided_total.inc()
                self._release_window(win)
                return 0
            self._last_window_key[track_id] = key
        if self._assembly == "ring":
            lease = RingLease(win.refs or [])
            payload = FrameStack(win.frames, norm=self._wire_norm(),
                                 on_consumed=lease.release)
        else:
            lease = None
            payload = build_payload(win.frames, self.wire,
                                    on_elide=self._count_copies_elided)
        content_key = (key, None) if key is not None and \
            self._cache_live() else None
        self.metrics.latency["assemble"].observe(time.monotonic() - t0)
        self.dispatcher.push(WindowJob(
            self.id, track_id, win.window_idx, win.frame_idxs, payload,
            context=self, content_key=content_key, lease=lease))
        return 1

    @staticmethod
    def _release_window(win) -> None:
        """Free the ring pins of a window that will never be dispatched
        (closed-stream tail, duplicate elision)."""
        if win.refs:
            for r in win.refs:
                r.decref()

    def _wire_norm(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Normalization constants for the float32 wire (None on uint8):
        the FrameStack gather applies the exact ``normalize_concat``
        per-frame expression while writing the batch slab."""
        if self.wire != "float32":
            return None
        if self._norm is None:
            from ..params import img_mean, img_std
            self._norm = (img_mean, img_std)
        return self._norm

    def _cache_live(self) -> bool:
        b = getattr(self.dispatcher, "batcher", None)
        return b is not None and getattr(b, "cache", None) is not None

    def _count_copies_elided(self, n: int) -> None:
        self.canvas_copies_elided += n
        self.metrics.canvas_copies_elided_total.inc(n)

    def current_verdict(self) -> str:
        """The status() verdict rule without building the whole status
        dict — the per-chunk ack path."""
        with self._lock:
            worst = self.stream_verdict.state
            for vm in self.track_verdicts.values():
                if SEVERITY[vm.state] > SEVERITY[worst]:
                    worst = vm.state
            return worst

    def _canvas(self, crop: np.ndarray) -> np.ndarray:
        """Crop → engine canvas: the CLI's exact geometric preprocess
        (aspect-preserving downfit + center pad), skipped when the crop
        already IS the canvas (the full-frame / pre-sized parity path —
        prepare_canvas is already a no-op there, this just saves work).
        The historical unconditional ``ascontiguousarray`` is elided
        (counted) for crops that are already contiguous."""
        h, w = crop.shape[:2]
        if crop.flags.c_contiguous:
            self._count_copies_elided(1)
        else:
            crop = np.ascontiguousarray(crop)
        if h == self.image_size and w == self.image_size:
            return crop
        from ..params import prepare_canvas
        return prepare_canvas(crop, self.image_size)

    def _canvas_into(self, row: np.ndarray, crop: np.ndarray) -> None:
        """``prepare_canvas`` written straight into a ring row — the
        frame's ONE copy.  Bit-identical to
        ``params.prepare_canvas(crop, image_size)``: same aspect-
        preserving BILINEAR downfit, same center zero-pad placement
        (``padding_image``'s ``(size - fitted) // 2`` top/left)."""
        h, w = crop.shape[:2]
        size = self.image_size
        if h == size and w == size:
            row[...] = crop               # pre-sized parity path: no-op fit
            return
        from ..params import resize
        if not crop.flags.c_contiguous:
            crop = np.ascontiguousarray(crop)
        fitted = resize(crop, (size, size))
        fh, fw = fitted.shape[:2]
        if fh == size and fw == size:
            row[...] = fitted
            return
        row[...] = 0
        top = (size - fh) // 2
        left = (size - fw) // 2
        row[top:top + fh, left:left + fw] = fitted

    # ------------------------------------------------------------------
    def decode_chunk(self, encoded: List[bytes]
                     ) -> Tuple[List[np.ndarray], List[bool], int]:
        """One chunk's encoded frames → (decoded arrays, per-frame dup
        flags, decode-error count), via ONE batched fan-out to the
        native decode pool.

        With ``dedup_frames`` on, a frame whose encoded bytes digest
        equals its predecessor's skips decode entirely — counted
        (``frames_dup_elided``), never silent — and reuses the previous
        decoded array; a duplicate of an undecodable frame is an error
        without burning a decode (same bytes, same failure)."""
        if not self._dedup:
            decoded = decode_frames_batch(encoded)
            arrays = [a for a in decoded if a is not None]
            errors = len(decoded) - len(arrays)
            return arrays, [False] * len(arrays), errors
        with self._lock:
            prev_digest = self._last_enc_digest
            last = self._last_frame
        digests = [hashlib.sha256(d).digest() for d in encoded]
        dup: List[bool] = []
        unique_idx: List[int] = []
        p = prev_digest
        for i, dg in enumerate(digests):
            is_dup = p is not None and dg == p
            dup.append(is_dup)
            if not is_dup:
                unique_idx.append(i)
            p = dg
        by_idx = dict(zip(unique_idx, decode_frames_batch(
            [encoded[i] for i in unique_idx])))
        arrays: List[np.ndarray] = []
        flags: List[bool] = []
        errors = elided = 0
        for i in range(len(encoded)):
            if dup[i]:
                if last is None:
                    errors += 1
                else:
                    elided += 1
                    arrays.append(last)
                    flags.append(True)
            else:
                a = by_idx[i]
                last = a
                if a is None:
                    errors += 1
                else:
                    arrays.append(a)
                    flags.append(False)
        with self._lock:
            if digests:
                self._last_enc_digest = digests[-1]
                self._last_frame = last
            self.frames_dup_elided += elided
        if elided:
            self.metrics.frames_dup_elided_total.inc(elided)
        return arrays, flags, errors

    # ------------------------------------------------------------------
    def on_window_result(self, job: WindowJob,
                         scores: Optional[np.ndarray],
                         error: Optional[BaseException]) -> None:
        """Collector-thread callback: fold one window score into the
        track + stream verdict machines."""
        with self._lock:
            if self.detached:
                # exported mid-flight: the snapshot already booked this
                # window dropped — folding it here would double-count
                return
            if error is not None:
                self.windows_failed += 1
                self.metrics.windows_failed_total.inc()
                return
            fake = float(scores[0])
            if self.verdict_vector:
                # planted score (bench/test): indexed by arrival order —
                # cache hits arrive too, so the index is hits + scored
                i = min(self.windows_scored + self.windows_cache_hit,
                        len(self.verdict_vector) - 1)
                fake = self.verdict_vector[i]
            if getattr(job, "cache_hit", False):
                # resolved from the verdict cache: a real score for this
                # clip content, folded into the verdict machines like any
                # other — but booked as a hit, not a device window
                self.windows_cache_hit += 1
                self.metrics.windows_cache_hit_total.inc()
            else:
                self.windows_scored += 1
                self.metrics.windows_scored_total.inc()
            self.metrics.latency["score"].observe(
                time.monotonic() - job.enqueue_t)
            frame_idx = job.frame_idxs[-1]
            t = self.tracker.tracks.get(job.track_id)
            vm = self.track_verdicts.get(job.track_id)
            if vm is None and t is not None:    # late result for a dead
                vm = self.track_verdicts[job.track_id] = VerdictMachine(
                    self.thresholds, ema_alpha=self.cfg.verdict_ema_alpha,
                    min_windows=self.cfg.verdict_min_windows,
                    context={"stream_id": self.id, "scope": "track",
                             "track_id": job.track_id})
            if t is not None:
                t.windows_scored += 1
            if vm is not None:
                self._emit(vm.update(fake, frame_idx=frame_idx))
            self._emit(self.stream_verdict.update(fake,
                                                  frame_idx=frame_idx))

    def on_window_drop(self, job: WindowJob, reason: str) -> None:
        with self._lock:
            if self.detached:
                return         # already booked dropped by the snapshot
            if reason == "shed":
                self.windows_shed += 1
                self.metrics.windows_shed_total.inc()
            else:
                self.windows_dropped += 1
                self.metrics.windows_dropped_total.inc()

    # ------------------------------------------------------------------
    def status(self, *, events: int = 10) -> Dict[str, Any]:
        with self._lock:
            # stream verdict: the stream-scope machine (EMA over every
            # window, de-escalates naturally) escalated by any LIVE
            # track's machine — retired tracks no longer vote
            worst = self.stream_verdict.state
            for vm in self.track_verdicts.values():
                if SEVERITY[vm.state] > SEVERITY[worst]:
                    worst = vm.state
            return {
                "schema": _STATUS_SCHEMA,
                "stream_id": self.id,
                "created": self.created_t,
                "closed": self.closed,
                "verdict": worst,
                "stream": self.stream_verdict.snapshot(),
                "tracks": {
                    str(tid): vm.snapshot()
                    for tid, vm in sorted(self.track_verdicts.items())},
                "dead_tracks": list(self.dead_tracks),
                "active_tracks": self.tracker.snapshot(),
                "counters": {
                    "frames_ingested": self.frames_ingested,
                    "decode_errors": self.decode_errors,
                    "demux_failures": self.demux_failures,
                    "windows_emitted": self.windows_emitted,
                    "windows_scored": self.windows_scored,
                    "windows_dropped": self.windows_dropped,
                    "windows_shed": self.windows_shed,
                    "windows_failed": self.windows_failed,
                    "windows_cache_hit": self.windows_cache_hit,
                    "windows_dup_elided": self.windows_dup_elided,
                    "frames_dup_elided": self.frames_dup_elided,
                    "canvas_copies_elided": self.canvas_copies_elided,
                },
                "events": self.events[-events:],
            }

    def close(self) -> Dict[str, Any]:
        with self._lock:
            self.closed = True
            demuxer, self.demuxer = self.demuxer, None
        if demuxer is not None:
            # flush + terminate ffmpeg; trailing frames are discarded —
            # their windows could only complete AFTER the final status
            # below, so decoding them would be wasted work
            demuxer.close()
        st = self.status()
        with self._lock:
            if self._event_log is not None:
                self._event_log.close()
                self._event_log = None
            self._event_log_path = None
        return st

    # ------------------------------------------------------------------
    # durability: a server bounce must RESUME this stream's verdicts, not
    # reset them (tracker + verdict machines + window-position state all
    # round-trip; the verdict event log stays ONE coherent stream)
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        with self._lock:
            # windows still in flight at snapshot time can never report
            # back into the restored session — account them dropped NOW so
            # the per-stream books (emitted == scored + dropped + shed +
            # failed + cache_hit + dup_elided) still balance after the
            # bounce
            pending = self.windows_emitted - self.windows_scored - \
                self.windows_dropped - self.windows_shed - \
                self.windows_failed - self.windows_cache_hit - \
                self.windows_dup_elided
            if pending > 0:
                self.windows_dropped += pending
                self.metrics.windows_dropped_total.inc(pending)
            return {
                "schema": _STATE_SCHEMA,
                "stream_id": self.id,
                "created": self.created_t,
                "frame_idx": self.frame_idx,
                "counters": {
                    "frames_ingested": self.frames_ingested,
                    "decode_errors": self.decode_errors,
                    "demux_failures": self.demux_failures,
                    "windows_emitted": self.windows_emitted,
                    "windows_scored": self.windows_scored,
                    "windows_dropped": self.windows_dropped,
                    "windows_shed": self.windows_shed,
                    "windows_failed": self.windows_failed,
                    "windows_cache_hit": self.windows_cache_hit,
                    "windows_dup_elided": self.windows_dup_elided,
                    "frames_dup_elided": self.frames_dup_elided,
                    "canvas_copies_elided": self.canvas_copies_elided,
                },
                "stream_verdict": self.stream_verdict.state_dict(),
                "track_verdicts": {
                    str(tid): vm.state_dict()
                    for tid, vm in sorted(self.track_verdicts.items())},
                "dead_tracks": list(self.dead_tracks),
                "events": self.events[-self._event_limit:],
                "tracker": self.tracker.state_dict(),
                "windower": self.windower.state_dict(),
            }

    def load_state(self, d: Dict[str, Any]) -> None:
        if d.get("schema") != _STATE_SCHEMA:
            raise ValueError(
                f"stream {self.id}: snapshot schema {d.get('schema')!r} "
                f"!= {_STATE_SCHEMA!r}; refusing to resume from it")
        if d.get("stream_id") != self.id:
            raise ValueError(f"snapshot is for stream "
                             f"{d.get('stream_id')!r}, not {self.id!r}")
        with self._lock:
            self.created_t = float(d["created"])
            self.frame_idx = int(d["frame_idx"])
            c = d["counters"]
            self.frames_ingested = int(c["frames_ingested"])
            self.decode_errors = int(c["decode_errors"])
            self.demux_failures = int(c.get("demux_failures", 0))
            self.windows_emitted = int(c["windows_emitted"])
            self.windows_scored = int(c["windows_scored"])
            self.windows_dropped = int(c["windows_dropped"])
            self.windows_shed = int(c["windows_shed"])
            self.windows_failed = int(c["windows_failed"])
            # pre-ISSUE-20 snapshots predate these terms (schema v1
            # layout unchanged — absent keys restore as 0)
            self.windows_cache_hit = int(c.get("windows_cache_hit", 0))
            self.windows_dup_elided = int(c.get("windows_dup_elided", 0))
            self.frames_dup_elided = int(c.get("frames_dup_elided", 0))
            self.canvas_copies_elided = int(
                c.get("canvas_copies_elided", 0))
            # duplicate-elision chains never cross a restore (the decoded
            # predecessor is gone) and restored windower entries live
            # outside the rings
            self._last_enc_digest = None
            self._last_frame = None
            self._last_detections = None
            self._last_window_key.clear()
            for _box, ref in self._crop_memo.values():
                ref.decref()
            self._crop_memo.clear()
            self._rings.clear()
            self.stream_verdict.load_state_dict(d["stream_verdict"])
            self.track_verdicts = {}
            for tid_s, vmd in d["track_verdicts"].items():
                tid = int(tid_s)
                vm = VerdictMachine(
                    self.thresholds, ema_alpha=self.cfg.verdict_ema_alpha,
                    min_windows=self.cfg.verdict_min_windows,
                    context={"stream_id": self.id, "scope": "track",
                             "track_id": tid})
                vm.load_state_dict(vmd)
                self.track_verdicts[tid] = vm
            self.dead_tracks.clear()
            self.dead_tracks.extend(d.get("dead_tracks", []))
            self.events = list(d.get("events", []))
            self.tracker.load_state_dict(d["tracker"])
            self.windower.load_state_dict(d["windower"])
            # the event log is APPENDED to across the bounce; a SIGTERM
            # can tear its last line, so reopen with the PR 6 repair
            # discipline — one coherent schema-versioned stream
            if self._event_log_path and \
                    os.path.exists(self._event_log_path):
                from ..obs.events import repair_torn_tail
                repair_torn_tail(self._event_log_path)
            self.last_activity = time.monotonic()


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------

class StreamManager:
    """Session table: create/get/close, caps, idle (TTL) eviction, and
    the fan-in point the dispatcher routes results through."""

    def __init__(self, cfg, dispatcher: WindowDispatcher,
                 metrics: StreamingMetrics, image_size: int, wire: str):
        self.cfg = cfg
        self.dispatcher = dispatcher
        self.metrics = metrics
        self.image_size = int(image_size)
        self.wire = wire
        self._sessions: Dict[str, StreamSession] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._evictor: Optional[threading.Thread] = None

    # -- dispatcher callbacks (job.context is the session) -------------
    def on_result(self, job: WindowJob, scores, error) -> None:
        session: StreamSession = job.context
        session.on_window_result(job, scores, error)

    def on_drop(self, job: WindowJob, reason: str) -> None:
        session: StreamSession = job.context
        session.on_window_drop(job, reason)

    # ------------------------------------------------------------------
    def create(self, stream_id: Optional[str] = None) -> StreamSession:
        sid = stream_id or uuid.uuid4().hex[:12]
        if not _ID_RE.match(sid):
            raise ValueError(f"invalid stream id {sid!r} "
                             f"(need {_ID_RE.pattern})")
        log_path = None
        if self.cfg.event_log_dir:
            os.makedirs(self.cfg.event_log_dir, exist_ok=True)
            log_path = os.path.join(self.cfg.event_log_dir,
                                    f"{sid}.events.jsonl")
        with self._lock:
            if sid in self._sessions:
                raise KeyError(f"stream {sid!r} already exists")
            if len(self._sessions) >= self.cfg.max_streams:
                raise OverflowError(
                    f"at max_streams={self.cfg.max_streams}")
            s = StreamSession(sid, self.cfg, self.dispatcher, self.metrics,
                              self.image_size, self.wire,
                              event_log_path=log_path)
            self._sessions[sid] = s
            self.metrics.streams_opened_total.inc()
            self.metrics.active_streams = len(self._sessions)
        return s

    def get(self, stream_id: str) -> Optional[StreamSession]:
        with self._lock:
            return self._sessions.get(stream_id)

    def close(self, stream_id: str,
              evicted: bool = False) -> Optional[Dict[str, Any]]:
        with self._lock:
            s = self._sessions.pop(stream_id, None)
            self.metrics.active_streams = len(self._sessions)
        if s is None:
            return None
        self.dispatcher.drop_stream(stream_id)
        st = s.close()
        (self.metrics.streams_evicted_total if evicted
         else self.metrics.streams_closed_total).inc()
        self.refresh_track_gauge()
        return st

    def list_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._sessions)

    def refresh_track_gauge(self) -> None:
        with self._lock:
            self.metrics.active_tracks = sum(
                len(s.tracker.tracks) for s in self._sessions.values())

    # ------------------------------------------------------------------
    # session durability: shutdown snapshot + startup restore
    # ------------------------------------------------------------------
    def save_state(self, state_dir: str) -> int:
        """Snapshot every live session into ``state_dir`` (one JSON per
        stream, write → fsync → atomic rename — the checkpoint-writer
        discipline); returns how many were saved.  Called on shutdown/
        SIGTERM so a server bounce can resume verdict streams."""
        if not state_dir:
            return 0
        os.makedirs(state_dir, exist_ok=True)
        with self._lock:
            sessions = list(self._sessions.values())
        saved = 0
        for s in sessions:
            path = os.path.join(state_dir, f"{s.id}.state.json")
            try:
                data = json.dumps(s.state_dict(), sort_keys=True)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                saved += 1
            except (OSError, ValueError, TypeError):
                self.metrics.state_errors_total.inc()
                _logger.exception("stream %s: state snapshot failed "
                                  "(stream will RESET on restart)", s.id)
        if saved:
            _logger.info("saved %d stream session snapshot(s) to %s",
                         saved, state_dir)
        return saved

    def restore_state(self, state_dir: str) -> int:
        """Resume sessions from ``state_dir`` snapshots; returns how many.

        Each snapshot is CONSUMED (unlinked) on successful restore so a
        later crash-without-snapshot cannot resurrect stale state; a
        corrupt/unreadable snapshot is renamed ``.bad`` (kept for
        forensics, never retried) and counted, loudly."""
        if not state_dir or not os.path.isdir(state_dir):
            return 0
        restored = 0
        for name in sorted(os.listdir(state_dir)):
            if not name.endswith(".state.json"):
                continue
            path = os.path.join(state_dir, name)
            try:
                with open(path) as f:
                    d = json.load(f)
                s = self.create(d.get("stream_id"))
                try:
                    s.load_state(d)
                except Exception:
                    # half-restored sessions must not serve: drop it
                    self.close(s.id)
                    raise
            except Exception:                      # noqa: BLE001
                self.metrics.state_errors_total.inc()
                _logger.exception("cannot restore stream snapshot %s; "
                                  "renaming .bad", path)
                try:
                    os.replace(path, path + ".bad")
                except OSError:
                    pass
                continue
            os.unlink(path)
            restored += 1
            self.metrics.streams_restored_total.inc()
            _logger.info("resumed stream %s (verdict %r, %d windows "
                         "scored)", s.id, s.current_verdict(),
                         s.windows_scored)
        self.refresh_track_gauge()
        return restored

    # ------------------------------------------------------------------
    # live migration (ISSUE 15): export one session as the exact
    # --state-dir snapshot + restore it on another replica.  The fleet
    # router's drain path drives these through POST /streams/<id>/migrate
    # and POST /streams/restore; restart resume (PR 10) and migration
    # ride the SAME state_dict/load_state code.
    # ------------------------------------------------------------------
    def export_session(self, stream_id: str,
                       quiesce_s: float = 5.0) -> Optional[Dict[str, Any]]:
        """Detach one live session and return its state snapshot (None =
        unknown stream).

        Quiesce discipline (the runner's shutdown order, per-session):
        the session leaves the table first (no new chunks route to it),
        its queued windows are dropped (counted), then in-flight windows
        get up to ``quiesce_s`` to fold back before the snapshot books
        any stragglers dropped — per-stream books (emitted == scored +
        dropped + shed + failed) balance across the move exactly as they
        do across a restart."""
        with self._lock:
            s = self._sessions.pop(stream_id, None)
            self.metrics.active_streams = len(self._sessions)
        if s is None:
            return None
        self.dispatcher.drop_stream(stream_id)
        deadline = time.monotonic() + max(0.0, quiesce_s)
        while time.monotonic() < deadline:
            with s._lock:
                pending = s.windows_emitted - s.windows_scored - \
                    s.windows_dropped - s.windows_shed - \
                    s.windows_failed - s.windows_cache_hit - \
                    s.windows_dup_elided
            if pending <= 0:
                break
            time.sleep(0.02)
        with s._lock:
            state = s.state_dict()     # books stragglers dropped
            s.detached = True          # late results: touch nothing
            if s._event_log is not None:
                s._event_log.close()
                s._event_log = None
            s._event_log_path = None
        if s.demuxer is not None:
            try:
                s.demuxer.close()
            except Exception:                      # noqa: BLE001
                pass
        self.metrics.streams_migrated_out_total.inc()
        self.refresh_track_gauge()
        _logger.info("exported stream %s for migration (%d windows "
                     "scored)", stream_id,
                     state["counters"]["windows_scored"])
        return state

    def import_session(self, state: Dict[str, Any]) -> StreamSession:
        """Rebuild a session from an exported snapshot (the restore half
        of a migration).  Raises like :meth:`create` (KeyError if the id
        is live here, OverflowError at the cap) or ValueError for a
        snapshot this server can't resume; a half-restored session is
        dropped, never served."""
        sid = state.get("stream_id")
        s = self.create(sid)
        try:
            s.load_state(state)
        except Exception:
            self.close(s.id)
            raise
        self.metrics.streams_migrated_in_total.inc()
        self.refresh_track_gauge()
        _logger.info("imported stream %s (verdict %r, %d windows "
                     "scored)", s.id, s.current_verdict(),
                     s.windows_scored)
        return s

    # ------------------------------------------------------------------
    def start_evictor(self) -> None:
        if self.cfg.stream_ttl_s <= 0 or self._evictor is not None:
            return
        self._evictor = threading.Thread(target=self._evict_loop,
                                         name="stream-evictor", daemon=True)
        self._evictor.start()

    def _evict_loop(self) -> None:
        period = max(0.5, self.cfg.stream_ttl_s / 4.0)
        while not self._stop.wait(period):
            now = time.monotonic()
            with self._lock:
                idle = [sid for sid, s in self._sessions.items()
                        if now - s.last_activity > self.cfg.stream_ttl_s]
            for sid in idle:
                _logger.info("evicting idle stream %s", sid)
                self.close(sid, evicted=True)

    def shutdown(self) -> None:
        self._stop.set()
        if self._evictor is not None:
            self._evictor.join(timeout=5.0)
            self._evictor = None
        for sid in self.list_ids():
            self.close(sid)


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

_STREAM_PATH = re.compile(
    r"^/streams/([A-Za-z0-9_.-]{1,64})(/frames|/migrate)?$")


class StreamServer(ThreadingHTTPServer):
    daemon_threads = True
    protocol_version = "HTTP/1.1"
    request_queue_size = 256     # the serving front end's burst-connect
    # discipline (router tier / many pushers connect at once)

    def __init__(self, addr: Tuple[str, int], manager: StreamManager,
                 engine, serving_metrics, metrics: StreamingMetrics):
        super().__init__(addr, _StreamHandler)
        self.manager = manager
        self.engine = engine
        self.serving_metrics = serving_metrics
        self.metrics = metrics


class _StreamHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True   # two-write responses vs delayed ACK
    server: StreamServer     # typing aid

    def log_message(self, fmt, *args):
        _logger.debug("%s " + fmt, self.address_string(), *args)

    # -- plumbing (the serving handler's keep-alive discipline) --------
    def _respond(self, status: int, body: bytes,
                 content_type: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, status: int, obj: dict) -> None:
        self._respond(status, json.dumps(obj).encode())

    def _read_body(self) -> Optional[bytes]:
        """Drain the request body before ANY response (keep-alive: an
        unread body would be parsed as the next request line)."""
        if self.headers.get("Transfer-Encoding"):
            self.close_connection = True
            return None
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 <= length <= _MAX_BODY:
            self.close_connection = True
            return None
        return self.rfile.read(length)

    # ------------------------------------------------------------------
    def do_GET(self) -> None:                     # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        srv = self.server
        if path == "/healthz":
            self._respond(200, b"ok\n", "text/plain")
        elif path == "/readyz":
            # the serving front end's per-model JSON detail (ISSUE 15):
            # a fleet router distinguishes "cold model warming" from
            # "engine down" off this body
            detail = srv.engine.readiness_detail()
            body = (json.dumps(detail, sort_keys=True) + "\n").encode()
            self._respond(200 if detail["ready"] else 503, body)
        elif path == "/metrics":
            text = srv.serving_metrics.render_prometheus() + \
                srv.metrics.render_prometheus()
            self._respond(200, text.encode(),
                          "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/streams":
            ids = srv.manager.list_ids()
            self._json(200, {"streams": ids, "active": len(ids)})
        else:
            m = _STREAM_PATH.match(path)
            if m and not m.group(2):
                s = srv.manager.get(m.group(1))
                if s is None:
                    self._json(404, {"error": f"no stream {m.group(1)!r}"})
                else:
                    self._json(200, s.status())
            else:
                self._json(404, {"error": f"no route {path!r}"})

    def do_DELETE(self) -> None:                  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        m = _STREAM_PATH.match(path)
        if not m or m.group(2):
            self._json(404, {"error": f"no route {path!r}"})
            return
        st = self.server.manager.close(m.group(1))
        if st is None:
            self._json(404, {"error": f"no stream {m.group(1)!r}"})
        else:
            self._json(200, st)

    # ------------------------------------------------------------------
    def do_POST(self) -> None:                    # noqa: N802 (stdlib API)
        t0 = time.monotonic()
        body = self._read_body()
        path = self.path.split("?", 1)[0]
        srv = self.server
        if path == "/streams":
            self._create_stream(body)
            return
        if path == "/streams/restore":
            # migration restore (ISSUE 15; shadows a stream literally
            # named "restore" for this one verb — documented)
            self._restore_stream(body)
            return
        m = _STREAM_PATH.match(path)
        if not m or not m.group(2):
            self._json(404, {"error": f"no route {path!r}"})
            return
        if m.group(2) == "/migrate":
            self._migrate_stream(m.group(1))
            return
        if body is None:
            self._json(400, {"error": "unreadable/oversize body"})
            return
        if not srv.engine.ready:
            self._json(503, {"error": "model warming up"})
            return
        session = srv.manager.get(m.group(1))
        if session is None:
            self._json(404, {"error": f"no stream {m.group(1)!r}"})
            return
        srv.metrics.chunks_total.inc()
        session.touch()          # a pushing stream is active even if this
        try:                     # chunk yields no decodable frames yet
            ack = self._ingest_chunk(session, body)
        except _ChunkError as e:
            self._json(e.status, {"error": str(e)})
            return
        srv.manager.refresh_track_gauge()
        dt = time.monotonic() - t0
        srv.metrics.latency["ingest"].observe(dt)
        ack.update(stream_id=session.id,
                   verdict=session.current_verdict())
        self._json(200, ack)

    def _create_stream(self, body: Optional[bytes]) -> None:
        stream_id = None
        if body is None:         # unreadable/oversize — don't burn a
            self._json(400, {"error": "unreadable/oversize body"})
            return               # max_streams slot on a malformed request
        if body:
            try:
                payload = json.loads(body)
                stream_id = payload.get("stream_id") if \
                    isinstance(payload, dict) else None
            except ValueError:
                self._json(400, {"error": "body must be JSON"})
                return
        try:
            s = self.server.manager.create(stream_id)
        except KeyError as e:
            self._json(409, {"error": str(e)})
            return
        except ValueError as e:
            self._json(400, {"error": str(e)})
            return
        except OverflowError as e:
            self._json(429, {"error": str(e)})
            return
        self._json(201, {"stream_id": s.id})

    # -- live migration (ISSUE 15) -------------------------------------
    def _migrate_stream(self, stream_id: str) -> None:
        """Export + detach one session; the body IS the snapshot the
        caller (the fleet router's drain) restores elsewhere.  The
        session is gone from this server on 200 — a lost response means
        a lost session, which is why the router's migrate path restores
        back on failure and never drops the state on the floor."""
        state = self.server.manager.export_session(stream_id)
        if state is None:
            self._json(404, {"error": f"no stream {stream_id!r}"})
            return
        self._json(200, state)

    def _restore_stream(self, body: Optional[bytes]) -> None:
        if not body:
            self._json(400, {"error": "body must be a session snapshot "
                                      "(dfd.streaming.session_state.v1)"})
            return
        try:
            state = json.loads(body)
            if not isinstance(state, dict):
                raise ValueError("snapshot must be a JSON object")
        except ValueError as e:
            self._json(400, {"error": f"unparseable snapshot: {e}"})
            return
        try:
            s = self.server.manager.import_session(state)
        except KeyError as e:
            self._json(409, {"error": str(e)})
            return
        except OverflowError as e:
            self._json(429, {"error": str(e)})
            return
        except Exception as e:                     # noqa: BLE001
            self.server.metrics.state_errors_total.inc()
            self._json(400, {"error": f"cannot resume snapshot: {e!r}"})
            return
        self._json(201, {"stream_id": s.id,
                         "verdict": s.current_verdict(),
                         "windows_scored": s.windows_scored})

    # ------------------------------------------------------------------
    def _ingest_chunk(self, session: StreamSession,
                      body: bytes) -> Dict[str, Any]:
        ctype_full = self.headers.get("Content-Type") or \
            "application/octet-stream"
        ctype = ctype_full.split(";")[0].strip().lower()
        t0 = time.monotonic()
        if ctype.startswith("multipart/"):
            boundary = multipart_boundary(ctype_full)
            if not boundary:
                raise _ChunkError(400, "multipart body without boundary")
            encoded = split_multipart(body, boundary)
        elif ctype.startswith("image/"):
            encoded = [body]
        elif ctype == "application/x-dfd-raw":
            return self._ingest_raw(session, body, t0)
        elif ctype.startswith("video/") or ctype in (
                "application/mp4", "application/x-container"):
            return self._ingest_container(session, body, t0)
        else:                        # octet-stream: concatenated JPEGs
            encoded = split_jpeg_stream(body)
        arrays, dup_flags, errors = session.decode_chunk(encoded)
        with session._lock:
            session.decode_errors += errors
        self.server.metrics.frames_decode_errors_total.inc(errors)
        self.server.metrics.latency["decode"].observe(
            time.monotonic() - t0)
        ack = session.ingest_arrays(arrays, dup_flags) if arrays else \
            {"frames_accepted": 0, "windows_emitted": 0}
        ack["decode_errors"] = errors
        return ack

    def _ingest_raw(self, session: StreamSession, body: bytes,
                    t0: float) -> Dict[str, Any]:
        try:
            w = int(self.headers["X-Frame-Width"])
            h = int(self.headers["X-Frame-Height"])
        except (KeyError, TypeError, ValueError):
            raise _ChunkError(400, "x-dfd-raw needs integer X-Frame-Width/"
                              "X-Frame-Height headers") from None
        frame_bytes = w * h * 3
        if w < 1 or h < 1 or not body or len(body) % frame_bytes:
            raise _ChunkError(400, f"body length {len(body)} is not a "
                              f"multiple of {h}x{w}x3")
        n = len(body) // frame_bytes
        arrays = list(np.frombuffer(body, np.uint8).reshape(n, h, w, 3))
        with session._lock:
            # raw frames break the encoded-byte duplicate chain: the next
            # encoded chunk's first frame is no longer "consecutive" with
            # the last encoded one
            session._last_enc_digest = None
            session._last_frame = None
        self.server.metrics.latency["decode"].observe(
            time.monotonic() - t0)
        ack = session.ingest_arrays(arrays)
        ack["decode_errors"] = 0
        return ack

    def _ingest_container(self, session: StreamSession, body: bytes,
                          t0: float) -> Dict[str, Any]:
        if not FfmpegDemuxer.available():
            raise _ChunkError(501, "container ingest needs an ffmpeg "
                              "binary on PATH (soft dependency, "
                              "not installed)")
        with session._lock:
            if session.demuxer is None:
                session.demuxer = FfmpegDemuxer()
            demuxer = session.demuxer
        try:
            demuxer.feed(body)
            encoded = demuxer.poll_frames()
            if demuxer.dead:
                # the process died AFTER accepting the bytes (kill, codec
                # crash mid-chunk): the reader saw EOF and exited, so
                # this surfaces here, counted — never as a silent stall
                raise OSError(f"ffmpeg exited with code "
                              f"{demuxer._proc.returncode} mid-stream")
        except OSError as e:
            # ffmpeg died (corrupt container, codec error, killed): count
            # it per-stream + process-wide, reset so the NEXT chunk gets
            # a fresh demuxer instead of a wedged pipe, and tell the
            # client instead of dropping the connection
            with session._lock:
                if session.demuxer is demuxer:
                    session.demuxer = None
                session.demux_failures += 1
            self.server.metrics.demux_failures_total.inc()
            try:
                demuxer.close()
            except Exception:                      # noqa: BLE001
                pass
            raise _ChunkError(
                422, f"ffmpeg demuxer failed ({e!r}); demuxer reset — "
                     f"resend from a container keyframe") from None
        arrays, dup_flags, errors = session.decode_chunk(encoded)
        with session._lock:
            session.decode_errors += errors
        self.server.metrics.frames_decode_errors_total.inc(errors)
        self.server.metrics.latency["decode"].observe(
            time.monotonic() - t0)
        ack = session.ingest_arrays(arrays, dup_flags) if arrays else \
            {"frames_accepted": 0, "windows_emitted": 0}
        ack["decode_errors"] = errors
        ack["note"] = "container frames surface as ffmpeg flushes"
        return ack


class _ChunkError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


def make_stream_server(host: str, port: int, manager: StreamManager,
                       engine, serving_metrics,
                       metrics: StreamingMetrics) -> StreamServer:
    return StreamServer((host, port), manager, engine, serving_metrics,
                        metrics)
